"""Process-mode fleet: every emulated node is its own OS process.

The one-process rig (fleet/node.py + fleet/controller.py) proved the
*logic* of chaos recovery, but a scenario ``kill`` there is a method
call: ``PyXferd.stop(crash=True)`` still runs Python teardown inside a
process that keeps living.  Production daemons do not get that
courtesy — SIGKILL runs zero lines of their code, their sockets die
with the task_struct, their mmap segment files linger on disk, and the
supervisor that respawns them is a different process with its own
bounded patience.  This module supplies that substrate:

- **worker half** (``python -m container_engine_accelerators_tpu.
  fleet.proc``): one :class:`~…fleet.node.EmulatedNode` — real
  TpuManager + health checker + PyXferd + per-node MetricServer — in
  its own process.  It reports its daemon/metrics ports to the
  coordinator over a handshake line on stdout, then serves a tiny
  newline-JSON RPC (chip faults, recovery pumps, snapshots) on
  stdin/stdout.  stdin EOF is a clean shutdown; SIGTERM dumps the
  flight recorder first (the evidence must outlive the pod); SIGKILL
  is the chaos the rest of the stack exists to survive.

- **coordinator half** (:class:`ProcNode`): the EmulatedNode-shaped
  handle the controller drives.  ``kill_daemon`` delivers a real
  ``SIGKILL`` and reaps the corpse (waitpid — no zombies);
  ``restart_daemon`` respawns under a small supervisor — RetryPolicy
  backoff on spawn attempts, a bounded per-scenario restart budget
  (``fleet.node.restarts`` counts successes; exhaustion marks the node
  permanently down instead of looping forever).  The coordinator keeps
  the production :class:`ResilientDcnXferClient` pointed at the
  worker's UDS path, so every leg of the ring workload crosses a real
  process boundary and heals through the same reconnect/replay/restage
  machinery a production caller would.

A worker that never completes its handshake is killed, reaped, and
surfaced as :class:`ProcHandshakeError` — ``cmd/fleet_sim.py`` exits
nonzero instead of hanging on it.

Link-table faults (partition/loss/latency) are a one-process feature:
the delivery fabric cannot interpose on another process's TCP stack,
so ``proc: true`` scenarios get endpoint chaos (SIGKILL, chip faults)
and direct daemon→daemon TCP; link-level chaos stays with the
in-process rig.  Telemetry aggregation flips the other way: with no
shared registry, ``fleet/telemetry.py`` scrapes each worker's
MetricServer over HTTP (per-node timeout, one retry, ``stale``
verdicts) — the aggregation path production would use.
"""

import json
import logging
import os
import queue
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import trace
from container_engine_accelerators_tpu.utils.retry import RetryPolicy

log = logging.getLogger(__name__)

SPEC_ENV = "FLEET_PROC_SPEC"
# Test hook: a worker that parks before its handshake — the
# never-completes-handshake failure cmd/fleet_sim.py must exit 2 on.
HANG_ENV = "FLEET_PROC_HANG"

DEFAULT_HANDSHAKE_TIMEOUT_S = 60.0
DEFAULT_RPC_TIMEOUT_S = 15.0
DEFAULT_RESTART_BUDGET = 3
# Teardown escalation grace per stage: stdin EOF -> SIGTERM -> SIGKILL.
CLOSE_GRACE_S = 5.0

# Supervisor respawn attempts for ONE restart_daemon call; a spec that
# cannot come up inside this budget marks the node permanently down.
RESPAWN_RETRY = RetryPolicy(
    max_attempts=3, initial_backoff_s=0.2, max_backoff_s=1.0,
    deadline_s=30.0,
)

_PKG_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


class ProcHandshakeError(RuntimeError):
    """A node worker never reported ready (spawn failed, import crash,
    or a hang) — the coordinator killed and reaped it."""


# ---------------------------------------------------------------------------
# coordinator half
# ---------------------------------------------------------------------------


class _DaemonHandle:
    """What the controller needs of a remote daemon: where to send
    (the handshake-reported data port) and which incarnation is
    serving (cumulative across respawns, like the in-process
    ``PyXferd.generation``)."""

    def __init__(self):
        self.data_port = 0
        self.generation = 0


class ProcNode:
    """Coordinator-side handle for one node worker process.

    Interface-compatible with :class:`~…fleet.node.EmulatedNode` where
    the controller touches it: ``client`` / ``daemon.data_port`` for
    the workload legs, ``down`` / ``snapshot`` / ``all_healthy`` for
    the report, ``inject_chip_fault`` / ``force_recover`` / ``recover``
    for the fault schedule — except that here each of those crosses a
    real process boundary.
    """

    def __init__(self, spec, root: str,
                 env: Optional[dict] = None,
                 handshake_timeout_s: float = DEFAULT_HANDSHAKE_TIMEOUT_S,
                 restart_budget: int = DEFAULT_RESTART_BUDGET,
                 respawn_retry: Optional[RetryPolicy] = None,
                 metrics_interval_s: float = 0.25,
                 client_retry: Optional[RetryPolicy] = None,
                 stderr=None):
        self.spec = spec
        self.name = spec.name
        self.root = root
        self.down = True  # until the first handshake lands
        self.permanently_down = False
        self.restarts = 0
        self.restart_budget = int(restart_budget)
        self.handshake_timeout_s = float(handshake_timeout_s)
        self.metrics_interval_s = float(metrics_interval_s)
        self.respawn_retry = respawn_retry or RESPAWN_RETRY
        self.metrics_port = 0
        self.shm_dir = os.path.join(root, "tpu-dcn", "shm")
        self.pid: Optional[int] = None
        self.daemon = _DaemonHandle()
        self.proc: Optional[subprocess.Popen] = None
        self._base_env = dict(os.environ if env is None else env)
        self._stderr = stderr
        self._q: "queue.Queue" = queue.Queue()
        self._rpc_lock = threading.Lock()
        self._rpc_id = 0
        self._spawns = 0
        self._last_snapshot: Dict[str, object] = {
            "rack": spec.rack, "devices": {}, "healthy": 0, "total": 0,
        }
        self._spawn()
        # The production client, pointed across the process boundary:
        # the worker's daemon binds the same UDS path on every respawn,
        # so reconnect + flow-table replay heal a SIGKILL transparently.
        from container_engine_accelerators_tpu.parallel.dcn_client import (
            ResilientDcnXferClient,
        )
        from container_engine_accelerators_tpu.fleet.node import (
            FLEET_CLIENT_RETRY,
        )

        self.client = ResilientDcnXferClient(
            os.path.join(root, "tpu-dcn"),
            retry=client_retry or FLEET_CLIENT_RETRY,
        )

    # -- spawn / handshake ---------------------------------------------------

    def _spawn(self, extra_env: Optional[dict] = None) -> None:
        blob = {
            "name": self.spec.name,
            "rack": self.spec.rack,
            "chips": self.spec.chips,
            "topology": self.spec.topology,
            "partition_size": self.spec.partition_size,
            "slice_id": self.spec.slice_id,
            "root": self.root,
            "metrics_interval_s": self.metrics_interval_s,
        }
        env = dict(self._base_env)
        # Respawns inherit the coordinator's CURRENT trace context so a
        # mid-scenario restart joins the scenario's trace.
        ctx = trace.context_env()
        if ctx:
            env[trace.TRACE_CONTEXT_ENV] = ctx
        env[SPEC_ENV] = json.dumps(blob)
        env["PYTHONUNBUFFERED"] = "1"
        env["PYTHONPATH"] = _PKG_ROOT + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        if extra_env:
            env.update(extra_env)
        # -c instead of -m: the package __init__ imports this module,
        # and runpy warns when the -m target is already in sys.modules.
        proc = subprocess.Popen(
            [sys.executable, "-c",
             "from container_engine_accelerators_tpu.fleet.proc "
             "import worker_main; raise SystemExit(worker_main())"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self._stderr, env=env, cwd=_PKG_ROOT, text=True,
        )
        q: "queue.Queue" = queue.Queue()
        threading.Thread(target=self._pump_stdout, args=(proc, q),
                         name=f"fleet-proc-read-{self.name}",
                         daemon=True).start()
        deadline = time.monotonic() + self.handshake_timeout_s
        ready = None
        while ready is None:
            try:
                line = q.get(timeout=max(0.0,
                                         deadline - time.monotonic()))
            except queue.Empty:
                line = False
            if line in (None, False):  # EOF (died) or timeout (hung)
                self._reap(proc, force=True)
                why = ("worker died before its handshake"
                       if line is None else
                       f"no handshake within {self.handshake_timeout_s:g}s")
                raise ProcHandshakeError(
                    f"node {self.name}: {why} "
                    f"(pid {proc.pid}, rc {proc.returncode})"
                )
            try:
                msg = json.loads(line)
            except ValueError:
                continue  # stray output on stdout; keep waiting
            if not isinstance(msg, dict):
                continue  # a bare JSON scalar is stray output too
            if msg.get("event") == "ready":
                ready = msg
        self.proc = proc
        self._q = q
        self.pid = int(ready["pid"])
        self._spawns += 1
        self.daemon.data_port = int(ready["daemon_port"])
        self.daemon.generation = self._spawns
        self.metrics_port = int(ready["metrics_port"])
        self.down = False
        log.info("node %s up: pid %d, daemon :%d, metrics :%d (spawn %d)",
                 self.name, self.pid, self.daemon.data_port,
                 self.metrics_port, self._spawns)
        # Prime the cached snapshot: a node SIGKILLed before any
        # report query must still show its last known devices.
        self.snapshot()

    @staticmethod
    def _pump_stdout(proc: subprocess.Popen, q: "queue.Queue") -> None:
        try:
            for line in proc.stdout:
                q.put(line)
        except (OSError, ValueError):
            pass
        finally:
            q.put(None)  # EOF sentinel: the worker is gone

    def _reap(self, proc: Optional[subprocess.Popen],
              force: bool = False) -> None:
        """waitpid the child — every exit path runs through here, so a
        scenario can never leave a zombie (or worse, a live orphan
        still bound to the node's ports)."""
        if proc is None:
            return
        if proc.poll() is None and force:
            try:
                proc.kill()
            except OSError:
                pass
        try:
            proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:  # pragma: no cover — SIGKILL'd
            log.error("node %s pid %d did not exit after SIGKILL",
                      self.name, proc.pid)
        for f in (proc.stdin, proc.stdout):
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass

    # -- RPC -----------------------------------------------------------------

    def _rpc(self, op: str, timeout_s: float = DEFAULT_RPC_TIMEOUT_S,
             **kw) -> dict:
        with self._rpc_lock:
            proc = self.proc
            if self.down or proc is None or proc.poll() is not None:
                raise OSError(f"node {self.name} worker is down")
            self._rpc_id += 1
            req = dict(kw, op=op, id=self._rpc_id)
            try:
                proc.stdin.write(json.dumps(req) + "\n")
                proc.stdin.flush()
            except (OSError, ValueError) as e:
                raise OSError(
                    f"node {self.name} RPC write failed: {e}") from e
            deadline = time.monotonic() + timeout_s
            while True:
                try:
                    line = self._q.get(
                        timeout=max(0.0, deadline - time.monotonic()))
                except queue.Empty:
                    raise OSError(
                        f"node {self.name} RPC {op!r} timed out "
                        f"after {timeout_s:g}s")
                if line is None:
                    raise OSError(
                        f"node {self.name} worker died mid-RPC {op!r}")
                try:
                    resp = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(resp, dict):
                    continue  # stray stdout that happens to be JSON
                if resp.get("id") != self._rpc_id:
                    continue  # a previous timed-out op's late answer
                if not resp.get("ok"):
                    raise OSError(
                        f"node {self.name} RPC {op!r} failed: "
                        f"{resp.get('error')}")
                return resp

    # -- health / fault surface (RPC-backed) ---------------------------------

    def inject_chip_fault(self, chip: str, code: int = 48) -> None:
        trace.event("fleet.chip_fault", node=self.name, chip=chip,
                    code=code)
        self._rpc("chip_fault", chip=chip, code=code)

    def force_recover(self) -> int:
        return int(self._rpc("chip_recover").get("recovered", 0))

    def recover(self, now: Optional[float] = None) -> int:
        if self.down:
            return 0
        try:
            return int(self._rpc("recover").get("recovered", 0))
        except OSError:
            return 0

    def pump_health(self) -> int:
        return int(self._rpc("pump_health").get("pumped", 0))

    def drop_response_once(self, op: str, times: int = 1) -> None:
        """Arm the worker daemon's lost-response hook (chaos tests)."""
        self._rpc("drop_response", dop=op, times=times)

    def apply_link_fault(self, port: int, action: str,
                         param: float = 0.0,
                         host: str = "127.0.0.1") -> int:
        """Arm the worker daemon's outbound link shim toward a peer's
        data port — the proc-mode stand-in for the in-process link
        table (netem-like partition/latency/drop in PyXferd's send
        path, driven over this RPC)."""
        return int(self._rpc("link_fault", port=int(port),
                             action=action, param=float(param),
                             host=host).get("applied", 0))

    def ring_delay(self, seconds: float) -> float:
        """Arm the worker daemon's slow-ring-completer grey fault:
        every posted descriptor costs ``seconds`` before the completer
        drives it — slow, not dead (the cursor keeps crawling).  0
        disarms."""
        return float(self._rpc("ring_delay",
                               seconds=float(seconds)).get(
                                   "delay_s", 0.0))

    def shm_delay(self, seconds: float) -> float:
        """Arm the worker daemon's slow-shm-commit grey fault: every
        shm commit pays ``seconds`` before landing — a throttled
        staging memcpy, slow, not dead (commits still land and
        account).  0 disarms."""
        return float(self._rpc("shm_delay",
                               seconds=float(seconds)).get(
                                   "delay_s", 0.0))

    def resources(self) -> Dict[str, int]:
        """The worker's resource census (fds / threads / shm segments
        / rss) for the soak leak sentinel.  Raises OSError on a dark
        worker — unlike :meth:`snapshot` there is no cached fallback,
        because a stale census would fake a flat (leak-free) series
        for exactly as long as the worker is unobservable."""
        return dict(self._rpc("resources").get("resources", {}))

    def burn_cpu(self, seconds: float = 1.0) -> float:
        """Arm the grey-failure CPU burn: the worker spins a core for
        ``seconds`` (capped worker-side) — slow, not dead."""
        return float(self._rpc("burn",
                               seconds=float(seconds)).get(
                                   "burning_s", 0.0))

    def stop_burn(self) -> None:
        """Disarm any in-flight CPU burn (the grey fault's heal)."""
        self._rpc("burn_stop")

    def device_health(self) -> Dict[str, str]:
        return dict(self.snapshot().get("devices", {}))

    def all_healthy(self) -> bool:
        snap = self.snapshot()
        return (snap.get("total", 0) > 0
                and snap.get("healthy") == snap.get("total"))

    # -- daemon churn (real signals) -----------------------------------------

    def kill_daemon(self) -> None:
        """SIGKILL the node worker: no teardown runs — sockets die
        with the process, shm segment files linger until the next
        incarnation wipes them.  The corpse is reaped immediately."""
        trace.event("fleet.node_kill", node=self.name, pid=self.pid,
                    signal="SIGKILL")
        self.down = True
        proc = self.proc
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except OSError:
                pass
        self._reap(proc)
        self.proc = None

    def restart_daemon(self, extra_env: Optional[dict] = None) -> bool:
        """Supervised respawn: RetryPolicy backoff across spawn
        attempts, a bounded per-scenario restart budget.  Exhausting
        either marks the node permanently down — the scenario then
        reports non-converged instead of the supervisor spinning.
        Returns whether a respawn actually happened, so the round log
        can record a refused restart as skipped, not applied."""
        if self.permanently_down:
            log.error("node %s is permanently down; not restarting",
                      self.name)
            return False
        if self.restarts >= self.restart_budget:
            self.permanently_down = True
            counters.inc("fleet.node.budget_exhausted")
            log.error(
                "node %s restart budget (%d) exhausted; marking "
                "permanently down", self.name, self.restart_budget)
            return False
        # A restart on a LIVE node (rolling-restart schedules) must
        # not leak the old worker: kill and reap it before spawning
        # its replacement — the respawn rebinding the same UDS path
        # and node root depends on the old incarnation being gone.
        old = self.proc
        if old is not None and old.poll() is None:
            self.down = True
            self._reap(old, force=True)
            self.proc = None
        trace.event("fleet.node_restart", node=self.name)
        last: Optional[BaseException] = None
        for _attempt in self.respawn_retry.attempts():
            try:
                self._spawn(extra_env=extra_env)
                break
            except ProcHandshakeError as e:
                last = e
        else:
            self.permanently_down = True
            counters.inc("fleet.node.budget_exhausted")
            log.error("node %s could not be respawned (%s); marking "
                      "permanently down", self.name, last)
            return False
        self.restarts += 1
        counters.inc("fleet.node.restarts")
        return True

    # -- reporting -----------------------------------------------------------

    def snapshot(self) -> dict:
        if not self.down:
            try:
                fresh = self._rpc("snapshot")["snapshot"]
                self._last_snapshot = {
                    k: fresh[k]
                    for k in ("rack", "devices", "healthy", "total")
                    if k in fresh
                }
            except OSError as e:
                log.warning("node %s snapshot RPC failed: %s",
                            self.name, e)
        snap = dict(self._last_snapshot)
        snap.update(
            daemon_generation=self._spawns,
            down=self.down,
            restarts=self.restarts,
            permanently_down=self.permanently_down,
            proc=True,
            pid=self.pid,
            metrics_port=self.metrics_port,
        )
        return snap

    def close(self) -> None:
        """Teardown escalation: stdin EOF (clean exit) → SIGTERM
        (flight-recorder dump, then exit) → SIGKILL.  Always reaps."""
        try:
            self.client.close()
        except OSError:
            pass
        proc = self.proc
        self.proc = None
        self.down = True
        if proc is None:
            return
        if proc.poll() is None:
            try:
                proc.stdin.close()
            except OSError:
                pass
            try:
                proc.wait(timeout=CLOSE_GRACE_S)
            except subprocess.TimeoutExpired:
                try:
                    proc.terminate()  # SIGTERM: dump flight, then die
                except OSError:
                    pass
                try:
                    proc.wait(timeout=CLOSE_GRACE_S)
                except subprocess.TimeoutExpired:
                    log.error("node %s pid %d survived SIGTERM; "
                              "killing", self.name, proc.pid)
        self._reap(proc, force=True)


# ---------------------------------------------------------------------------
# worker half
# ---------------------------------------------------------------------------


def _emit(out, obj: dict) -> None:
    out.write(json.dumps(obj) + "\n")
    out.flush()


def _resource_snapshot(shm_dir: Optional[str] = None) -> dict:
    """Process-local resource census for the soak leak sentinel: open
    fds (``/proc/self/fd``), OS thread count, shm segment files under
    the daemon's segment dir, and resident set size.  Every probe
    degrades to 0 instead of raising — a worker mid-teardown must
    still answer its supervisor."""
    snap = {"fds": 0, "threads": 0, "shm_segments": 0, "rss_bytes": 0}
    try:
        snap["fds"] = len(os.listdir("/proc/self/fd"))
    except OSError:
        pass
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("Threads:"):
                    snap["threads"] = int(line.split()[1])
                    break
    except (OSError, ValueError, IndexError):
        snap["threads"] = threading.active_count()
    if not snap["threads"]:
        snap["threads"] = threading.active_count()
    if shm_dir:
        try:
            snap["shm_segments"] = len(os.listdir(shm_dir))
        except OSError:
            pass
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        snap["rss_bytes"] = pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    return snap


# Grey-failure CPU burn: a daemon thread spinning until its deadline
# or until ``_stop_burn`` bumps the epoch — the "slow, not dead" half
# of the soak world's grey fault (the other half is outbound link
# latency via the PyXferd shim).  Bounded so a lost ``burn_stop`` can
# never wedge a worker past the fault window it was armed for.
MAX_BURN_S = 30.0
_burn_lock = threading.Lock()
_burn_epoch = 0


def _start_burn(seconds: float) -> float:
    seconds = max(0.0, min(float(seconds), MAX_BURN_S))
    with _burn_lock:
        epoch = _burn_epoch

    def _spin():
        deadline = time.monotonic() + seconds
        x = 1
        while time.monotonic() < deadline:
            with _burn_lock:
                if _burn_epoch != epoch:
                    return
            for _ in range(20000):
                x = (x * 1103515245 + 12345) & 0x7FFFFFFF

    threading.Thread(target=_spin, name="grey-burn",
                     daemon=True).start()
    return seconds


def _stop_burn() -> int:
    global _burn_epoch
    with _burn_lock:
        _burn_epoch += 1
        return _burn_epoch


def _serve(node, out) -> None:
    """The worker's RPC loop: newline-JSON requests on stdin, one
    response line each on stdout.  EOF means the coordinator is gone
    (or closing us cleanly) — either way, stop serving."""
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
        except ValueError:
            continue
        if not isinstance(req, dict):
            continue  # a scalar line is noise, not a request
        op = req.get("op")
        resp = {"id": req.get("id"), "ok": True}
        try:
            if op == "ping":
                pass
            elif op == "snapshot":
                resp["snapshot"] = node.snapshot()
            elif op == "chip_fault":
                node.inject_chip_fault(req.get("chip", "accel0"),
                                       int(req.get("code", 48)))
            elif op == "chip_recover":
                resp["recovered"] = node.force_recover()
            elif op == "recover":
                resp["recovered"] = node.recover()
            elif op == "pump_health":
                resp["pumped"] = node.pump_health()
            elif op == "drop_response":
                node.daemon.drop_response_once(
                    req["dop"], int(req.get("times", 1)))
            elif op == "link_fault":
                resp["applied"] = node.daemon.set_link_fault(
                    req.get("host", "127.0.0.1"), int(req["port"]),
                    req.get("action", ""),
                    float(req.get("param", 0.0)))
            elif op == "ring_delay":
                resp["delay_s"] = node.daemon.set_ring_delay(
                    float(req.get("seconds", 0.0)))
            elif op == "shm_delay":
                resp["delay_s"] = node.daemon.set_shm_delay(
                    float(req.get("seconds", 0.0)))
            elif op == "resources":
                resp["resources"] = _resource_snapshot(
                    getattr(node.daemon, "shm_dir", None))
            elif op == "burn":
                resp["burning_s"] = _start_burn(
                    float(req.get("seconds", 1.0)))
            elif op == "burn_stop":
                resp["epoch"] = _stop_burn()
            elif op == "shutdown":
                _emit(out, resp)
                return
            else:
                resp = {"id": req.get("id"), "ok": False,
                        "error": f"unknown op: {op!r}"}
        except Exception as e:  # noqa: BLE001 — RPC errors must answer
            resp = {"id": req.get("id"), "ok": False, "error": str(e)}
        _emit(out, resp)


def worker_main() -> int:
    """Entry point for one node worker process."""
    from container_engine_accelerators_tpu.fleet.node import EmulatedNode
    from container_engine_accelerators_tpu.fleet.topology import NodeSpec
    from container_engine_accelerators_tpu.obs import flight, profiler

    if os.environ.get(HANG_ENV):
        time.sleep(3600)  # test hook: a worker that never handshakes
    blob = json.loads(os.environ[SPEC_ENV])
    # The pod-resources socket does not exist in the sim; at the fast
    # proc-mode collection interval its absence would be a warning
    # flood, so absorb it below warning level.
    logging.getLogger(
        "container_engine_accelerators_tpu.metrics.metrics"
    ).setLevel(logging.ERROR)

    def _sigterm(signum, frame):
        # The supervisor's pre-kill courtesy signal: dump what this
        # node was DOING before the evidence dies with the process.
        flight.dump("signal 15 (SIGTERM): fleet supervisor teardown")
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, _sigterm)
    flight.install()  # SIGUSR1 on-demand dumps, as on a real agent
    # Always-on continuous profiler at the low default rate: the
    # worker's /profile endpoint (scraped by the fleet aggregator) and
    # the flight dumps above both read it.  TPU_PROF=0 disables.
    profiler.start()
    with trace.attach_from_env():
        spec = NodeSpec(
            name=blob["name"], rack=blob.get("rack", "r0"),
            chips=int(blob.get("chips", 4)),
            topology=blob.get("topology", "2x2x1"),
            partition_size=blob.get("partition_size", ""),
            slice_id=blob.get("slice_id"),
        )
        node = EmulatedNode(
            spec, blob["root"], net=None, metrics=True,
            metrics_interval_s=float(blob.get("metrics_interval_s",
                                              0.25)),
        )
        try:
            with trace.span("fleet.proc_node", node=spec.name,
                            pid=os.getpid()):
                _emit(sys.stdout, {
                    "event": "ready",
                    "pid": os.getpid(),
                    "node": spec.name,
                    "daemon_port": node.daemon.data_port,
                    "metrics_port": node.metrics.port,
                    "generation": node.daemon.generation,
                })
                _serve(node, sys.stdout)
        finally:
            node.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(worker_main())
