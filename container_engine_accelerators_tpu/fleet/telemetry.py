"""Fleet telemetry: per-round scrapes + declarative SLO verdicts.

The fleet report (fleet/controller.py) already says whether a scenario
*converged* — every surviving node's final legs completed.  That is a
liveness verdict, and liveness is a low bar: a fleet that re-sends
every chunk three times through a lossy link still converges while
delivering a third of the bandwidth anyone provisioned for.  This
module adds the quality verdict:

- **scrape**: each round the aggregator reads every emulated node's
  telemetry into a round-indexed history.  In the one-process rig the
  series registry IS the fleet's (windowed goodput per ``{node,
  link}`` from obs/timeseries.py, keyed by the ``goodput.node.<n>`` /
  ``goodput.link.<a>-><b>`` naming convention, plus each daemon's flow
  accounting).  In **process mode** (``proc: true`` scenarios) there
  is no shared registry: the aggregator scrapes each node worker's
  MetricServer over HTTP — per-node timeout, one retry, and a
  ``stale: true`` verdict on the round entry when a node cannot be
  scraped (down, killed, or just slow), so one dead node degrades the
  report instead of hanging the round;

- **SLOs**: the scenario spec's ``slo:`` mapping declares ceilings and
  floors, evaluated over the whole run::

      slo:
        p99_leg_ms: 250            # ceiling: p99 of fleet.leg latency
        min_goodput_bps: 4096      # floor: delivered link bytes/s
        max_retransmit_ratio: 0.5  # cap: (drops + dups) / frames
        max_dedup_ratio: 0.25      # cap: dups / frames

  Unknown keys are logged and skipped — the TPU_FAULT_SPEC rule: a
  typo'd scenario must degrade, not crash the rig.  Each check also
  lands in the gauge registry as ``slo.<key>.ok`` / ``slo.<key>.value``
  so the MetricServer scrape (``agent_gauge``), ``cmd/agent_top.py``,
  and the flight recorder all show SLO state live.

  In scrape mode the measurements come from the HTTP history instead
  of the link table (process workers see no link fabric): the goodput
  floor is judged over the per-round scraped ``goodput.node.*`` sums
  with **stale windows skipped** (a round where a node was down must
  not count as zero goodput against the floor — the kill is the
  scenario's point), and the retransmit/dedup ratios come from each
  worker's scraped ``dcn.frames.deduped`` / ``xferd.frames.landed``
  counters, accumulated restart-aware (a respawned worker's counters
  restart at zero; the aggregator sums increments, not raw values).

- **spans**: each round the aggregator also pages the span evidence
  the report's ``critical_path`` section (obs/critpath.py) is built
  from — the coordinator's own ring by cursor (``trace.tail_since``;
  the transfer clients and the serving frontend live coordinator-side
  in both modes), plus, in scrape mode, every worker's ``/spans``
  endpoint under the same timeout/stale discipline as the metric
  scrape (``fleet.scrape.spans_stale``).  Collection is bounded
  (``MAX_COLLECTED_SPANS``); overflow drops oldest and is counted,
  never hidden.

- **profile**: each round the aggregator also pages every worker's
  ``/profile`` endpoint (the continuous profiler, obs/profiler.py)
  under the same timeout/one-retry/stale rules
  (``fleet.scrape.profile_stale``), merging cumulative folded-stack
  counts restart-aware — a respawned worker's samples restart at
  zero, so the merge sums increments keyed by incarnation, exactly
  like the counter accumulator.  :meth:`FleetTelemetry.profile_report`
  renders the merged result (per-node and fleet-wide top-N stacks +
  subsystem rollups) into the report's ``profile`` section.

The controller folds :meth:`FleetTelemetry.evaluate`'s result into the
report's ``slo`` section and ``cmd/fleet_sim.py`` exits non-zero on
breach — a fleet that converges while violating its goodput floor
fails CI, not just a dashboard.
"""

import json
import logging
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Set, Tuple

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import (
    anomaly,
    histo,
    profiler,
    promtext,
    timeseries,
    trace,
)

log = logging.getLogger(__name__)

# Per-node HTTP scrape budget (scrape mode): one attempt + one retry,
# each under this timeout — a dead node costs the round at most
# 2 * timeout and a `stale` entry, never a hang.
DEFAULT_SCRAPE_TIMEOUT_S = 1.0

# Span-collection bounds: per-GET page size against each worker's
# /spans endpoint, and the retained fleet-wide span cap (oldest spans
# drop first; the count dropped is reported, never hidden).
SPANS_SCRAPE_LIMIT = 2048
MAX_COLLECTED_SPANS = 20000

# Profile scrape page size: the worker registry is LRU-bounded at
# profiler.MAX_STACKS, so one page at this limit is always complete.
PROFILE_SCRAPE_LIMIT = profiler.SCRAPE_MAX_LIMIT
# Top-N folded stacks the report's profile section keeps per node and
# fleet-wide (agent_prof renders more detail from a live scrape).
PROFILE_REPORT_TOP_N = 20

# SLO key -> (kind, description).  Ceilings fail when value > limit,
# floors when value < limit.
SLO_KEYS = {
    "p99_leg_ms": ("ceiling", "p99 of fleet.leg latency (ms)"),
    "min_goodput_bps": ("floor", "delivered link bytes per second"),
    # The recovery floor (the self-tuning data plane's acceptance
    # gate): goodput over the LAST sampled round with live telemetry —
    # a scenario that degrades a link mid-run and heals it passes only
    # if the fleet is back above this floor by the end, with no
    # operator knob change.  Judged from the same per-round node
    # goodput history in both fleet modes; stale entries are skipped
    # exactly like the whole-run floor.
    "min_final_goodput_bps": ("floor",
                              "delivered bytes per second over the "
                              "final sampled round (post-heal "
                              "recovery floor)"),
    "max_retransmit_ratio": ("ceiling",
                             "(link drops + deduped replays) / frames"),
    "max_dedup_ratio": ("ceiling", "deduped replays / frames"),
    # Serving SLOs (workload: serving — serving/frontend.py).  The
    # frontend lives in the COORDINATOR process in both fleet modes,
    # so these are judged from this process's registries either way;
    # only the byte-level goodput/ratio inputs flip to HTTP scrapes.
    "p99_e2e_ms": ("ceiling",
                   "p99 of serving end-to-end request latency (ms)"),
    "min_qps": ("floor", "completed (ok) serving requests per second"),
    "max_error_ratio": ("ceiling",
                        "errored serving requests / terminated"),
    # Collective SLOs (workload: collective — collectives/runner.py).
    # The engine runs in the COORDINATOR process in both fleet modes,
    # so these are judged from the controller-fed round history either
    # way: bus bandwidth follows bench.py's nccl-tests conventions
    # (busbw = algbw * bus_factor(op, n)), and only rounds that
    # completed AND verified count — a failed round contributes no
    # bandwidth rather than a flattering zero-time sample.
    "min_busbw_bps": ("floor",
                      "mean collective bus bandwidth over completed "
                      "rounds (bytes/s)"),
    "min_final_busbw_bps": ("floor",
                            "bus bandwidth of the final completed "
                            "collective round (bytes/s) — the "
                            "post-heal recovery floor"),
    # Routed-mode lane accounting (collective: {routed: true}): the
    # forwarding-plane proof as SLOs.  The floor demands the daemons
    # actually moved payload daemon->daemon; the ceiling (0 in the
    # pinned scenarios) is the pure-control-plane claim — any leg
    # payload crossing a coordinator client (a downgraded leg) is
    # counted against it.
    "min_forward_bytes": ("floor",
                          "daemon-forwarded payload bytes over "
                          "completed routed collective rounds"),
    "max_coordinator_leg_bytes": ("ceiling",
                                  "routed-leg payload bytes that "
                                  "crossed coordinator clients "
                                  "(downgraded legs; 0 = pure "
                                  "control plane)"),
    # Exposed-communication ceiling (obs/critpath.py): DCN time not
    # hidden behind staging, over the run's pipelined transfers.  The
    # inputs (`dcn.exposed` / `dcn.comm` histogram sums) are recorded
    # by the transfer CLIENTS, which live in the coordinator process
    # in BOTH fleet modes — so this is judged coordinator-side, no
    # scrape needed.  A run with no pipelined transfers measures 0.0
    # (vacuously inside any ceiling).
    "max_exposed_comm_ratio": ("ceiling",
                               "exposed DCN time / total DCN time "
                               "(pipelined transfers, this run)"),
    # Grey-failure detection latency (obs/anomaly.py): worst
    # windows-from-onset over the run's seeded grey faults, judged
    # closed-loop against the soak schedule's ground truth.  A run
    # with no seeded grey truth measures 0.0 (vacuous); a seeded grey
    # window the detector never flagged measures the whole run length
    # — honestly past any sane ceiling.
    "max_grey_detection_windows": ("ceiling",
                                   "worst windows-to-flag over the "
                                   "seeded grey faults (0 = none "
                                   "seeded; a miss measures the run "
                                   "length)"),
}

# Windows an idle node's last histogram p99 stands in as peer
# baseline before aging out (see _anom_hold_fill).
ANOMALY_HOLD_WINDOWS = 3

# The per-node attribution histograms the anomaly detector compares
# across peers, scraped as cumulative agent_latency{op,bucket} families
# and deltaed per window: the ring completer's per-descriptor drive and
# the shm lane's per-frame commit — one op per grey-fault modality.
ANOMALY_HISTO_OPS = ("xferd.ring.drive", "xferd.shm.commit")

# The latency histogram the p99 ceiling reads; one fleet-sim leg with
# its retries included (fleet/controller.py stamps it).
LEG_OP = "fleet.leg"
# The serving end-to-end histogram (submit -> delivery, per request).
E2E_OP = "serving.e2e"
# Coordinator-side serving counters the qps/error SLOs read (delta
# against the boot baseline, like the leg histogram).
SERVING_COUNTERS = ("serving.ok", "serving.errors")


def parse_slo_spec(raw: Optional[dict]) -> Dict[str, float]:
    """Validate a scenario's ``slo:`` mapping: known keys with numeric
    values survive, everything else is logged and dropped — including
    a section that is not a mapping at all (a YAML authoring typo must
    cost the SLOs, not the run)."""
    if raw is None:
        return {}
    if not isinstance(raw, dict):
        log.error("ignoring slo section of type %s (want a mapping)",
                  type(raw).__name__)
        return {}
    spec: Dict[str, float] = {}
    for key, value in raw.items():
        if key not in SLO_KEYS:
            log.error("ignoring unknown SLO key %r (known: %s)",
                      key, ", ".join(sorted(SLO_KEYS)))
            continue
        try:
            spec[key] = float(value)
        except (TypeError, ValueError):
            log.error("ignoring SLO %r with non-numeric limit %r",
                      key, value)
    return spec


class ScrapeError(OSError):
    """One node's /metrics endpoint could not be read (connection
    refused, timeout, bad body) — the per-node degradation signal."""


class NodeScrape:
    """One parsed Prometheus exposition: labeled samples per family."""

    def __init__(self, families: Dict[str, List[Tuple[dict, float]]]):
        self._families = families

    def value(self, family: str, default: float = 0.0,
              **labels: str) -> float:
        """First sample of ``family`` whose labels include ``labels``
        (absent family/labels -> ``default`` — an idle node and a
        never-active one scrape the same, like timeseries.rate)."""
        for lab, v in self._families.get(family, []):
            if all(lab.get(k) == want for k, want in labels.items()):
                return v
        return default

    def buckets(self, family: str, **labels: str) -> Dict[str, float]:
        """Every sample of ``family`` matching ``labels``, keyed by
        its ``bucket`` label — the ``agent_latency{op,bucket}``
        cumulative-histogram reader (empty dict when the op never
        observed anything on this node)."""
        out: Dict[str, float] = {}
        for lab, v in self._families.get(family, []):
            if all(lab.get(k) == want for k, want in labels.items()) \
                    and lab.get("bucket") is not None:
                out[lab["bucket"]] = v
        return out


def parse_prometheus_text(body: str) -> NodeScrape:
    return NodeScrape(promtext.parse_samples(body))


def scrape_metric_server(port: int,
                         timeout_s: float = DEFAULT_SCRAPE_TIMEOUT_S,
                         host: str = "127.0.0.1") -> NodeScrape:
    """One GET of a node's /metrics, parsed.  Raises
    :class:`ScrapeError` on any transport or parse trouble."""
    url = f"http://{host}:{int(port)}/metrics"
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            body = resp.read().decode("utf-8", "replace")
    except (urllib.error.URLError, OSError, ValueError) as e:
        raise ScrapeError(f"scrape of {url} failed: {e}") from e
    return parse_prometheus_text(body)


def scrape_spans(port: int, since: int,
                 timeout_s: float = DEFAULT_SCRAPE_TIMEOUT_S,
                 host: str = "127.0.0.1",
                 limit: int = SPANS_SCRAPE_LIMIT):
    """One GET of a node's ``/spans?since=<cursor>``: returns
    ``(spans, next_cursor, dropped)``.  Raises :class:`ScrapeError` on
    transport/parse trouble — callers apply the same stale discipline
    as metric scrapes."""
    url = (f"http://{host}:{int(port)}/spans?since={int(since)}"
           f"&limit={int(limit)}")
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            obj = json.loads(resp.read().decode("utf-8", "replace"))
        spans = obj.get("spans")
        cursor = int(obj.get("cursor", since))
        dropped = int(obj.get("dropped") or 0)
        if not isinstance(spans, list):
            raise ValueError("spans is not a list")
    except (urllib.error.URLError, OSError, ValueError, TypeError) as e:
        raise ScrapeError(f"span scrape of {url} failed: {e}") from e
    return spans, cursor, dropped


def scrape_profile(port: int, since: int,
                   timeout_s: float = DEFAULT_SCRAPE_TIMEOUT_S,
                   host: str = "127.0.0.1",
                   limit: int = PROFILE_SCRAPE_LIMIT):
    """One GET of a node's ``/profile?since=<cursor>``: returns
    ``(stacks, next_cursor, samples, dropped, subsystems)``.  Raises
    :class:`ScrapeError` on transport/parse trouble — callers apply
    the same stale discipline as metric and span scrapes."""
    url = (f"http://{host}:{int(port)}/profile?since={int(since)}"
           f"&limit={int(limit)}")
    try:
        obj = profiler.fetch(url, timeout_s)
        stacks_raw = obj.get("stacks")
        subs_raw = obj.get("subsystems") or {}
        if not isinstance(stacks_raw, list) \
                or not isinstance(subs_raw, dict):
            raise ValueError("malformed /profile body")
        # Normalize every numeric field HERE, inside the degradation
        # boundary: a port reused by some other process (a SIGKILLed
        # worker's successor) can answer JSON that passes the shape
        # check with garbage counts — that must cost a counted stale
        # miss, never an exception out of the round loop.
        stacks = [{"stack": str(e["stack"]),
                   "subsystem": str(e.get("subsystem", "other")),
                   "count": float(e.get("count") or 0)}
                  for e in stacks_raw
                  if isinstance(e, dict) and "stack" in e]
        subsystems = {str(k): float(v or 0)
                      for k, v in subs_raw.items()}
        cursor = int(obj.get("cursor", since))
        samples = float(obj.get("samples") or 0)
        dropped = float(obj.get("dropped") or 0)
    except (urllib.error.URLError, OSError, ValueError,
            TypeError, KeyError) as e:
        raise ScrapeError(f"profile scrape of {url} failed: {e}") from e
    return stacks, cursor, samples, dropped, subsystems


class FleetTelemetry:
    """Scrapes the fleet's telemetry each round and renders the SLO
    verdict at the end of the run.

    ``scrape=True`` (process-mode fleets) aggregates over HTTP from
    each node's MetricServer instead of reading this process's
    registries — the in-process registry reads are gone from that
    path entirely; a node that cannot be scraped degrades to a
    ``stale`` round entry instead of raising.
    """

    def __init__(self, nodes: dict, links, slo: Optional[dict] = None,
                 *, scrape: bool = False,
                 scrape_timeout_s: float = DEFAULT_SCRAPE_TIMEOUT_S,
                 learned_slo: Optional[dict] = None):
        self.nodes = nodes
        self.links = links
        self.slo = parse_slo_spec(slo)
        # History-learned SLO limits (obs/history.learned_limit
        # shapes: {key: {"limit", "source", "n", ...}}), applied on
        # top of the scenario's pinned limits in evaluate() — a
        # learned limit may TIGHTEN a check, never relax it past the
        # pinned constant (fleet/soak.py feeds this from prior runs'
        # measured values under TPU_HISTORY_DIR).
        self.learned_slo: Dict[str, dict] = dict(learned_slo or {})
        self.scrape = bool(scrape)
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.history: List[dict] = []
        # Restart-aware counter accumulation per node: worker counters
        # reset to zero on respawn, so the fleet totals sum increments
        # between scrapes, treating a decrease as a fresh process.
        self._accum: Dict[str, Dict[str, float]] = {}
        # Same-generation counter DECREASES — dropped from the totals
        # (see _accumulate) but recorded here, because a worker whose
        # cumulative counters go backwards without a respawn is a
        # monotonicity violation the soak sentinel must see, not just
        # a sample to silently skip.
        self.misreads: List[dict] = []
        self._t0 = time.monotonic()
        # Histograms are process-global and cumulative; the p99 SLOs
        # must judge THIS run only, so snapshot their buckets at boot
        # and evaluate the delta (the same baseline discipline
        # FleetController applies to counters).
        self._leg0: Dict[str, int] = dict(
            histo.snapshot().get(LEG_OP, {}).get("buckets", {}))
        self._e2e0: Dict[str, int] = dict(
            histo.snapshot().get(E2E_OP, {}).get("buckets", {}))
        self._serving0 = {k: counters.get(k) for k in SERVING_COUNTERS}
        # Exposed-comm SLO inputs: run-delta of the dcn.exposed /
        # dcn.comm histogram SUMS (coordinator-side in both modes —
        # the transfer clients live here).
        self._exposed_sum0 = histo.snapshot().get(
            "dcn.exposed", {}).get("sum_us", 0.0)
        self._comm_sum0 = histo.snapshot().get(
            "dcn.comm", {}).get("sum_us", 0.0)
        # Span collection for the report's critical_path section: the
        # coordinator's own ring is paged by cursor each round (the
        # clients' pipeline/serving spans live here); scrape-mode
        # fleets ALSO page each worker's /spans endpoint, so the
        # daemon-side halves of the same traces merge in.
        self._spans: List[dict] = []
        self._spans_dropped = 0
        self._local_cursor = 0
        self._span_cursors: Dict[str, int] = {}
        # Continuous-profiler collection (the report's ``profile``
        # section): per-node merged folded stacks, accumulated
        # restart-aware like the counters — a worker's cumulative
        # stack counts restart at zero on respawn, so the merge sums
        # increments keyed by incarnation.  Scraped per round so a
        # SIGKILL costs at most one round of samples, never the run's.
        self._prof: Dict[str, dict] = {}
        self._prof_cursors: Dict[str, int] = {}
        # The coordinator's own profiler registry is cumulative for
        # the process (like the histograms), so the report's
        # coordinator entry judges THIS run only: snapshot at boot,
        # delta at report time.
        self._prof0 = profiler.snapshot()
        # Collective round history (workload: collective): the
        # controller appends one entry per round — the engine lives
        # coordinator-side in both modes, so the busbw SLOs never
        # need the scrape path.
        self.collective_rounds: List[dict] = []
        # Grey-failure detection (obs/anomaly.py): peer-relative
        # robust z-scores per window folded into hysteretic per-node
        # verdicts.  Evidence per round: per-node goodput, scrape RTT,
        # profiler busy-share deltas, per-window p99s of the
        # attribution histograms (ANOMALY_HISTO_OPS), and fleet.leg
        # span latency per source node.  TPU_ANOMALY=0 makes all of
        # it inert.  One warmup window: the boot round's cold-start
        # transients (first-connection legs, half-warmed histograms)
        # have no peer baseline worth judging against.
        self.anomaly = anomaly.AnomalyDetector(
            anomaly.AnomalyConfig(warmup_windows=1))
        # Ground truth, fed by the soak world DURING the run (the
        # report is assembled before the soak section exists): seeded
        # grey-family faults as TruthWindow dicts, plus the FULL
        # schedule's window footprint — false positives only count on
        # windows with no scheduled fault of any kind in flight.
        self.anomaly_truth: List[dict] = []
        self.anomaly_chaos: set = set()
        # Per-(node, op) cumulative-bucket baselines for the windowed
        # histogram deltas, reset on worker generation change (a
        # respawned worker's buckets restart at zero).
        self._anom_buckets: Dict[Tuple[str, str], Dict[str, float]] = {}
        self._anom_bucket_gen: Dict[str, Optional[int]] = {}
        # Last-seen merged profiler totals per node, for the
        # per-window busy-share delta (the merge is already
        # restart-aware, so these totals are monotone).
        self._anom_prof_last: Dict[str, Tuple[float, float]] = {}
        # The window under assembly: {metric_op: {node: value}},
        # rebuilt each sample_round by the scrape path.
        self._anom_window: Dict[str, Dict[str, float]] = {}
        # Last-observation-carried-forward state for the sparse
        # histogram streams: {(node, stream): (p99, windows_held)}.
        self._anom_histo_hold: Dict[
            Tuple[str, str], Tuple[float, int]] = {}
        # Last (generation, cumulative transferred) per node, for the
        # WINDOWED goodput evidence.  The workers' own goodput gauge
        # is a lifetime average, and a lifetime average poisons the
        # peer comparison after a respawn: the fresh process's reset
        # counters read as roughly half its peers' goodput for the
        # remainder of the run — a systematic false conviction.
        self._anom_goodput_last: Dict[
            str, Tuple[Optional[int], float]] = {}

    # -- per-round scrape ----------------------------------------------------

    def sample_round(self, rnd: int) -> dict:
        """One scrape across every node: windowed goodput per node and
        per link, plus each live daemon's flow accounting.  The entry
        schema is identical in both modes; scrape mode adds HTTP as
        the transport and ``stale`` as the degradation verdict."""
        per_node = {}
        self._anom_window = {}
        for name, node in self.nodes.items():
            if self.scrape:
                per_node[name] = self._scrape_entry(name, node)
                continue
            entry = {
                "goodput_bps": round(
                    timeseries.rate(f"goodput.node.{name}"), 1),
                "down": node.down,
                "stale": False,
            }
            if not node.down:
                stats = node.daemon._stats()
                entry["active_flows"] = stats["active_flows"]
                entry["transferred"] = stats["total_transferred"]
            per_node[name] = entry
        per_link = {
            key: round(timeseries.rate(f"goodput.link.{key}"), 1)
            for key in self.links.report()
        } if not self.scrape else {}
        sample = {"round": rnd, "nodes": per_node,
                  "links_goodput_bps": per_link}
        if not self.scrape:
            # Lane evidence, fleet-wide: in-process daemons share one
            # gauge registry, so the split is global here; proc mode
            # carries it per node inside each scraped entry instead.
            gauges = timeseries.gauges()
            lanes = {
                lane: int(gauges.get(f"dcn.lane.{lane}.total_bytes",
                                     0.0))
                for lane in ("shm_direct", "shm", "socket")
            }
            if any(lanes.values()):
                sample["lanes_total_bytes"] = lanes
        self.history.append(sample)
        before = len(self._spans)
        self._drain_local_spans()
        if self.anomaly.enabled:
            # This round's coordinator spans (the drain may trim from
            # the front at the cap; then the whole tail stands in —
            # blurrier evidence, never an index error).
            fresh = (self._spans[before:]
                     if len(self._spans) >= before
                     else list(self._spans))
            self._anomaly_observe(rnd, per_node, fresh)
        return sample

    # -- grey-failure evidence (obs/anomaly.py) ------------------------------

    def _anomaly_observe(self, rnd: int, per_node: Dict[str, dict],
                         fresh_spans: List[dict]) -> None:
        """Fold one window of peer-comparable evidence into the
        detector.  Every stream is optional — a window where a stream
        carries no signal (idle lane, degenerate dispersion, too few
        peers) contributes nothing, and a stale/down node holds its
        verdict instead of scoring."""
        absent = {n for n, e in per_node.items()
                  if e.get("stale") or e.get("down")}
        evidence = [anomaly.Evidence(
            "goodput_win_bytes", self._anom_goodput(per_node),
            direction="low", abs_floor=4096.0, rel_floor=0.5)]
        rtts = {n: float(e["scrape_rtt_s"])
                for n, e in per_node.items() if "scrape_rtt_s" in e}
        if rtts:
            evidence.append(anomaly.Evidence(
                "scrape_rtt_s", rtts, direction="high",
                abs_floor=0.02))
        # Worst fleet.leg latency per SOURCE node this round — the
        # legs live coordinator-side in both fleet modes, so this
        # stream needs no scrape.
        legs: Dict[str, float] = {}
        for sp in fresh_spans:
            if sp.get("name") != LEG_OP:
                continue
            src = (sp.get("attrs") or {}).get("src")
            if src in per_node:
                legs[src] = max(legs.get(src, 0.0),
                                float(sp.get("dur_us") or 0.0))
        if legs:
            # Worst-leg latency is heavy-tailed even on a healthy
            # fleet (burst alignment, connection reuse), and a slow
            # DESTINATION drags its sources' legs too — corroborating
            # evidence, so the wide rel_floor keeps it from convicting
            # alone the way the node-local histograms may.
            evidence.append(anomaly.Evidence(
                "leg_dur_us", legs, direction="high",
                abs_floor=4096.0, rel_floor=0.5))
        for op, vals in self._anom_window.items():
            floor = 0.15 if op == "busy_share" else 4096.0
            if op != "busy_share":
                vals = self._anom_hold_fill(op, vals, per_node,
                                            absent)
            evidence.append(anomaly.Evidence(
                op, vals, direction="high", abs_floor=floor))
        self.anomaly.observe(rnd, evidence, absent=absent)

    def _anom_hold_fill(self, op: str, vals: Dict[str, float],
                        per_node: Dict[str, dict],
                        absent: Set[str]) -> Dict[str, float]:
        """Last-observation-carried-forward for the sparse histogram
        streams: a node that performed no ops this window contributes
        its last measured p99 (for up to ANOMALY_HOLD_WINDOWS) as the
        peer baseline.  Without it, one quiet node silences the whole
        stream under min_peers — exactly when a peer's throttle
        spikes and the conviction matters most."""
        out = dict(vals)
        for n in per_node:
            if n in out:
                self._anom_histo_hold[(n, op)] = (out[n], 0)
                continue
            if n in absent:
                continue
            held = self._anom_histo_hold.get((n, op))
            if held and held[1] < ANOMALY_HOLD_WINDOWS:
                out[n] = held[0]
                self._anom_histo_hold[(n, op)] = (held[0],
                                                  held[1] + 1)
        return out

    def _anom_goodput(self, per_node: Dict[str, dict]
                      ) -> Dict[str, float]:
        """Windowed goodput per node: the delta of each node's
        cumulative transferred total since the last window, keyed by
        worker incarnation.  A respawned node is judged on what its
        NEW process moved this window — its reset lifetime average
        would read grey for the rest of the run.  Sharper, too: a
        grey window's stall shows whole in its own delta instead of
        diluted into the run-long mean."""
        out: Dict[str, float] = {}
        for n, e in per_node.items():
            tot = e.get("transferred")
            if tot is None:
                # Down/stale entries carry no total (and sit in the
                # absent set); anything else falls back to the gauge.
                out[n] = float(e.get("goodput_bps") or 0.0)
                continue
            gen = getattr(getattr(self.nodes.get(n), "daemon", None),
                          "generation", None)
            last_gen, last_tot = self._anom_goodput_last.get(
                n, (gen, 0.0))
            if gen != last_gen:
                last_tot = 0.0
            self._anom_goodput_last[n] = (gen, float(tot))
            out[n] = max(0.0, float(tot) - last_tot)
        return out

    def _anom_fold_node(self, name: str, s: NodeScrape,
                        gen: Optional[int]) -> None:
        """One scraped node's contribution to the window under
        assembly: per-window p99s of the attribution histograms
        (cumulative le buckets deltaed against the last scrape,
        baselines reset on respawn) and the profiler busy-share delta
        (the merged profile totals are already restart-aware)."""
        if gen is not None and self._anom_bucket_gen.get(name) != gen:
            for op in ANOMALY_HISTO_OPS:
                self._anom_buckets.pop((name, op), None)
                self._anom_histo_hold.pop(
                    (name, f"{op}.p99_us"), None)
            self._anom_bucket_gen[name] = gen
            self._anom_prof_last.pop(name, None)
        for op in ANOMALY_HISTO_OPS:
            cur = s.buckets("agent_latency", op=op)
            base = self._anom_buckets.get((name, op), {})
            self._anom_buckets[(name, op)] = cur
            if not cur:
                continue
            p99 = anomaly.bucket_delta_p99_us(cur, base)
            if p99 is not None:
                self._anom_window.setdefault(
                    f"{op}.p99_us", {})[name] = p99
        st = self._prof.get(name)
        if st:
            samples = float(st["samples"])
            idle = float(st["subsystems"].get("idle", 0.0))
            last_s, last_i = self._anom_prof_last.get(name,
                                                     (0.0, 0.0))
            self._anom_prof_last[name] = (samples, idle)
            ds, di = samples - last_s, idle - last_i
            if ds > 0:
                busy = max(0.0, ds - max(0.0, di)) / ds
                self._anom_window.setdefault("busy_share",
                                             {})[name] = busy

    def anomaly_report(self) -> dict:
        """The report's ``anomaly`` section: the detector's verdicts
        plus — when the soak world fed seeded ground truth — the
        closed-loop precision/recall judgment."""
        rep = self.anomaly.report()
        if self.anomaly_truth:
            rep["detection"] = self.detection_summary()
        return rep

    def detection_summary(self,
                          k: int = anomaly.DETECT_WINDOWS_K) -> dict:
        truth = [anomaly.TruthWindow(**t) for t in self.anomaly_truth]
        return anomaly.detection_report(
            truth, self.anomaly.flagged,
            self.anomaly.windows_observed, k=k,
            chaos_windows=self.anomaly_chaos)

    def _grey_detection_windows(self) -> float:
        """The max_grey_detection_windows SLO input: 0.0 with no
        seeded truth (vacuous), the worst windows-to-flag otherwise —
        and a MISS measures the whole run length, honestly outside
        any sane ceiling."""
        if not self.anomaly_truth:
            return 0.0
        det = self.detection_summary()
        if det["missed"]:
            return float(max(self.anomaly.windows_observed,
                             det["k"] + 1))
        return det["detect_windows_max"]

    # -- span collection (the critical_path section's evidence) --------------

    def _keep_spans(self, spans: List[dict]) -> None:
        self._spans.extend(spans)
        over = len(self._spans) - MAX_COLLECTED_SPANS
        if over > 0:
            del self._spans[:over]
            self._spans_dropped += over

    def _drain_local_spans(self) -> None:
        """Page the COORDINATOR's span ring by cursor — per round, so
        a long scenario outrunning the ring loses (and counts) spans
        instead of silently keeping only the tail."""
        spans, self._local_cursor, dropped = trace.tail_since(
            self._local_cursor)
        self._spans_dropped += dropped
        self._keep_spans(spans)

    def _scrape_node_spans(self, name: str, node) -> bool:
        """One worker's /spans page, same timeout/stale discipline as
        the metric scrape (one attempt + one retry, degrade to a
        counted miss — never a hang, never an exception).  The cursor
        is respawn-aware, like the counter accumulator: a new worker
        incarnation's ring restarts at sequence 0, so carrying the
        dead incarnation's cursor would silently skip everything the
        fresh process recorded — reset to 0 on a generation change."""
        gen = getattr(getattr(node, "daemon", None), "generation",
                      None)
        key = "_gen_" + name
        if gen is not None and self._span_cursors.get(key) != gen:
            self._span_cursors[name] = 0
            self._span_cursors[key] = gen
        last: Optional[ScrapeError] = None
        for _attempt in range(2):
            try:
                spans, cursor, dropped = scrape_spans(
                    node.metrics_port,
                    self._span_cursors.get(name, 0),
                    self.scrape_timeout_s)
                self._span_cursors[name] = cursor
                self._spans_dropped += dropped
                self._keep_spans(spans)
                return True
            except ScrapeError as e:
                last = e
        counters.inc("fleet.scrape.spans_stale")
        log.warning("node %s span scrape degraded to stale: %s",
                    name, last)
        return False

    def spans(self) -> List[dict]:
        """Every span collected so far (coordinator ring + scraped
        workers), with a final local drain so the report sees the last
        round's tail — the critical_path section's input."""
        self._drain_local_spans()
        return list(self._spans)

    @property
    def spans_dropped(self) -> int:
        return self._spans_dropped

    # -- profile collection (the report's ``profile`` section) ---------------

    def _scrape_node_profile(self, name: str, node) -> bool:
        """One worker's /profile page, same timeout/stale discipline
        as the metric and span scrapes (one attempt + one retry,
        degrade to a counted miss — never a hang, never a raise).
        The cursor is respawn-aware like the span cursor: a fresh
        incarnation's sample sequence restarts at 0, so a generation
        change resets the cursor instead of silently skipping
        everything the new process sampled."""
        gen = getattr(getattr(node, "daemon", None), "generation",
                      None)
        key = "_gen_" + name
        if gen is not None and self._prof_cursors.get(key) != gen:
            self._prof_cursors[name] = 0
            self._prof_cursors[key] = gen
        last: Optional[ScrapeError] = None
        for _attempt in range(2):
            try:
                stacks, cursor, samples, dropped, subsystems = \
                    scrape_profile(node.metrics_port,
                                   self._prof_cursors.get(name, 0),
                                   self.scrape_timeout_s)
                self._prof_cursors[name] = cursor
                self._merge_profile(name, stacks, samples, dropped,
                                    subsystems, gen)
                return True
            except ScrapeError as e:
                last = e
        counters.inc("fleet.scrape.profile_stale")
        log.warning("node %s profile scrape degraded to stale: %s",
                    name, last)
        return False

    def _merge_profile(self, name: str, stacks: List[dict],
                       samples: float, dropped: float,
                       subsystems: Dict[str, float],
                       gen: Optional[int] = None) -> None:
        """Fold one scraped /profile page into ``name``'s merged
        profile, restart-aware: every scraped count is cumulative for
        the worker's life, so the merge adds increments against the
        last-seen value — a generation change means a fresh process
        (everything it shows is new increment), and a same-incarnation
        decrease is a misread to drop, exactly like `_accumulate`."""
        st = self._prof.setdefault(name, {
            "stacks": {}, "subsystems": {}, "samples": 0.0,
            "dropped": 0.0, "_last": {}, "_gen": None,
        })
        if gen is not None and gen != st["_gen"]:
            st["_last"] = {}
            st["_gen"] = gen

        def fold(key, current, bump, decrease="drop"):
            current = float(current)
            last = st["_last"].get(key, 0.0)
            if current < last:
                if gen is not None and decrease == "drop":
                    return  # same incarnation: a misread, drop it
                # Fresh accumulation: no gen evidence means a fresh
                # process; decrease="fresh" means the worker's LRU
                # legitimately evicted and re-admitted this stack
                # (its pre-eviction samples are already merged, and
                # the evicted remainder was counted in `dropped`).
                delta = current
            else:
                delta = current - last
            st["_last"][key] = current
            bump(delta)

        fold(("total", "samples"), samples,
             lambda d: st.__setitem__("samples", st["samples"] + d))
        fold(("total", "dropped"), dropped,
             lambda d: st.__setitem__("dropped", st["dropped"] + d))
        for sub, count in subsystems.items():
            fold(("sub", sub), count,
                 lambda d, s=sub: st["subsystems"].__setitem__(
                     s, st["subsystems"].get(s, 0.0) + d))
        for entry in stacks:
            stack = entry.get("stack")
            if not isinstance(stack, str):
                continue
            sub = str(entry.get("subsystem", "other"))
            # decrease="fresh": the worker profiler never resets its
            # registry mid-life, so a same-incarnation PER-STACK
            # decrease can only be LRU eviction + re-admission — the
            # new count is new accumulation, not a misread.  (The
            # totals and subsystem counters above are monotonic for
            # the worker's life, so a decrease there stays a misread.)
            fold(("stack", stack), entry.get("count", 0),
                 lambda d, s=stack, m=sub: st["stacks"].__setitem__(
                     s, {"subsystem": m,
                         "count": st["stacks"].get(
                             s, {"count": 0.0})["count"] + d}),
                 decrease="fresh")

    def profile_report(self,
                       top_n: int = PROFILE_REPORT_TOP_N) -> dict:
        """The report's ``profile`` section: per-node merged folded
        stacks (scraped workers plus this process's own profiler when
        it sampled anything — the coordinator runs the transfer
        clients in both fleet modes) and the fleet-wide aggregate,
        each with a subsystem rollup and the top-N stacks."""

        def top(stacks: Dict[str, dict], n: int) -> List[dict]:
            rows = sorted(stacks.items(),
                          key=lambda kv: (-kv[1]["count"], kv[0]))
            return [{"stack": s, "subsystem": m["subsystem"],
                     "count": int(m["count"])}
                    for s, m in rows[:n] if m["count"] > 0]

        merged = {
            name: {"stacks": dict(st["stacks"]),
                   "subsystems": dict(st["subsystems"]),
                   "samples": st["samples"], "dropped": st["dropped"]}
            for name, st in self._prof.items()
        }
        local = profiler.snapshot()
        base_stacks = {e["stack"]: e["count"]
                       for e in self._prof0["stacks"]}
        base_subs = self._prof0["subsystems"]
        local_samples = local["samples"] - self._prof0["samples"]
        if local_samples > 0:
            stacks = {}
            for e in local["stacks"]:
                d = e["count"] - base_stacks.get(e["stack"], 0)
                if d > 0:
                    stacks[e["stack"]] = {"subsystem": e["subsystem"],
                                          "count": float(d)}
            merged["coordinator"] = {
                "stacks": stacks,
                "subsystems": {
                    k: float(v - base_subs.get(k, 0))
                    for k, v in local["subsystems"].items()
                    if v - base_subs.get(k, 0) > 0},
                "samples": float(local_samples),
                "dropped": float(max(0, local["dropped"]
                                     - self._prof0["dropped"])),
            }
        nodes = {}
        fleet_stacks: Dict[str, dict] = {}
        fleet_subs: Dict[str, float] = {}
        total = dropped = 0.0
        for name, st in merged.items():
            nodes[name] = {
                "samples": int(st["samples"]),
                "dropped": int(st["dropped"]),
                "subsystems": {k: int(v)
                               for k, v in st["subsystems"].items()
                               if v > 0},
                "top": top(st["stacks"], top_n),
            }
            for stack, m in st["stacks"].items():
                f = fleet_stacks.setdefault(
                    stack, {"subsystem": m["subsystem"], "count": 0.0})
                f["count"] += m["count"]
            for sub, v in st["subsystems"].items():
                fleet_subs[sub] = fleet_subs.get(sub, 0.0) + v
            total += st["samples"]
            dropped += st["dropped"]
        return {
            "nodes": nodes,
            "fleet": {
                "samples": int(total),
                "dropped": int(dropped),
                "subsystems": {k: int(v) for k, v in fleet_subs.items()
                               if v > 0},
                "top": top(fleet_stacks, top_n),
            },
        }

    # -- HTTP scrape path (process-mode fleets) ------------------------------

    def _scrape_entry(self, name: str, node) -> dict:
        """One node's round entry, from its /metrics endpoint.  A down
        or unreachable node yields ``stale: true`` — never an
        exception, never a hang past the per-node budget."""
        if node.down:
            return {"goodput_bps": 0.0, "down": True, "stale": True}
        last: Optional[ScrapeError] = None
        for _attempt in range(2):  # one retry, same budget each
            try:
                rtt_t0 = time.monotonic()
                s = scrape_metric_server(node.metrics_port,
                                         self.scrape_timeout_s)
                scrape_rtt_s = time.monotonic() - rtt_t0
                break
            except ScrapeError as e:
                last = e
        else:
            counters.inc("fleet.scrape.stale")
            log.warning("node %s metrics scrape degraded to stale: %s",
                        name, last)
            return {"goodput_bps": 0.0, "down": False, "stale": True}
        # Fleet ratio inputs are cumulative worker counters; fold them
        # into the restart-aware totals while the scrape is fresh,
        # keyed by the worker's incarnation (the coordinator-side
        # spawn count) so a respawn is detected even when the new
        # process has already climbed past the dead one's last value.
        gen = getattr(getattr(node, "daemon", None), "generation", None)
        self._accumulate(name, "deduped",
                         s.value("agent_events",
                                 event="dcn.frames.deduped"), gen=gen)
        self._accumulate(name, "frames",
                         s.value("agent_events",
                                 event="xferd.frames.landed"), gen=gen)
        entry = {
            "goodput_bps": round(
                s.value("agent_goodput", scope="node", name=name), 1),
            "down": False,
            "stale": False,
            # Scrape round-trip time doubles as grey-failure evidence:
            # a worker whose GIL a CPU burn is holding answers its
            # /metrics GET late, and only THAT worker does.
            "scrape_rtt_s": round(scrape_rtt_s, 4),
            "spans_stale": not self._scrape_node_spans(name, node),
            "profile_stale": not self._scrape_node_profile(name, node),
            "active_flows": int(s.value("agent_gauge",
                                        name="xferd.active_flows")),
            "transferred": int(s.value("agent_gauge",
                                       name="xferd.total_transferred")),
        }
        if self.anomaly.enabled:
            self._anom_fold_node(name, s, gen)
        # Per-node lane evidence (the memcpy-speed same-host plane):
        # a worker whose shm_direct total grows while its socket
        # total stays flat is provably skipping the peer TCP stream.
        lanes = {
            lane: int(s.value("agent_gauge",
                              name=f"dcn.lane.{lane}.total_bytes"))
            for lane in ("shm_direct", "shm", "socket")
        }
        if any(lanes.values()):
            entry["lanes_total_bytes"] = lanes
        return entry

    def _accumulate(self, node: str, key: str, current: float,
                    gen: Optional[int] = None) -> None:
        st = self._accum.setdefault(node, {})
        last = st.get("_last_" + key, 0.0)
        if gen is not None and gen != st.get("_gen_" + key):
            # A new worker incarnation: its counters started at zero,
            # so everything it shows is new increment — even when it
            # has already climbed PAST the dead incarnation's last
            # scraped value (the decrease heuristic alone misses that).
            delta = current
        elif gen is not None and current < last:
            # Same incarnation but the counter went DOWN: a worker
            # cannot decrement its own cumulative counters and the
            # supervisor bumps the generation on every respawn, so
            # this can only be a misread (e.g. the scrape raced the
            # exporter's periodic registry reset).  Folding it in
            # would double-count the pre-reset total on the next
            # scrape — drop the sample and keep the last-known state,
            # but RECORD the event: the soak monotonicity sentinel
            # treats a same-generation decrease as a verdict input.
            self.misreads.append({
                "node": node, "key": key,
                "last": last, "current": current, "gen": gen,
            })
            return
        elif current < last:
            # No incarnation evidence but the counter went DOWN:
            # still unmistakably a fresh process.
            delta = current
        else:
            delta = current - last
        st[key] = st.get(key, 0.0) + delta
        st["_last_" + key] = current
        if gen is not None:
            st["_gen_" + key] = gen

    def _accum_total(self, key: str) -> float:
        return sum(st.get(key, 0.0) for st in self._accum.values())

    # -- SLO evaluation ------------------------------------------------------

    def _histo_p99_ms(self, op: str, baseline: Dict[str, int]) -> float:
        """p99 of THIS run's observations of ``op``: current buckets
        minus the boot baseline (histo.delta_percentile_us)."""
        p_us = histo.delta_percentile_us(op, baseline, 0.99)
        return 0.0 if p_us is None else p_us / 1e3

    def _leg_p99_ms(self) -> float:
        return self._histo_p99_ms(LEG_OP, self._leg0)

    def _exposed_comm_ratio(self) -> float:
        """THIS run's exposed-communication ratio: the dcn.exposed /
        dcn.comm histogram-sum deltas since boot.  0.0 when the run
        moved no pipelined bytes (nothing to judge)."""
        snap = histo.snapshot()
        exp = snap.get("dcn.exposed", {}).get("sum_us", 0.0) \
            - self._exposed_sum0
        comm = snap.get("dcn.comm", {}).get("sum_us", 0.0) \
            - self._comm_sum0
        if comm <= 0:
            return 0.0
        return max(0.0, exp) / comm

    def _final_round_goodput(self) -> float:
        """Goodput of the last sampled round with any live (non-stale)
        node entry — the post-heal recovery floor's input.  Rounds
        where every node was stale are walked past (a node mid-respawn
        at the final sample must not zero the verdict); no history at
        all measures 0.0."""
        for sample in reversed(self.history):
            live = [e["goodput_bps"] for e in sample["nodes"].values()
                    if not e.get("stale")]
            if live:
                return sum(live)
        return 0.0

    def _collective_measurements(self) -> dict:
        """The collective busbw SLO inputs, from the controller-fed
        round history.  Only completed (ok) rounds carry bandwidth; a
        run with no collective rounds measures 0.0 — vacuous only if
        no busbw SLO was configured, honestly breached otherwise (a
        floor on a workload that never ran must fail, not pass)."""
        done = [r.get("busbw_bps", 0.0)
                for r in self.collective_rounds if r.get("ok")]
        routed = [r["routed"] for r in self.collective_rounds
                  if r.get("ok") and r.get("routed")]
        return {
            "min_busbw_bps": (sum(done) / len(done)) if done else 0.0,
            "min_final_busbw_bps": done[-1] if done else 0.0,
            # No routed rounds: the floor honestly breaches (a
            # forwarding proof on a workload that never forwarded must
            # fail), the ceiling is vacuously inside 0.
            "min_forward_bytes": float(sum(
                r.get("forward_bytes", 0) for r in routed)),
            "max_coordinator_leg_bytes": float(sum(
                r.get("coordinator_payload_bytes", 0)
                for r in routed)),
        }

    def _serving_measurements(self, elapsed_s: float) -> dict:
        """The serving SLO inputs — coordinator-side in BOTH modes:
        the ServingFrontend runs in the controller process, so its
        counters and the e2e histogram never need the scrape path."""
        ok = counters.get("serving.ok") - self._serving0["serving.ok"]
        errors = (counters.get("serving.errors")
                  - self._serving0["serving.errors"])
        return {
            "p99_e2e_ms": self._histo_p99_ms(E2E_OP, self._e2e0),
            "min_qps": ok / elapsed_s,
            "max_error_ratio": errors / max(1, ok + errors),
        }

    def _measurements(self, links_report: Dict[str, dict]) -> dict:
        elapsed_s = max(time.monotonic() - self._t0, 1e-9)
        delivered_bytes = sum(l["bytes"] for l in links_report.values())
        frames = sum(l["frames"] for l in links_report.values())
        drops = sum(l["drops"] for l in links_report.values())
        dups = sum(l["dups"] for l in links_report.values())
        return {
            "elapsed_s": round(elapsed_s, 3),
            "p99_leg_ms": self._leg_p99_ms(),
            "min_goodput_bps": delivered_bytes / elapsed_s,
            "max_retransmit_ratio": (drops + dups) / max(1, frames),
            "max_dedup_ratio": dups / max(1, frames),
            "max_exposed_comm_ratio": self._exposed_comm_ratio(),
            "min_final_goodput_bps": self._final_round_goodput(),
            "max_grey_detection_windows":
                self._grey_detection_windows(),
            **self._collective_measurements(),
            **self._serving_measurements(elapsed_s),
        }

    def _measurements_scraped(self) -> dict:
        """Scrape-mode measurements, from the HTTP history.  Stale
        windows are SKIPPED, not zeroed: a round where a node was down
        must not be averaged in as zero goodput — the kill is the
        scenario's point, and the floor judges the fleet while it was
        observable.  Node entries that were stale are excluded from
        their round's sum; rounds with no live entry at all are
        dropped outright."""
        elapsed_s = max(time.monotonic() - self._t0, 1e-9)
        round_sums = []
        stale_entries = 0
        for sample in self.history:
            live = [e["goodput_bps"] for e in sample["nodes"].values()
                    if not e.get("stale")]
            stale_entries += sum(1 for e in sample["nodes"].values()
                                 if e.get("stale"))
            if live:
                round_sums.append(sum(live))
        goodput = (sum(round_sums) / len(round_sums)
                   if round_sums else 0.0)
        # No link fabric between processes: drops are invisible here,
        # so both ratio caps judge the receiver-side dedup evidence
        # (replays that actually re-landed) over frames that landed.
        deduped = self._accum_total("deduped")
        frames = self._accum_total("frames")
        ratio = deduped / max(1.0, frames)
        return {
            "elapsed_s": round(elapsed_s, 3),
            "p99_leg_ms": self._leg_p99_ms(),
            "min_goodput_bps": goodput,
            "max_retransmit_ratio": ratio,
            "max_dedup_ratio": ratio,
            "max_exposed_comm_ratio": self._exposed_comm_ratio(),
            "min_final_goodput_bps": self._final_round_goodput(),
            "max_grey_detection_windows":
                self._grey_detection_windows(),
            "stale_entries_skipped": stale_entries,
            **self._collective_measurements(),
            **self._serving_measurements(elapsed_s),
        }

    def evaluate(self, links_report: Dict[str, dict]) -> dict:
        """The report's ``slo`` section: every configured check with
        its measured value, the limit, and pass/fail; ``ok`` is the
        conjunction (vacuously true with no SLOs configured).  Each
        verdict is also published as ``slo.<key>.ok`` /
        ``slo.<key>.value`` gauges for the live scrape surface."""
        measured = (self._measurements_scraped() if self.scrape
                    else self._measurements(links_report))
        checks = []
        for key, limit in self.slo.items():
            kind, what = SLO_KEYS[key]
            value = measured[key]
            source = "pinned"
            learned = self.learned_slo.get(key)
            if learned and learned.get("source") == "learned":
                # Tighten-only: a ceiling may come DOWN toward the
                # fleet's demonstrated baseline, a floor may come UP
                # — neither ever relaxes past the scenario's pinned
                # limit (the hard bound the learner cannot cross).
                lv = float(learned["limit"])
                tightened = (min(limit, lv) if kind == "ceiling"
                             else max(limit, lv))
                if tightened != limit:
                    limit = tightened
                    source = "learned"
            ok = value >= limit if kind == "floor" else value <= limit
            check = {
                "slo": key, "kind": kind, "what": what,
                "limit": limit, "value": round(value, 3),
                "ok": bool(ok),
            }
            if source == "learned":
                check["limit_source"] = "learned"
                check["pinned_limit"] = self.slo[key]
                check["history_n"] = learned.get("n")
            checks.append(check)
            timeseries.gauge(f"slo.{key}.ok", 1.0 if ok else 0.0)
            timeseries.gauge(f"slo.{key}.value", value)
        ok = all(c["ok"] for c in checks)
        if checks and not ok:
            breached = [c["slo"] for c in checks if not c["ok"]]
            log.warning("SLO breach: %s", ", ".join(breached))
        return {
            "spec": dict(self.slo),
            "measured": {k: round(v, 3) for k, v in measured.items()},
            "checks": checks,
            "ok": ok,
        }
