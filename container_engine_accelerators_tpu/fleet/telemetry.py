"""Fleet telemetry: per-round scrapes + declarative SLO verdicts.

The fleet report (fleet/controller.py) already says whether a scenario
*converged* — every surviving node's final legs completed.  That is a
liveness verdict, and liveness is a low bar: a fleet that re-sends
every chunk three times through a lossy link still converges while
delivering a third of the bandwidth anyone provisioned for.  This
module adds the quality verdict:

- **scrape**: each round the aggregator reads every emulated node's
  telemetry — windowed goodput per ``{node, link}`` from
  obs/timeseries.py (the sim runs nodes in one process, so the series
  registry is the fleet's, keyed by the ``goodput.node.<n>`` /
  ``goodput.link.<a>-><b>`` naming convention) plus each daemon's flow
  accounting — into a round-indexed history;

- **SLOs**: the scenario spec's ``slo:`` mapping declares ceilings and
  floors, evaluated over the whole run::

      slo:
        p99_leg_ms: 250            # ceiling: p99 of fleet.leg latency
        min_goodput_bps: 4096      # floor: delivered link bytes/s
        max_retransmit_ratio: 0.5  # cap: (drops + dups) / frames
        max_dedup_ratio: 0.25      # cap: dups / frames

  Unknown keys are logged and skipped — the TPU_FAULT_SPEC rule: a
  typo'd scenario must degrade, not crash the rig.  Each check also
  lands in the gauge registry as ``slo.<key>.ok`` / ``slo.<key>.value``
  so the MetricServer scrape (``agent_gauge``), ``cmd/agent_top.py``,
  and the flight recorder all show SLO state live.

The controller folds :meth:`FleetTelemetry.evaluate`'s result into the
report's ``slo`` section and ``cmd/fleet_sim.py`` exits non-zero on
breach — a fleet that converges while violating its goodput floor
fails CI, not just a dashboard.
"""

import logging
import time
from typing import Dict, List, Optional

from container_engine_accelerators_tpu.obs import histo, timeseries

log = logging.getLogger(__name__)

# SLO key -> (kind, description).  Ceilings fail when value > limit,
# floors when value < limit.
SLO_KEYS = {
    "p99_leg_ms": ("ceiling", "p99 of fleet.leg latency (ms)"),
    "min_goodput_bps": ("floor", "delivered link bytes per second"),
    "max_retransmit_ratio": ("ceiling",
                             "(link drops + deduped replays) / frames"),
    "max_dedup_ratio": ("ceiling", "deduped replays / frames"),
}

# The latency histogram the p99 ceiling reads; one fleet-sim leg with
# its retries included (fleet/controller.py stamps it).
LEG_OP = "fleet.leg"


def parse_slo_spec(raw: Optional[dict]) -> Dict[str, float]:
    """Validate a scenario's ``slo:`` mapping: known keys with numeric
    values survive, everything else is logged and dropped — including
    a section that is not a mapping at all (a YAML authoring typo must
    cost the SLOs, not the run)."""
    if raw is None:
        return {}
    if not isinstance(raw, dict):
        log.error("ignoring slo section of type %s (want a mapping)",
                  type(raw).__name__)
        return {}
    spec: Dict[str, float] = {}
    for key, value in raw.items():
        if key not in SLO_KEYS:
            log.error("ignoring unknown SLO key %r (known: %s)",
                      key, ", ".join(sorted(SLO_KEYS)))
            continue
        try:
            spec[key] = float(value)
        except (TypeError, ValueError):
            log.error("ignoring SLO %r with non-numeric limit %r",
                      key, value)
    return spec


class FleetTelemetry:
    """Scrapes the fleet's telemetry each round and renders the SLO
    verdict at the end of the run."""

    def __init__(self, nodes: dict, links, slo: Optional[dict] = None):
        self.nodes = nodes
        self.links = links
        self.slo = parse_slo_spec(slo)
        self.history: List[dict] = []
        self._t0 = time.monotonic()
        # Histograms are process-global and cumulative; the p99 SLO
        # must judge THIS run only, so snapshot the leg histogram's
        # buckets at boot and evaluate the delta (the same baseline
        # discipline FleetController applies to counters).
        self._leg0: Dict[str, int] = dict(
            histo.snapshot().get(LEG_OP, {}).get("buckets", {}))

    # -- per-round scrape ----------------------------------------------------

    def sample_round(self, rnd: int) -> dict:
        """One scrape across every node: windowed goodput per node and
        per link, plus each live daemon's flow accounting."""
        per_node = {}
        for name, node in self.nodes.items():
            entry = {
                "goodput_bps": round(
                    timeseries.rate(f"goodput.node.{name}"), 1),
                "down": node.down,
            }
            if not node.down:
                stats = node.daemon._stats()
                entry["active_flows"] = stats["active_flows"]
                entry["transferred"] = stats["total_transferred"]
            per_node[name] = entry
        per_link = {
            key: round(timeseries.rate(f"goodput.link.{key}"), 1)
            for key in self.links.report()
        }
        sample = {"round": rnd, "nodes": per_node,
                  "links_goodput_bps": per_link}
        self.history.append(sample)
        return sample

    # -- SLO evaluation ------------------------------------------------------

    def _leg_p99_ms(self) -> float:
        """p99 of THIS run's fleet.leg observations: current buckets
        minus the boot baseline, upper-bound quantile like
        histo.percentile."""
        now = histo.snapshot().get(LEG_OP, {}).get("buckets", {})
        delta = {int(le): n - self._leg0.get(le, 0)
                 for le, n in now.items()
                 if n - self._leg0.get(le, 0) > 0}
        total = sum(delta.values())
        if not total:
            return 0.0
        target = 0.99 * total
        seen = 0
        for le in sorted(delta):
            seen += delta[le]
            if seen >= target:
                return le / 1e3
        return max(delta) / 1e3  # pragma: no cover — q <= 1

    def _measurements(self, links_report: Dict[str, dict]) -> dict:
        elapsed_s = max(time.monotonic() - self._t0, 1e-9)
        delivered_bytes = sum(l["bytes"] for l in links_report.values())
        frames = sum(l["frames"] for l in links_report.values())
        drops = sum(l["drops"] for l in links_report.values())
        dups = sum(l["dups"] for l in links_report.values())
        return {
            "elapsed_s": round(elapsed_s, 3),
            "p99_leg_ms": self._leg_p99_ms(),
            "min_goodput_bps": delivered_bytes / elapsed_s,
            "max_retransmit_ratio": (drops + dups) / max(1, frames),
            "max_dedup_ratio": dups / max(1, frames),
        }

    def evaluate(self, links_report: Dict[str, dict]) -> dict:
        """The report's ``slo`` section: every configured check with
        its measured value, the limit, and pass/fail; ``ok`` is the
        conjunction (vacuously true with no SLOs configured).  Each
        verdict is also published as ``slo.<key>.ok`` /
        ``slo.<key>.value`` gauges for the live scrape surface."""
        measured = self._measurements(links_report)
        checks = []
        for key, limit in self.slo.items():
            kind, what = SLO_KEYS[key]
            value = measured[key]
            ok = value >= limit if kind == "floor" else value <= limit
            checks.append({
                "slo": key, "kind": kind, "what": what,
                "limit": limit, "value": round(value, 3),
                "ok": bool(ok),
            })
            timeseries.gauge(f"slo.{key}.ok", 1.0 if ok else 0.0)
            timeseries.gauge(f"slo.{key}.value", value)
        ok = all(c["ok"] for c in checks)
        if checks and not ok:
            breached = [c["slo"] for c in checks if not c["ok"]]
            log.warning("SLO breach: %s", ", ".join(breached))
        return {
            "spec": dict(self.slo),
            "measured": {k: round(v, 3) for k, v in measured.items()},
            "checks": checks,
            "ok": ok,
        }
