"""Fleet topology model: hosts, racks, slices — scheduler labels reused.

A simulated fleet node carries the SAME label set the node labeler
stamps and the topology scheduler sorts on (scheduler/topology.py), so
the simulator and the production placement logic agree about what is
"near": two nodes in one rack are one DCN tier apart, two racks are
two, and the classification below is computed with the production
``node_topology_distance`` — not re-derived ad hoc.  That is the point
of the rig: when the ROADMAP's topology-reasoning work lands, it can be
validated against fleets whose distance structure is the scheduler's
own.
"""

import dataclasses
from typing import Dict, List, Optional

from container_engine_accelerators_tpu.scheduler import topology as topo

# Link tiers, from the production distance function's point of view.
TIER_ICI = "ici"              # same slice: ICI mesh hops, no DCN
TIER_INTRA_RACK = "intra-rack"  # same rack, different slice: one DCN tier
TIER_CROSS_RACK = "cross-rack"  # different rack: the expensive links


@dataclasses.dataclass
class NodeSpec:
    """One simulated host: identity, placement, and its chip complement."""

    name: str
    rack: str = "r0"
    cluster: str = "c0"
    placement_group: str = "pg0"
    slice_id: Optional[str] = None  # defaults to the node name (1 host/slice)
    chips: int = 4
    topology: str = "2x2x1"
    partition_size: str = ""  # e.g. "2x2" → sub-slice devices
    # Host origin in the slice's ICI mesh ("x,y,z").  Single-host
    # slices sit at the origin; a multi-host slice gives each member
    # its real coordinates, so the production distance function sees
    # actual torus hops between them instead of every host aliasing
    # to one point (which made same-slice hosts indistinguishable).
    coords: str = "0,0,0"

    def labels(self) -> Dict[str, str]:
        """The label set label_nodes.py would stamp on this host."""
        return {
            topo.PLACEMENT_GROUP_LABEL: self.placement_group,
            topo.CLUSTER_LABEL: self.cluster,
            topo.RACK_LABEL: self.rack,
            topo.HOST_LABEL: self.name,
            topo.SLICE_LABEL: self.slice_id or self.name,
            topo.COORDS_LABEL: self.coords,
            topo.TPU_TOPOLOGY_LABEL: self.topology,
        }

    def node_info(self) -> dict:
        """The shape scheduler.topology functions consume."""
        return {"node_labels": self.labels()}


class FleetTopology:
    """The fleet's node set plus selector and distance queries."""

    def __init__(self, specs: List[NodeSpec]):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate node names in fleet: {names}")
        self.specs: Dict[str, NodeSpec] = {s.name: s for s in specs}

    def __contains__(self, name: str) -> bool:
        return name in self.specs

    def names(self) -> List[str]:
        return list(self.specs)

    def select(self, selector: str) -> List[str]:
        """Resolve a fleet selector to node names.

        ``*`` = every node, ``node:<name>`` = that node,
        ``rack:<name>`` = every node in the rack.  Unknown selectors
        resolve empty (a scenario naming a missing rack should produce
        an empty fault, not a crash mid-run).
        """
        if selector == "*":
            return self.names()
        kind, _, value = selector.partition(":")
        if kind == "node":
            return [value] if value in self.specs else []
        if kind == "rack":
            return [n for n, s in self.specs.items() if s.rack == value]
        return []

    def distance(self, a: str, b: str) -> float:
        """Production scheduler distance between two fleet nodes."""
        return topo.node_topology_distance(
            self.specs[a].node_info(), self.specs[b].node_info()
        )

    def tier(self, a: str, b: str) -> str:
        """Classify the (a, b) link by the production distance: below
        the DCN floor is ICI; at/above it, same-rack labels are one
        tier, cross-rack the other."""
        if self.distance(a, b) < topo.DCN_MIN:
            return TIER_ICI
        if self.specs[a].rack == self.specs[b].rack:
            return TIER_INTRA_RACK
        return TIER_CROSS_RACK


def build_specs(
    num_nodes: int,
    racks: int = 1,
    chips: int = 4,
    topology: str = "2x2x1",
    partition_size: str = "",
) -> List[NodeSpec]:
    """Round-robin ``num_nodes`` hosts over ``racks`` racks — the quick
    path for scenario specs that give counts instead of explicit node
    lists."""
    if num_nodes < 1 or racks < 1:
        raise ValueError("need at least one node and one rack")
    return [
        NodeSpec(
            name=f"n{i}",
            rack=f"r{i % racks}",
            chips=chips,
            topology=topology,
            partition_size=partition_size,
        )
        for i in range(num_nodes)
    ]
