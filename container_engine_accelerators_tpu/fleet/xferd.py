"""PyXferd — a protocol-faithful Python dcnxferd with a real data plane.

tests/xferd_stub.py models only the control plane: enough to kill and
restart "a daemon" under a single resilient client, useless for a
fleet.  PyXferd is the fleet's per-node daemon double, faithful to the
native daemon's whole contract (native/dcnxferd/dcnxferd.cc):

- newline-JSON control ops over a UDS (register/record/release/stats/
  ping/version/data_port/send/read), flows owned by their registering
  connection (buffer lifetime == connection lifetime, like rxdm);
- a real TCP data plane: ``put`` frames land over it byte-identical to
  the native daemon's framing, and ``send`` streams a staged flow to a
  peer daemon — directly over TCP when standalone (cross-process
  rigs), or through the :class:`~…fleet.links.FleetNet` link table
  when part of a fleet (per-link faults + accounting);

plus the two protocol extensions this stack adds (ROADMAP "DCN
data-plane idempotence", "trace context across processes"):

- **frame sequencing + dedup**: ``send`` frames carry the client's
  per-flow monotonic ``seq`` in a v2 frame header; the receiver keeps a
  per-flow window of seqs that actually LANDED and drops replays, so a
  retried send after a connection loss cannot double-land a frame —
  while a retransmit of a frame that was genuinely lost (never landed)
  passes.  Dups count as ``dcn.frames.deduped``.
- **trace propagation**: control requests carry the client's active
  (trace, span); data frames carry the sender's — every daemon-side
  span joins the trace of the op that caused it, so one cross-node
  transfer is ONE trace across every process it touched.

Frame wire format (data plane):

    v1 (native-compatible): "DXF1" | u32 LE name_len | u64 LE
        payload_len | name | payload
    v2 (seq + meta):        "DXF2" | u32 LE name_len | u64 LE
        payload_len | u64 LE seq | u32 LE meta_len | name |
        meta (JSON: trace/span/src) | payload

Receivers accept both; v1 frames (the native daemon, local ``put``
staging) have no seq and bypass dedup — exactly what a restage wants.
"""

import base64
import json
import logging
import os
import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import trace

log = logging.getLogger(__name__)

VERSION = "pyxferd/2"
SOCKET_NAME = "xferd.sock"
READ_CAP = 512 << 10  # per-call read cap, like the native daemon
DEDUP_WINDOW = 64  # landed-seq memory per flow

_MAGIC_V1 = b"DXF1"
_MAGIC_V2 = b"DXF2"


class _Flow:
    __slots__ = ("owner", "peer", "buffer_bytes", "transferred",
                 "rx_bytes", "frame_bytes", "staged", "seen_seqs",
                 "max_seq")

    def __init__(self, owner: int, peer: str, buffer_bytes: int):
        self.owner = owner
        self.peer = peer
        self.buffer_bytes = buffer_bytes
        self.transferred = 0
        self.rx_bytes = 0
        self.frame_bytes = 0
        self.staged = b""
        self.seen_seqs = set()
        self.max_seq = 0


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("data connection closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def encode_frame(flow: str, payload: bytes, seq: Optional[int] = None,
                 meta: Optional[dict] = None) -> bytes:
    """Build a wire frame: v1 when seq is None (native-compatible), v2
    otherwise."""
    name = flow.encode()
    if seq is None:
        return (_MAGIC_V1 + struct.pack("<I", len(name))
                + struct.pack("<Q", len(payload)) + name + payload)
    meta_b = json.dumps(meta or {}).encode()
    return (_MAGIC_V2 + struct.pack("<I", len(name))
            + struct.pack("<Q", len(payload)) + struct.pack("<Q", seq)
            + struct.pack("<I", len(meta_b)) + name + meta_b + payload)


class PyXferd:
    """One emulated node's transfer daemon."""

    def __init__(self, uds_dir: str, node: str = "", net=None,
                 data_host: str = "127.0.0.1"):
        self.uds_dir = uds_dir
        self.node = node
        self.net = net
        self.data_host = data_host
        self.sock_path = os.path.join(uds_dir, SOCKET_NAME)
        self.data_port = 0
        self.generation = 0
        self._flows: Dict[str, _Flow] = {}
        self._total_transferred = 0
        self._unmatched = 0
        self._lock = threading.Lock()
        self._server: Optional[socket.socket] = None
        self._data_server: Optional[socket.socket] = None
        self._conns = set()
        self._stopping = threading.Event()
        # Test hook: {op: n} — process the next n requests of `op`, then
        # sever the connection BEFORE responding (a daemon that did the
        # work but whose answer was lost: the replay-dedup scenario).
        self._drop_response: Dict[str, int] = {}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "PyXferd":
        os.makedirs(self.uds_dir, exist_ok=True)
        if os.path.exists(self.sock_path):
            os.unlink(self.sock_path)  # the real daemon unlinks-then-binds
        self._stopping.clear()
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(self.sock_path)
        srv.listen(16)
        self._server = srv
        dsrv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        dsrv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        dsrv.bind((self.data_host, 0))
        dsrv.listen(16)
        self._data_server = dsrv
        self.data_port = dsrv.getsockname()[1]
        self.generation += 1
        for target, name in ((self._accept_loop, "pyxferd-ctl"),
                             (self._data_accept_loop, "pyxferd-data")):
            threading.Thread(target=target, name=f"{name}-{self.node}",
                             daemon=True).start()
        return self

    def stop(self, *, crash: bool = False) -> None:
        """``crash=True`` models SIGKILL: connections die, the socket
        path lingers until the next start() unlinks it."""
        self._stopping.set()
        for attr in ("_server", "_data_server"):
            srv = getattr(self, attr)
            if srv is not None:
                try:
                    try:
                        srv.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    srv.close()
                finally:
                    setattr(self, attr, None)
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()
        if not crash and os.path.exists(self.sock_path):
            os.unlink(self.sock_path)
        # Process death: all staging buffers, seqs windows, accounting
        # die with it — exactly what the restart chaos scenarios need.
        with self._lock:
            self._flows.clear()
            self._total_transferred = 0
            self._unmatched = 0

    # -- control plane -------------------------------------------------------

    def _accept_loop(self) -> None:
        srv = self._server
        while not self._stopping.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            if self._stopping.is_set():
                conn.close()
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name=f"pyxferd-conn-{self.node}",
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        conn_id = id(conn)
        with self._lock:
            self._conns.add(conn)
        rfile = conn.makefile("r")
        try:
            for line in rfile:
                req = None
                try:
                    req = json.loads(line)
                    resp = self._handle(conn_id, req)
                except (ValueError, KeyError, TypeError) as e:
                    resp = {"ok": False, "error": f"bad request: {e}"}
                op = req.get("op") if isinstance(req, dict) else None
                if op and self._drop_response.get(op, 0) > 0:
                    # The work is DONE; the answer is lost.  Sever so
                    # the client's retry exercises the dedup window.
                    self._drop_response[op] -= 1
                    try:
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    break
                try:
                    conn.sendall((json.dumps(resp) + "\n").encode())
                except OSError:
                    break
        finally:
            rfile.close()
            conn.close()
            with self._lock:
                self._conns.discard(conn)
            self._release_owned(conn_id)

    def drop_response_once(self, op: str, times: int = 1) -> None:
        """Arm the lost-response hook for the next ``times`` ``op``
        requests (chaos tests)."""
        self._drop_response[op] = self._drop_response.get(op, 0) + times

    def _release_owned(self, conn_id: int) -> None:
        with self._lock:
            for name in [n for n, f in self._flows.items()
                         if f.owner == conn_id]:
                del self._flows[name]

    def _handle(self, conn_id: int, req: dict) -> dict:
        op = req.get("op")
        # Join the client's trace: daemon-side work hangs off the
        # control round trip that asked for it, across the process
        # boundary.
        with trace.attach(req.get("trace"), req.get("span")):
            with trace.span("xferd.op", op=op, node=self.node):
                return self._dispatch(conn_id, op, req)

    def _dispatch(self, conn_id: int, op: str, req: dict) -> dict:
        if op == "version":
            return {"ok": True, "version": VERSION, "frame_version": 2}
        if op == "ping":
            return {"ok": True}
        if op == "data_port":
            return {"ok": True, "port": self.data_port}
        if op == "register_flow":
            flow = req["flow"]
            with self._lock:
                if flow in self._flows:
                    return {"ok": False,
                            "error": f"flow already exists: {flow}"}
                nbytes = int(req.get("bytes") or 4096)
                self._flows[flow] = _Flow(conn_id, req.get("peer", ""),
                                          nbytes)
            return {"ok": True, "flow": flow, "buffer_bytes": nbytes}
        if op == "record_transfer":
            nbytes = req.get("bytes")
            if not isinstance(nbytes, int) or nbytes < 0:
                return {"ok": False, "error": "invalid 'bytes'"}
            with self._lock:
                f = self._flows.get(req["flow"])
                if f is None:
                    return {"ok": False, "error": "unknown flow"}
                if f.owner != conn_id:
                    return {"ok": False,
                            "error": "flow owned by another client"}
                f.transferred += nbytes
                self._total_transferred += nbytes
                return {"ok": True, "flow_bytes": f.transferred}
        if op == "release_flow":
            with self._lock:
                f = self._flows.get(req["flow"])
                if f is None:
                    return {"ok": False, "error": "unknown flow"}
                if f.owner != conn_id:
                    return {"ok": False,
                            "error": "flow owned by another client"}
                del self._flows[req["flow"]]
            return {"ok": True}
        if op == "read":
            return self._read(req)
        if op == "send":
            return self._send(req)
        if op == "stats":
            return self._stats()
        return {"ok": False, "error": f"unknown op: {op}"}

    def _read(self, req: dict) -> dict:
        nbytes = int(req.get("bytes") or 0)
        offset = int(req.get("offset") or 0)
        with self._lock:
            f = self._flows.get(req["flow"])
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            staged = f.staged
            frame_bytes = f.frame_bytes
        if offset > len(staged):
            return {"ok": False,
                    "error": f"'offset' beyond staged data "
                             f"(frame_bytes={frame_bytes})"}
        chunk = staged[offset:offset + min(nbytes, READ_CAP)]
        return {"ok": True, "data": base64.b64encode(chunk).decode(),
                "frame_bytes": frame_bytes}

    def _send(self, req: dict) -> dict:
        flow = req["flow"]
        host = req.get("host", "127.0.0.1")
        port = int(req["port"])
        seq = req.get("seq")
        seq = int(seq) if seq is not None else None
        with self._lock:
            f = self._flows.get(flow)
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            payload = f.staged
        if not payload:
            return {"ok": False,
                    "error": f"nothing staged for flow {flow!r}"}
        nbytes = int(req.get("bytes") or len(payload))
        payload = payload[:nbytes]
        t0 = time.monotonic()
        with trace.span("xferd.send", histogram="xferd.send", flow=flow,
                        node=self.node, dst=f"{host}:{port}", seq=seq,
                        bytes=len(payload)) as span:
            meta = {"src": self.node}
            ctx = trace.context()
            if ctx is not None:
                meta.update(ctx)
            try:
                if self.net is not None:
                    # Fleet mode: EVERY frame goes through the link
                    # table — a port the fabric doesn't know (stale
                    # after a peer restart, node down) is a dead link,
                    # never a raw TCP dial around the fault surface.
                    verdict = self.net.deliver(self.node, host, port,
                                               flow, payload, seq, meta)
                    span.annotate(verdict=verdict)
                else:
                    self._tcp_send(host, port, flow, payload, seq, meta)
            except OSError as e:
                return {"ok": False, "error": f"send failed: {e}"}
        micros = max(1.0, (time.monotonic() - t0) * 1e6)
        with self._lock:
            f = self._flows.get(flow)
            if f is not None:
                f.transferred += len(payload)
                self._total_transferred += len(payload)
        return {"ok": True, "bytes": len(payload),
                "micros": round(micros, 1),
                "gbps": round(len(payload) * 8 / micros / 1e3, 3)}

    def _tcp_send(self, host: str, port: int, flow: str, payload: bytes,
                  seq: Optional[int], meta: dict) -> None:
        frame = encode_frame(flow, payload, seq, meta)
        with socket.create_connection((host, port), timeout=30) as s:
            s.sendall(frame)

    def _stats(self) -> dict:
        with self._lock:
            return {
                "ok": True,
                "active_flows": len(self._flows),
                "total_transferred": self._total_transferred,
                "unmatched_frames": self._unmatched,
                "generation": self.generation,
                "node": self.node,
                "flows": [
                    {"flow": name, "peer": f.peer,
                     "transferred": f.transferred,
                     "rx_bytes": f.rx_bytes,
                     "frame_bytes": f.frame_bytes,
                     "max_seq": f.max_seq}
                    for name, f in self._flows.items()
                ],
            }

    # -- data plane ----------------------------------------------------------

    def _data_accept_loop(self) -> None:
        srv = self._data_server
        while not self._stopping.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            if self._stopping.is_set():
                conn.close()
                return
            threading.Thread(target=self._serve_data_conn, args=(conn,),
                             name=f"pyxferd-dconn-{self.node}",
                             daemon=True).start()

    def _serve_data_conn(self, conn: socket.socket) -> None:
        with self._lock:
            self._conns.add(conn)
        try:
            while not self._stopping.is_set():
                try:
                    magic = _recv_exact(conn, 4)
                except (ConnectionError, OSError):
                    return
                try:
                    flow, payload, seq, meta = self._read_frame(conn, magic)
                except (ConnectionError, OSError, ValueError) as e:
                    log.error("bad data-plane frame: %s", e)
                    return
                self.land_frame(flow, payload, seq, meta)
        finally:
            conn.close()
            with self._lock:
                self._conns.discard(conn)

    def _read_frame(self, conn: socket.socket, magic: bytes
                    ) -> Tuple[str, bytes, Optional[int], dict]:
        if magic == _MAGIC_V1:
            name_len = struct.unpack("<I", _recv_exact(conn, 4))[0]
            payload_len = struct.unpack("<Q", _recv_exact(conn, 8))[0]
            seq, meta_len = None, 0
        elif magic == _MAGIC_V2:
            name_len = struct.unpack("<I", _recv_exact(conn, 4))[0]
            payload_len = struct.unpack("<Q", _recv_exact(conn, 8))[0]
            seq = struct.unpack("<Q", _recv_exact(conn, 8))[0]
            meta_len = struct.unpack("<I", _recv_exact(conn, 4))[0]
        else:
            raise ValueError(f"unknown frame magic {magic!r}")
        if name_len > 4096 or payload_len > (1 << 31) or meta_len > 65536:
            raise ValueError("frame header out of bounds")
        flow = _recv_exact(conn, name_len).decode()
        meta = {}
        if meta_len:
            try:
                meta = json.loads(_recv_exact(conn, meta_len))
            except ValueError:
                meta = {}
        payload = _recv_exact(conn, payload_len)
        return flow, payload, seq, meta

    def land_frame(self, flow: str, payload: bytes,
                   seq: Optional[int] = None, meta: Optional[dict] = None,
                   link: Optional[Tuple[str, str]] = None) -> str:
        """Land one frame into a flow's staging buffer.

        Returns "landed", "dup" (seq already landed — dropped without
        touching accounting, the exactly-once half of frame
        sequencing), or "unmatched" (no such flow registered here).
        Landing joins the SENDER's trace via the frame meta.
        """
        meta = meta or {}
        with trace.attach(meta.get("trace"), meta.get("span")):
            with trace.span("xferd.land", histogram="xferd.land",
                            flow=flow, node=self.node, seq=seq,
                            bytes=len(payload),
                            src=meta.get("src", "")) as span:
                with self._lock:
                    f = self._flows.get(flow)
                    if f is None:
                        self._unmatched += 1
                        span.annotate(verdict="unmatched")
                        return "unmatched"
                    if seq is not None:
                        if (seq in f.seen_seqs
                                or (f.max_seq - seq) >= DEDUP_WINDOW):
                            span.annotate(verdict="dup")
                            counters.inc("dcn.frames.deduped")
                            return "dup"
                        f.seen_seqs.add(seq)
                        f.max_seq = max(f.max_seq, seq)
                        # Bound the window: forget seqs that fell out.
                        if len(f.seen_seqs) > 2 * DEDUP_WINDOW:
                            floor = f.max_seq - DEDUP_WINDOW
                            f.seen_seqs = {s for s in f.seen_seqs
                                           if s >= floor}
                    f.staged = bytes(payload)
                    f.frame_bytes = len(payload)
                    f.rx_bytes += len(payload)
                span.annotate(verdict="landed")
                return "landed"
