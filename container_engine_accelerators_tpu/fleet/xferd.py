"""PyXferd — a protocol-faithful Python dcnxferd with a real data plane.

tests/xferd_stub.py models only the control plane: enough to kill and
restart "a daemon" under a single resilient client, useless for a
fleet.  PyXferd is the fleet's per-node daemon double, faithful to the
native daemon's whole contract (native/dcnxferd/dcnxferd.cc):

- newline-JSON control ops over a UDS (register/record/release/stats/
  ping/version/data_port/send/read), flows owned by their registering
  connection (buffer lifetime == connection lifetime, like rxdm);
- a real TCP data plane: ``put`` frames land over it byte-identical to
  the native daemon's framing, and ``send`` streams a staged flow to a
  peer daemon — directly over TCP when standalone (cross-process
  rigs), or through the :class:`~…fleet.links.FleetNet` link table
  when part of a fleet (per-link faults + accounting);

plus the two protocol extensions this stack adds (ROADMAP "DCN
data-plane idempotence", "trace context across processes"):

- **frame sequencing + dedup**: ``send`` frames carry the client's
  per-flow monotonic ``seq`` in a v2 frame header; the receiver keeps a
  per-flow window of seqs that actually LANDED and drops replays, so a
  retried send after a connection loss cannot double-land a frame —
  while a retransmit of a frame that was genuinely lost (never landed)
  passes.  Dups count as ``dcn.frames.deduped``.
- **trace propagation**: control requests carry the client's active
  (trace, span); data frames carry the sender's — every daemon-side
  span joins the trace of the op that caused it, so one cross-node
  transfer is ONE trace across every process it touched.

Plus the pipelined data-plane extensions (the chunked/striped DCN hot
path, ISSUE 4):

- **chunk assembly**: a v2 frame whose meta carries ``off``/``tot``
  (and a transfer id ``xid``) lands at its offset into a per-flow
  assembly buffer instead of replacing the staging buffer wholesale;
  the flow's completed frame becomes visible (``frame_bytes``) only
  once every byte of ``tot`` has landed.  Each chunk carries its own
  seq, so the dedup window gives exactly-once PER CHUNK.
- **offset send**: the ``send`` control op takes ``offset``/``bytes``
  and streams just that chunk to the peer (waiting briefly for the
  chunk to finish landing locally — this is what lets a client stage
  chunk *k+1* while chunk *k* is in flight).
- **wait op**: a blocking control op (``op:wait``) parks the
  connection thread on a condition variable until a flow's
  ``rx_bytes`` (mode ``rx``) or ``frame_bytes`` (mode ``frame``)
  reaches a target — no more 20 ms poll quantum on the land path.
- **stats flow filter**: ``stats`` with a ``flow`` key returns only
  that flow's entry (O(1) per poll instead of O(flows)).
- **binary read-back**: a ``DXR1`` request on the data plane streams
  staged bytes back raw — the striped reader's escape from base64 on
  the control socket.

Plus the zero-copy same-host staging lane (ISSUE 6; client half in
``parallel/dcn_shm.py`` + ``parallel/dcn_pipeline.py``):

- the daemon advertises ``shm``/``shm_dir``/``host_id`` in the
  ``version`` handshake and hands out per-flow ``mmap``-backed
  segment files under ``shm_dir`` (``shm_attach``); a same-host
  client (exact ``host_id`` match) writes payload memoryviews
  straight into the segment and declares them staged with one
  ``shm_commit`` control op — the whole-frame landing happens **in
  place**, no payload bytes on any socket;
- a flow with a segment keeps ALL its staging storage there: remote
  chunks landing over the data plane assemble directly into the
  mmap, so the local reader's ``shm_read`` op (which migrates any
  heap-staged content into the segment first) is a buffer reference,
  not a copy stream;
- control semantics are untouched: commits are seq-less staging
  (dedup-exempt, like seq-0 frames), sends/waits/stats behave
  identically whether the bytes arrived by socket or by segment, and
  a daemon restart takes the segments with it — clients re-probe the
  handshake on reconnect and transparently drop back to the socket
  lane.

Frame wire format (data plane):

    v1 (native-compatible): "DXF1" | u32 LE name_len | u64 LE
        payload_len | name | payload
    v2 (seq + meta):        "DXF2" | u32 LE name_len | u64 LE
        payload_len | u64 LE seq | u32 LE meta_len | name |
        meta (JSON: trace/span/src[/off/tot/xid]) | payload
    read request:           "DXR1" | u32 LE name_len | u64 LE offset |
        u64 LE nbytes | name  →  u64 LE avail | bytes

Receivers accept all three; v1 frames (the native daemon, local
``put`` staging) have no seq and bypass dedup — exactly what a restage
wants.  A v2 frame with seq 0 (the striped writer staging chunks into
its OWN daemon) also bypasses dedup: local staging is idempotent by
construction, and a restage must be able to overwrite.
"""

import base64
import hashlib
import json
import logging
import mmap
import os
import shutil
import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple

from container_engine_accelerators_tpu.analysis import lockwatch
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import timeseries, trace
from container_engine_accelerators_tpu.parallel import dcn_shm
from container_engine_accelerators_tpu.utils import netio

log = logging.getLogger(__name__)

VERSION = "pyxferd/3"
SOCKET_NAME = "xferd.sock"
READ_CAP = 512 << 10  # per-call read cap, like the native daemon
# Landed-seq memory per flow.  Sized so one full chunked transfer's
# worth of seqs (a replay re-sends ALL of them under the same numbers)
# fits with 2x headroom: the striped writer caps itself at
# MAX_CHUNKS_PER_TRANSFER = 128 chunks (parallel/dcn_pipeline.py, with
# a cross-test pinning 2 * cap <= window).
DEDUP_WINDOW = 256
# How long an offset-send waits for its chunk to finish landing through
# the local data plane (the stage->send pipeline race is normally
# microseconds; the bound only matters when staging genuinely died).
CHUNK_STAGE_WAIT_S = 5.0
# Per-call cap on the blocking wait op: the client re-issues slices, so
# a daemon thread is never parked longer than this on one request.
MAX_WAIT_SLICE_S = 30.0

# Link-shim latency cap, mirroring fleet.links.MAX_INJECT_LATENCY_S
# (deliberately duplicated — the daemon must stay importable without
# the link table): a typo'd delay models relative slowness, not WAN.
LINK_SHIM_MAX_LATENCY_S = 0.25

_MAGIC_V1 = b"DXF1"
_MAGIC_V2 = b"DXF2"
_MAGIC_READ = b"DXR1"

# Segment files are at least a page so a 1-byte flow still maps.
SHM_MIN_SEGMENT = 4096


class _Flow:
    __slots__ = ("owner", "peer", "buffer_bytes", "transferred",
                 "rx_bytes", "frame_bytes", "staged", "seen_seqs",
                 "max_seq", "asm_xid", "asm_total", "asm_buf",
                 "asm_chunks", "asm_seqs", "seg_path", "seg_map",
                 "seg_size")

    def __init__(self, owner: int, peer: str, buffer_bytes: int):
        self.owner = owner
        self.peer = peer
        self.buffer_bytes = buffer_bytes
        self.transferred = 0
        self.rx_bytes = 0
        self.frame_bytes = 0
        self.staged = b""
        self.seen_seqs = set()
        self.max_seq = 0
        # Chunk-assembly state (pipelined transfers): one in-progress
        # logical payload, keyed by the sender's transfer id.
        self.asm_xid = None
        self.asm_total = 0
        self.asm_buf = None  # bytearray(asm_total) while assembling
        self.asm_chunks: Dict[int, int] = {}  # landed off -> len
        self.asm_seqs = set()  # seqs whose bytes live in THIS assembly
        # Shared-memory segment (same-host zero-copy lane).  When set,
        # the flow's staging storage lives IN the mmap: ``staged`` and
        # ``asm_buf`` become memoryviews of ``seg_map``.
        self.seg_path: Optional[str] = None
        self.seg_map = None  # mmap.mmap while attached
        self.seg_size = 0

    def discard_assembly(self) -> None:
        """Drop the in-progress assembly AND un-see its seqs: a seq is
        only exactly-once while its bytes are retained — keeping seqs
        of discarded chunks would dedup-drop their retransmits and
        wedge the transfer."""
        self.seen_seqs -= self.asm_seqs
        self.asm_seqs = set()
        self.asm_xid = None
        self.asm_buf = None
        self.asm_chunks = {}

    def seg_view(self, nbytes: int) -> memoryview:
        """A writable view of the segment's first ``nbytes``."""
        return memoryview(self.seg_map)[:nbytes]

    def close_segment(self, unlink: bool = True) -> None:
        """Detach the flow's shm segment: drop view-backed staging (the
        bytes die with the flow/daemon, same as heap staging), close
        the mmap, and unlink the file unless this is a crash (SIGKILL
        leaves files behind; the next start() wipes the directory)."""
        path, m = self.seg_path, self.seg_map
        self.seg_path, self.seg_map, self.seg_size = None, None, 0
        if isinstance(self.staged, memoryview):
            self.staged = b""
            self.frame_bytes = 0
        if isinstance(self.asm_buf, memoryview):
            self.discard_assembly()
        if m is not None:
            try:
                m.close()
            except (BufferError, ValueError):
                pass  # an exported slice keeps it alive until GC
            timeseries.gauge_add("dcn.shm.segments", -1)
        if unlink and path:
            try:
                os.unlink(path)
            except OSError:
                pass

    def range_staged(self, offset: int, nbytes: int,
                     xid: Optional[str] = None) -> bool:
        """True when bytes [offset, offset+nbytes) are readable — from
        the completed frame, or covered by landed assembly chunks.

        With ``xid`` set (a chunked send), only bytes belonging to
        THAT transfer count: a stale completed frame from a previous
        transfer on a reused flow must make the send WAIT for the new
        staging, not silently re-send last transfer's bytes."""
        if (self.frame_bytes and offset + nbytes <= len(self.staged)
                and (xid is None or self.asm_xid == xid)):
            return True
        if self.asm_buf is None or (xid is not None
                                    and self.asm_xid != xid):
            return False
        pos = offset
        for off in sorted(self.asm_chunks):
            if pos >= offset + nbytes:
                break
            if off <= pos < off + self.asm_chunks[off]:
                pos = off + self.asm_chunks[off]
        return pos >= offset + nbytes

    def read_range(self, offset: int, nbytes: int,
                   xid: Optional[str] = None) -> bytes:
        if (self.frame_bytes and offset + nbytes <= len(self.staged)
                and (xid is None or self.asm_xid == xid)):
            # bytes() either way: a memoryview (shm-backed staging)
            # must not escape the lock — the segment can be remapped
            # or closed the moment the caller lets go.
            return bytes(self.staged[offset:offset + nbytes])
        return bytes(self.asm_buf[offset:offset + nbytes])


# Exact reads and capped, short-write-proof sends live in utils/netio
# (the rig's stack truncates very large single-syscall payloads).
_recv_exact = netio.recv_exact


def _set_nodelay(sock: socket.socket) -> None:
    """Chunked frames are header+payload pairs and DXR1 replies are
    header+data pairs: Nagle coalescing against delayed ACKs costs
    milliseconds per chunk, which is the whole pipelining budget."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass  # not a TCP socket (UDS in tests)


def encode_frame(flow: str, payload: bytes, seq: Optional[int] = None,
                 meta: Optional[dict] = None) -> bytes:
    """Build a wire frame: v1 when there is neither seq nor meta
    (native-compatible), v2 otherwise.  A v2 frame with meta but no seq
    carries seq 0 on the wire — "no dedup", the staging-chunk case."""
    if seq is None and meta is None:
        name = flow.encode()
        return (_MAGIC_V1 + struct.pack("<I", len(name))
                + struct.pack("<Q", len(payload)) + name + payload)
    return encode_frame_header(flow, len(payload), seq, meta) + payload


def encode_frame_header(flow: str, payload_len: int,
                        seq: Optional[int] = None,
                        meta: Optional[dict] = None) -> bytes:
    """The v2 frame minus its payload — senders pass the payload as a
    separate ``sendmsg`` buffer and skip one full-chunk copy."""
    name = flow.encode()
    meta_b = json.dumps(meta or {}).encode()
    return (_MAGIC_V2 + struct.pack("<I", len(name))
            + struct.pack("<Q", payload_len)
            + struct.pack("<Q", seq or 0)
            + struct.pack("<I", len(meta_b)) + name + meta_b)


def encode_read_request(flow: str, offset: int, nbytes: int) -> bytes:
    """Build a DXR1 data-plane read request (the striped reader's
    binary read-back; the daemon answers u64 LE length + raw bytes)."""
    name = flow.encode()
    return (_MAGIC_READ + struct.pack("<I", len(name))
            + struct.pack("<Q", offset) + struct.pack("<Q", nbytes)
            + name)


class _PeerConn:
    """One cached outbound data-plane stream.  Sends hold the lock for
    the whole frame so concurrent users can never interleave bytes."""

    def __init__(self):
        self.lock = threading.Lock()
        self.sock: Optional[socket.socket] = None

    def send_frame(self, host: str, port: int, parts) -> None:
        # Serializing the whole frame under the lock IS the contract
        # (concurrent stripes interleaving bytes would corrupt the
        # stream) — a deliberate blocking-under-lock, annotated so
        # `make race` books it under `allowed` instead of failing.
        with self.lock, lockwatch.blocking_ok(
                "xferd.peer: frames on one stream must not interleave"):
            if self.sock is None:
                s = socket.create_connection((host, port), timeout=30)
                _set_nodelay(s)
                self.sock = s
            try:
                netio.sendall_parts(self.sock, parts)
            except OSError:
                self.close_locked()
                raise

    def close_locked(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def close(self) -> None:
        with self.lock:
            self.close_locked()


class PyXferd:
    """One emulated node's transfer daemon."""

    def __init__(self, uds_dir: str, node: str = "", net=None,
                 data_host: str = "127.0.0.1",
                 shm: Optional[bool] = None,
                 host_id: Optional[str] = None):
        self.uds_dir = uds_dir
        self.node = node
        self.net = net
        self.data_host = data_host
        self.sock_path = os.path.join(uds_dir, SOCKET_NAME)
        # Zero-copy same-host lane: per-flow mmap segments under
        # shm_dir, advertised with this daemon's host identity so a
        # client can tell "same address" from "same machine".
        # ``shm``/``host_id`` overrides are the cross-host and
        # capability-less test handles.
        self.shm_enabled = (dcn_shm.shm_enabled() if shm is None
                            else bool(shm))
        self.shm_dir = os.path.join(uds_dir, "shm")
        self.host_id = host_id or dcn_shm.host_identity()
        self.data_port = 0
        self.generation = 0
        self._flows: Dict[str, _Flow] = {}
        self._total_transferred = 0
        self._unmatched = 0
        self._lock = threading.Lock()
        # Landing notifications: wait ops and offset-sends park here
        # until land_frame advances the flow they watch.
        self._landed = threading.Condition(self._lock)
        self._server: Optional[socket.socket] = None
        self._data_server: Optional[socket.socket] = None
        self._conns = set()
        # Persistent outbound data-plane connections, keyed by
        # (control conn, host, port): chunked sends reuse one TCP
        # stream per stripe instead of dialing per chunk, and distinct
        # stripes (distinct control connections) get distinct streams
        # — the FlexLink point of striping one logical transfer.
        self._peer_conns: Dict[tuple, "_PeerConn"] = {}
        self._stopping = threading.Event()
        # Test hook: {op: n} — process the next n requests of `op`, then
        # sever the connection BEFORE responding (a daemon that did the
        # work but whose answer was lost: the replay-dedup scenario).
        self._drop_response: Dict[str, int] = {}
        # Proc-mode link-fault shim (netem analog): per-destination
        # (host, port) fault state consulted by the SEND path when
        # there is no in-process fabric to interpose (net is None).
        # Armed over the worker RPC by the fleet controller, so
        # `sel<->sel` link faults work against real OS-process nodes
        # too.  Keyed by the peer's CURRENT data port: a respawned
        # peer binds a fresh port and starts with a clean link —
        # the same reset its flows and dedup windows get.
        self._link_faults: Dict[Tuple[str, int], dict] = {}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "PyXferd":
        os.makedirs(self.uds_dir, exist_ok=True)
        if os.path.exists(self.sock_path):
            os.unlink(self.sock_path)  # the real daemon unlinks-then-binds
        # Crash-lingering segment files belong to the dead incarnation;
        # wipe them the same way the socket path is unlinked.
        shutil.rmtree(self.shm_dir, ignore_errors=True)
        if self.shm_enabled:
            os.makedirs(self.shm_dir, exist_ok=True)
        self._stopping.clear()
        # A fresh incarnation starts with clean links, like its flows.
        with self._lock:
            self._link_faults.clear()
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(self.sock_path)
        srv.listen(16)
        self._server = srv
        dsrv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        dsrv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        dsrv.bind((self.data_host, 0))
        dsrv.listen(16)
        self._data_server = dsrv
        self.data_port = dsrv.getsockname()[1]
        self.generation += 1
        for target, name in ((self._accept_loop, "pyxferd-ctl"),
                             (self._data_accept_loop, "pyxferd-data")):
            threading.Thread(target=target, name=f"{name}-{self.node}",
                             daemon=True).start()
        return self

    def stop(self, *, crash: bool = False) -> None:
        """``crash=True`` models SIGKILL: connections die, the socket
        path lingers until the next start() unlinks it."""
        self._stopping.set()
        for attr in ("_server", "_data_server"):
            srv = getattr(self, attr)
            if srv is not None:
                try:
                    try:
                        srv.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    srv.close()
                finally:
                    setattr(self, attr, None)
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()
        if not crash and os.path.exists(self.sock_path):
            os.unlink(self.sock_path)
        # Process death: all staging buffers, seqs windows, accounting
        # die with it — exactly what the restart chaos scenarios need.
        # Segments go too: on a clean stop the files are unlinked, on a
        # crash they linger (like the socket path) until the next
        # start() wipes the directory — either way a client holding a
        # stale mapping writes into an orphaned inode the next daemon
        # can never see, which is why the client remaps per transfer.
        with self._lock:
            for f in self._flows.values():
                f.close_segment(unlink=not crash)
            self._flows.clear()
            self._total_transferred = 0
            self._unmatched = 0
            self._publish_flow_gauges_locked()
            self._landed.notify_all()  # unpark any blocked wait op
            peer_conns = list(self._peer_conns.values())
            self._peer_conns.clear()
        for pc in peer_conns:
            pc.close()

    # -- control plane -------------------------------------------------------

    def _accept_loop(self) -> None:
        srv = self._server
        while not self._stopping.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            if self._stopping.is_set():
                conn.close()
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name=f"pyxferd-conn-{self.node}",
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        conn_id = id(conn)
        with self._lock:
            self._conns.add(conn)
        rfile = conn.makefile("r")
        try:
            for line in rfile:
                req = None
                try:
                    req = json.loads(line)
                    resp = self._handle(conn_id, req)
                except (ValueError, KeyError, TypeError) as e:
                    resp = {"ok": False, "error": f"bad request: {e}"}
                op = req.get("op") if isinstance(req, dict) else None
                if op and self._drop_response.get(op, 0) > 0:
                    # The work is DONE; the answer is lost.  Sever so
                    # the client's retry exercises the dedup window.
                    self._drop_response[op] -= 1
                    try:
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    break
                try:
                    netio.sendall(conn,
                                  (json.dumps(resp) + "\n").encode())
                except OSError:
                    break
        finally:
            rfile.close()
            conn.close()
            with self._lock:
                self._conns.discard(conn)
            self._release_owned(conn_id)

    def drop_response_once(self, op: str, times: int = 1) -> None:
        """Arm the lost-response hook for the next ``times`` ``op``
        requests (chaos tests)."""
        self._drop_response[op] = self._drop_response.get(op, 0) + times

    # -- link-fault shim (proc-mode netem analog) ----------------------------

    def set_link_fault(self, host: str, port: int, action: str,
                       param: float = 0.0) -> int:
        """Arm one outbound link fault toward ``(host, port)`` —
        ``partition`` (sends fail like a null route), ``heal`` (clear
        everything), ``latency`` (per-frame one-way delay, seconds,
        capped), ``drop`` (eat the next ``param`` frames in flight:
        the sender believes they left, the peer never sees them).
        Consulted by the send path only when this daemon has no
        in-process fabric (``net is None``) — with a fabric the
        LinkTable is the single fault surface."""
        key = (host, int(port))
        with self._lock:
            st = self._link_faults.get(key)
            if st is None:
                st = self._link_faults[key] = {
                    "up": True, "latency_s": 0.0, "drop_next": 0}
            if action == "partition":
                st["up"] = False
            elif action == "heal":
                self._link_faults.pop(key, None)
            elif action == "latency":
                st["latency_s"] = min(max(float(param), 0.0),
                                      LINK_SHIM_MAX_LATENCY_S)
            elif action == "drop":
                st["drop_next"] += max(1, int(param or 1))
            else:
                raise ValueError(f"unknown link-fault action "
                                 f"{action!r}")
        log.warning("link shim: %s toward %s:%d armed on node %s",
                    action, host, port, self.node or "?")
        return 1

    def _shim_consult(self, host: str, port: int):
        """One frame's verdict from the shim: (action, delay_s) where
        action is None / "blocked" / "dropped".  The latency sleep
        happens in the CALLER, outside the lock."""
        with self._lock:
            st = self._link_faults.get((host, int(port)))
            if st is None:
                return None, 0.0
            if not st["up"]:
                return "blocked", 0.0
            if st["drop_next"] > 0:
                st["drop_next"] -= 1
                return "dropped", st["latency_s"]
            return None, st["latency_s"]

    def _publish_flow_gauges_locked(self) -> None:
        """Flow accounting as gauges (caller holds the lock): what the
        in-process aggregator reads via ``_stats()``, the process-mode
        HTTP aggregator scrapes as ``agent_gauge`` — same numbers,
        different transport."""
        timeseries.gauge("xferd.active_flows", float(len(self._flows)))
        timeseries.gauge("xferd.total_transferred",
                         float(self._total_transferred))

    def _release_owned(self, conn_id: int) -> None:
        with self._lock:
            for name in [n for n, f in self._flows.items()
                         if f.owner == conn_id]:
                self._flows[name].close_segment()
                del self._flows[name]
            self._publish_flow_gauges_locked()
            self._landed.notify_all()  # waiters re-check released flows
            stale = [k for k in self._peer_conns if k[0] == conn_id]
            conns = [self._peer_conns.pop(k) for k in stale]
        for pc in conns:
            pc.close()

    def _handle(self, conn_id: int, req: dict) -> dict:
        op = req.get("op")
        # Join the client's trace: daemon-side work hangs off the
        # control round trip that asked for it, across the process
        # boundary.
        with trace.attach(req.get("trace"), req.get("span")):
            with trace.span("xferd.op", op=op, node=self.node):
                return self._dispatch(conn_id, op, req)

    def _dispatch(self, conn_id: int, op: str, req: dict) -> dict:
        if op == "version":
            resp = {"ok": True, "version": VERSION, "frame_version": 2,
                    "pipeline": 1}
            if self.shm_enabled:
                # The zero-copy lane's capability triple: clients take
                # it only on an exact host_id match (boot identity —
                # same ADDRESS is not same MACHINE), and only if the
                # advertised segment paths actually map.
                resp.update(shm=1, shm_dir=self.shm_dir,
                            host_id=self.host_id)
            return resp
        if op == "ping":
            return {"ok": True}
        if op == "data_port":
            return {"ok": True, "port": self.data_port}
        if op == "register_flow":
            flow = req["flow"]
            with self._lock:
                if flow in self._flows:
                    return {"ok": False,
                            "error": f"flow already exists: {flow}"}
                nbytes = int(req.get("bytes") or 4096)
                self._flows[flow] = _Flow(conn_id, req.get("peer", ""),
                                          nbytes)
                self._publish_flow_gauges_locked()
            return {"ok": True, "flow": flow, "buffer_bytes": nbytes}
        if op == "record_transfer":
            nbytes = req.get("bytes")
            if not isinstance(nbytes, int) or nbytes < 0:
                return {"ok": False, "error": "invalid 'bytes'"}
            with self._lock:
                f = self._flows.get(req["flow"])
                if f is None:
                    return {"ok": False, "error": "unknown flow"}
                if f.owner != conn_id:
                    return {"ok": False,
                            "error": "flow owned by another client"}
                f.transferred += nbytes
                self._total_transferred += nbytes
                self._publish_flow_gauges_locked()
                return {"ok": True, "flow_bytes": f.transferred}
        if op == "release_flow":
            with self._lock:
                f = self._flows.get(req["flow"])
                if f is None:
                    return {"ok": False, "error": "unknown flow"}
                if f.owner != conn_id:
                    return {"ok": False,
                            "error": "flow owned by another client"}
                f.close_segment()
                del self._flows[req["flow"]]
                self._publish_flow_gauges_locked()
            return {"ok": True}
        if op == "read":
            return self._read(req)
        if op == "send":
            return self._send(conn_id, req)
        if op == "wait":
            return self._wait(req)
        if op == "stats":
            return self._stats(req.get("flow"))
        if op == "shm_attach":
            return self._shm_attach(req)
        if op == "shm_commit":
            return self._shm_commit(req)
        if op == "shm_read":
            return self._shm_read(req)
        return {"ok": False, "error": f"unknown op: {op}"}

    def _wait(self, req: dict) -> dict:
        """Blocking wait: park this connection's thread until the flow
        reaches ``bytes`` of rx (mode ``rx``) or a completed frame of
        at least ``bytes`` (mode ``frame``), or the slice times out.
        The client loops slices against its own deadline, so a daemon
        thread is never held hostage by a dead client's deadline."""
        flow = req["flow"]
        nbytes = int(req.get("bytes") or 0)
        mode = req.get("mode", "rx")
        if mode not in ("rx", "frame"):
            return {"ok": False, "error": f"unknown wait mode: {mode}"}
        timeout_ms = req.get("timeout_ms")
        if timeout_ms is None:
            timeout_ms = 1000
        timeout_s = min(max(float(timeout_ms), 0.0) / 1e3,
                        MAX_WAIT_SLICE_S)

        def done() -> bool:
            f = self._flows.get(flow)
            if f is None:
                return True  # released/never registered: report, don't hang
            have = f.frame_bytes if mode == "frame" else f.rx_bytes
            return have >= nbytes

        with self._landed:
            reached = self._landed.wait_for(done, timeout=timeout_s)
            f = self._flows.get(flow)
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            return {"ok": True, "done": bool(reached),
                    "rx_bytes": f.rx_bytes, "frame_bytes": f.frame_bytes}

    def _read(self, req: dict) -> dict:
        nbytes = int(req.get("bytes") or 0)
        offset = int(req.get("offset") or 0)
        with self._lock:
            f = self._flows.get(req["flow"])
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            frame_bytes = f.frame_bytes
            if offset > len(f.staged):
                return {"ok": False,
                        "error": f"'offset' beyond staged data "
                                 f"(frame_bytes={frame_bytes})"}
            # Copy under the lock: shm-backed staging is a memoryview
            # whose mapping must not outlive this critical section.
            chunk = bytes(f.staged[offset:offset + min(nbytes, READ_CAP)])
        return {"ok": True, "data": base64.b64encode(chunk).decode(),
                "frame_bytes": frame_bytes}

    def _send(self, conn_id: int, req: dict) -> dict:
        flow = req["flow"]
        host = req.get("host", "127.0.0.1")
        port = int(req["port"])
        seq = req.get("seq")
        seq = int(seq) if seq is not None else None
        offset = req.get("offset")
        if offset is None:
            with self._lock:
                f = self._flows.get(flow)
                if f is None:
                    return {"ok": False, "error": "unknown flow"}
                # bytes() under the lock: shm-backed staging is a view
                # of a mapping that may be remapped once we let go.
                nbytes = int(req.get("bytes") or len(f.staged))
                payload = bytes(f.staged[:nbytes])
            if not payload:
                return {"ok": False,
                        "error": f"nothing staged for flow {flow!r}"}
            meta_extra = {}
        else:
            # Chunked send: stream staged[offset:offset+bytes] as one
            # chunk frame.  The chunk may still be in flight on the
            # local data plane (the stage->send pipeline), so wait
            # briefly for it to land rather than racing it.
            offset = int(offset)
            nbytes = int(req.get("bytes") or 0)
            if offset < 0 or nbytes <= 0:
                return {"ok": False,
                        "error": "chunked send needs offset >= 0 and "
                                 "bytes > 0"}
            stage_wait_s = min(
                float(req.get("stage_wait_ms")
                      or CHUNK_STAGE_WAIT_S * 1e3) / 1e3,
                CHUNK_STAGE_WAIT_S,
            )
            xid = req.get("xid") or ""
            with self._landed:
                staged = self._landed.wait_for(
                    lambda: (self._flows.get(flow) is None
                             or self._flows[flow].range_staged(
                                 offset, nbytes, xid)),
                    timeout=stage_wait_s,
                )
                f = self._flows.get(flow)
                if f is None:
                    return {"ok": False, "error": "unknown flow"}
                if not staged:
                    return {"ok": False,
                            "error": f"chunk not staged for flow "
                                     f"{flow!r} [{offset}:"
                                     f"{offset + nbytes}]"}
                payload = f.read_range(offset, nbytes, xid)
            meta_extra = {
                "off": offset,
                "tot": int(req.get("total") or 0),
                "xid": xid,
            }
        # Proc-mode link shim: when there is no in-process fabric, the
        # armed per-destination faults interpose here — the one point
        # every outbound frame passes, like FleetNet.deliver.
        shim = None
        if self.net is None:
            shim, shim_delay_s = self._shim_consult(host, port)
            if shim == "blocked":
                counters.inc("fleet.link.blocked")
                return {"ok": False,
                        "error": f"send failed: link to {host}:{port} "
                                 f"partitioned (injected)"}
            if shim_delay_s > 0:
                time.sleep(shim_delay_s)
        t0 = time.monotonic()
        with trace.span("xferd.send", histogram="xferd.send", flow=flow,
                        node=self.node, dst=f"{host}:{port}", seq=seq,
                        bytes=len(payload)) as span:
            meta = {"src": self.node}
            meta.update(meta_extra)
            ctx = trace.context()
            if ctx is not None:
                meta.update(ctx)
            verdict = None
            try:
                if shim == "dropped":
                    # Loss injection: the sender believes the frame
                    # left; the peer never sees it.  The verdict lets
                    # the striped writer retransmit without a timeout,
                    # exactly like the fleet fabric's answer.
                    counters.inc("fleet.link.dropped")
                    verdict = "dropped"
                    span.annotate(verdict=verdict)
                elif self.net is not None:
                    # Fleet mode: EVERY frame goes through the link
                    # table — a port the fabric doesn't know (stale
                    # after a peer restart, node down) is a dead link,
                    # never a raw TCP dial around the fault surface.
                    verdict = self.net.deliver(self.node, host, port,
                                               flow, payload, seq, meta)
                    span.annotate(verdict=verdict)
                elif offset is None:
                    # Whole-payload send: a fresh dial per send, so a
                    # dead peer surfaces as an immediate error (the
                    # serial path's error contract).
                    self._tcp_send(host, port, flow, payload, seq, meta)
                else:
                    # Chunked send: a persistent stream per (control
                    # connection, peer) — dialing per chunk costs more
                    # than the chunk.  A frame lost in a stale stream's
                    # buffer when the peer dies is re-sent by the
                    # striped writer's retry round (same seq, dedup).
                    self._peer_conn(conn_id, host, port).send_frame(
                        host, port,
                        [encode_frame_header(flow, len(payload), seq,
                                             meta), payload],
                    )
            except OSError as e:
                return {"ok": False, "error": f"send failed: {e}"}
        micros = max(1.0, (time.monotonic() - t0) * 1e6)
        timeseries.record("xferd.tx.bytes", len(payload))
        with self._lock:
            f = self._flows.get(flow)
            if f is not None:
                f.transferred += len(payload)
                self._total_transferred += len(payload)
                self._publish_flow_gauges_locked()
        resp = {"ok": True, "bytes": len(payload),
                "micros": round(micros, 1),
                "gbps": round(len(payload) * 8 / micros / 1e3, 3)}
        if verdict is not None:
            # The striped sender uses this to retransmit chunks the
            # link ate without waiting for a timeout.
            resp["verdict"] = verdict
        return resp

    def _tcp_send(self, host: str, port: int, flow: str, payload: bytes,
                  seq: Optional[int], meta: dict) -> None:
        with socket.create_connection((host, port), timeout=30) as s:
            _set_nodelay(s)
            netio.sendall_parts(
                s, (encode_frame_header(flow, len(payload), seq, meta),
                    payload))

    def _peer_conn(self, conn_id: int, host: str, port: int) -> _PeerConn:
        key = (conn_id, host, port)
        with self._lock:
            pc = self._peer_conns.get(key)
            if pc is None:
                pc = self._peer_conns[key] = _PeerConn()
            return pc

    def _stats(self, flow: Optional[str] = None) -> dict:
        """Daemon stats.  With ``flow`` set, the flows list holds just
        that flow's entry (one dict lookup) — the rx-wait poll path
        stops paying O(flows) per poll."""
        with self._lock:
            if flow is not None:
                f = self._flows.get(flow)
                items = [(flow, f)] if f is not None else []
            else:
                items = list(self._flows.items())
            return {
                "ok": True,
                "active_flows": len(self._flows),
                "total_transferred": self._total_transferred,
                "unmatched_frames": self._unmatched,
                "generation": self.generation,
                "node": self.node,
                "flows": [
                    {"flow": name, "peer": f.peer,
                     "transferred": f.transferred,
                     "rx_bytes": f.rx_bytes,
                     "frame_bytes": f.frame_bytes,
                     "max_seq": f.max_seq,
                     "shm": f.seg_map is not None}
                    for name, f in items
                ],
            }

    # -- shm lane (zero-copy same-host staging) ------------------------------

    def _ensure_segment_locked(self, flow: str, f: _Flow,
                               nbytes: int) -> None:
        """Create (or grow) ``flow``'s mmap segment to >= ``nbytes``
        and move every live staging buffer into the current mapping —
        heap content is copied once, old-mapping views are repointed
        (same inode, same bytes).  After this, "the flow has a
        segment" always implies "the flow's bytes are readable through
        it".  Caller holds the lock; raises ``OSError`` on filesystem
        trouble (the client's fallback signal)."""
        need = max(int(nbytes), SHM_MIN_SEGMENT)
        old_map = None
        remapped = False
        if f.seg_map is None or f.seg_size < need:
            os.makedirs(self.shm_dir, exist_ok=True)
            path = f.seg_path or os.path.join(
                self.shm_dir,
                hashlib.sha1(flow.encode()).hexdigest()[:16] + ".seg")
            size = max(need, f.seg_size)
            fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
            try:
                os.ftruncate(fd, size)
                new_map = mmap.mmap(fd, size)
            finally:
                os.close(fd)
            if f.seg_map is None:
                timeseries.gauge_add("dcn.shm.segments", 1)
            old_map = f.seg_map
            f.seg_map, f.seg_path, f.seg_size = new_map, path, size
            remapped = True
        view = memoryview(f.seg_map)
        if f.asm_buf is not None and f.asm_total <= f.seg_size:
            staged_is_asm = f.staged is f.asm_buf
            if isinstance(f.asm_buf, bytearray):
                view[:f.asm_total] = f.asm_buf  # heap -> segment, once
                f.asm_buf = view[:f.asm_total]
            elif remapped:  # old-mapping view: repoint, no copy
                f.asm_buf = view[:f.asm_total]
            if staged_is_asm:
                f.staged = f.asm_buf
        if isinstance(f.staged, (bytes, bytearray)) and f.frame_bytes \
                and f.frame_bytes <= f.seg_size:
            view[:f.frame_bytes] = f.staged
            f.staged = view[:f.frame_bytes]
        elif (isinstance(f.staged, memoryview) and remapped
                and f.staged is not f.asm_buf):
            f.staged = view[:len(f.staged)]
        if old_map is not None:
            try:
                old_map.close()
            except (BufferError, ValueError):
                pass  # an exported slice keeps it alive until GC

    def _shm_attach(self, req: dict) -> dict:
        """Hand the client a per-flow segment (path + mapped size).
        Idempotent; growing re-truncates the same inode so existing
        content — and existing client mappings of the old range —
        stay valid."""
        if not self.shm_enabled:
            return {"ok": False, "error": "shm lane disabled"}
        flow = req["flow"]
        nbytes = int(req.get("bytes") or 0)
        if nbytes < 0:
            return {"ok": False, "error": "invalid 'bytes'"}
        with self._lock:
            f = self._flows.get(flow)
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            try:
                self._ensure_segment_locked(flow, f, nbytes)
            except OSError as e:
                return {"ok": False, "error": f"shm attach failed: {e}"}
            return {"ok": True, "path": f.seg_path,
                    "bytes": f.seg_size, "frame_bytes": f.frame_bytes}

    def _shm_commit(self, req: dict) -> dict:
        """Declare ``[0, bytes)`` of the flow's segment a completed
        staged frame — the zero-copy analog of a whole-payload ``put``.
        The landing happens IN PLACE: no payload bytes cross a socket,
        but the bookkeeping (rx accounting, wait wakeups, assembly
        invalidation) is the same ``land_frame`` every other staging
        path uses.  Commits are seq-less staging, dedup-exempt and
        idempotent by construction — a restage after a failed round
        simply commits again."""
        if not self.shm_enabled:
            return {"ok": False, "error": "shm lane disabled"}
        flow = req["flow"]
        nbytes = int(req.get("bytes") or 0)
        xid = req.get("xid") or ""
        if nbytes <= 0:
            return {"ok": False, "error": "shm commit needs bytes > 0"}
        with self._lock:
            f = self._flows.get(flow)
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            if f.seg_map is None or f.seg_size < nbytes:
                return {"ok": False,
                        "error": "no shm segment attached for "
                                 f"{nbytes} bytes; shm_attach first"}
            view = f.seg_view(nbytes)
        verdict = self.land_frame(flow, view, None,
                                  {"xid": xid} if xid else {},
                                  in_place=True)
        if verdict != "landed":
            return {"ok": False,
                    "error": f"shm commit not landed: {verdict}"}
        counters.inc("dcn.shm.commits")
        return {"ok": True, "bytes": nbytes}

    def _shm_read(self, req: dict) -> dict:
        """Make the flow's completed frame readable through its
        segment and say where: frames that landed into heap buffers
        (the flow was never attached, or the segment was too small)
        are migrated in with one copy — still one copy fewer than any
        socket read-back.  The client maps the returned path and
        slices; no payload bytes cross the control socket."""
        if not self.shm_enabled:
            return {"ok": False, "error": "shm lane disabled"}
        flow = req["flow"]
        nbytes = int(req.get("bytes") or 0)
        with self._lock:
            f = self._flows.get(flow)
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            if not f.frame_bytes:
                return {"ok": False,
                        "error": "no completed frame staged"}
            try:
                self._ensure_segment_locked(
                    flow, f, max(nbytes, f.frame_bytes))
            except OSError as e:
                return {"ok": False, "error": f"shm read failed: {e}"}
            return {"ok": True, "path": f.seg_path,
                    "bytes": f.seg_size, "frame_bytes": f.frame_bytes}

    # -- data plane ----------------------------------------------------------

    def _data_accept_loop(self) -> None:
        srv = self._data_server
        while not self._stopping.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            if self._stopping.is_set():
                conn.close()
                return
            threading.Thread(target=self._serve_data_conn, args=(conn,),
                             name=f"pyxferd-dconn-{self.node}",
                             daemon=True).start()

    def _serve_data_conn(self, conn: socket.socket) -> None:
        _set_nodelay(conn)
        with self._lock:
            self._conns.add(conn)
        try:
            while not self._stopping.is_set():
                try:
                    magic = _recv_exact(conn, 4)
                except (ConnectionError, OSError):
                    return
                if magic == _MAGIC_READ:
                    if not self._serve_data_read(conn):
                        return
                    continue
                try:
                    flow, payload, seq, meta = self._read_frame(conn, magic)
                except (ConnectionError, OSError, ValueError) as e:
                    log.error("bad data-plane frame: %s", e)
                    return
                self.land_frame(flow, payload, seq, meta)
        finally:
            conn.close()
            with self._lock:
                self._conns.discard(conn)

    def _serve_data_read(self, conn: socket.socket) -> bool:
        """Answer one DXR1 read request: u64 LE length + raw staged
        bytes (bounded by the last COMPLETED frame — an assembling flow
        reads empty, exactly like the control-plane read's contract).
        Raw TCP instead of base64-over-JSON is what makes the striped
        reader's read-back leg cheap.  Returns False on a dead conn."""
        try:
            name_len = struct.unpack("<I", _recv_exact(conn, 4))[0]
            offset = struct.unpack("<Q", _recv_exact(conn, 8))[0]
            nbytes = struct.unpack("<Q", _recv_exact(conn, 8))[0]
            if name_len > 4096 or nbytes > (1 << 31):
                raise ValueError("read request out of bounds")
            flow = _recv_exact(conn, name_len).decode()
        except (ConnectionError, OSError, ValueError) as e:
            log.error("bad data-plane read request: %s", e)
            return False
        with self._lock:
            f = self._flows.get(flow)
            if f is None or not f.frame_bytes:
                data = b""
            else:
                end = min(offset + nbytes, f.frame_bytes,
                          len(f.staged))
                # bytes() under the lock — shm staging is a view.
                data = bytes(f.staged[offset:end]) if offset < end \
                    else b""
        try:
            netio.sendall_parts(conn, (struct.pack("<Q", len(data)),
                                       data))
        except OSError:
            return False
        return True

    def _read_frame(self, conn: socket.socket, magic: bytes
                    ) -> Tuple[str, bytes, Optional[int], dict]:
        if magic == _MAGIC_V1:
            name_len, payload_len = struct.unpack(
                "<IQ", _recv_exact(conn, 12))
            seq, meta_len = None, 0
        elif magic == _MAGIC_V2:
            name_len, payload_len, seq, meta_len = struct.unpack(
                "<IQQI", _recv_exact(conn, 24))
        else:
            raise ValueError(f"unknown frame magic {magic!r}")
        if name_len > 4096 or payload_len > (1 << 31) or meta_len > 65536:
            raise ValueError("frame header out of bounds")
        flow = _recv_exact(conn, name_len).decode()
        meta = {}
        if meta_len:
            try:
                meta = json.loads(_recv_exact(conn, meta_len))
            except ValueError:
                meta = {}
        payload = _recv_exact(conn, payload_len)
        return flow, payload, seq, meta

    def land_frame(self, flow: str, payload,
                   seq: Optional[int] = None, meta: Optional[dict] = None,
                   link: Optional[Tuple[str, str]] = None,
                   in_place: bool = False) -> str:
        """Land one frame into a flow's staging buffer.

        Returns "landed", "dup" (seq already landed — dropped without
        touching accounting, the exactly-once half of frame
        sequencing), "rejected" (malformed chunk geometry), or
        "unmatched" (no such flow registered here).  A frame whose meta
        carries ``off``/``tot`` is a CHUNK: it lands at its offset into
        the flow's assembly buffer, and the completed frame becomes
        visible only once every byte of ``tot`` has landed — a reader
        can never observe a half-assembled payload.  Seq 0 (or a v1
        frame) bypasses dedup: that is local staging, idempotent by
        construction.  Landing joins the SENDER's trace via the frame
        meta.

        ``in_place=True`` (the shm commit path) means the payload
        bytes already live in the flow's segment: the landing does all
        the bookkeeping — accounting, wait wakeups, assembly
        invalidation — without ever copying the payload.
        """
        meta = meta or {}
        with trace.attach(meta.get("trace"), meta.get("span")):
            with trace.span("xferd.land", histogram="xferd.land",
                            flow=flow, node=self.node, seq=seq,
                            bytes=len(payload),
                            src=meta.get("src", "")) as span:
                with self._lock:
                    f = self._flows.get(flow)
                    if f is None:
                        self._unmatched += 1
                        span.annotate(verdict="unmatched")
                        return "unmatched"
                    if seq:  # seq 0 == staging chunk, dedup-exempt
                        if (seq in f.seen_seqs
                                or (f.max_seq - seq) >= DEDUP_WINDOW):
                            span.annotate(verdict="dup")
                            counters.inc("dcn.frames.deduped")
                            return "dup"
                        f.seen_seqs.add(seq)
                        f.max_seq = max(f.max_seq, seq)
                        # Bound the window: forget seqs that fell out.
                        if len(f.seen_seqs) > 2 * DEDUP_WINDOW:
                            floor = f.max_seq - DEDUP_WINDOW
                            f.seen_seqs = {s for s in f.seen_seqs
                                           if s >= floor}
                    verdict = self._land_locked(flow, f, payload,
                                                meta, seq, in_place)
                    self._landed.notify_all()
                span.annotate(verdict=verdict)
                if verdict == "landed":
                    # Goodput = bytes that landed USEFULLY: dups and
                    # link-eaten frames never reach here.  A frame is
                    # remote-origin when it rode the fleet fabric or
                    # carries a sender's node stamp; everything else is
                    # local staging, tracked as its own series so the
                    # stage rate never inflates goodput.
                    remote = link is not None or bool(meta.get("src"))
                    if remote:
                        # Cumulative landed-frame count: the scrapeable
                        # denominator for fleet dedup/retransmit ratios
                        # when there is no link table to read (the
                        # process-mode aggregator's HTTP path).
                        counters.inc("xferd.frames.landed")
                        timeseries.record("xferd.rx.bytes", len(payload))
                        timeseries.record(f"goodput.flow.{flow}",
                                          len(payload))
                        if self.node:
                            timeseries.record(
                                f"goodput.node.{self.node}", len(payload))
                        if link is not None:
                            timeseries.record(
                                f"goodput.link.{link[0]}->{link[1]}",
                                len(payload))
                    else:
                        timeseries.record("xferd.stage.bytes",
                                          len(payload))
                return verdict

    def _land_locked(self, flow: str, f: _Flow, payload,
                     meta: dict, seq, in_place: bool = False) -> str:
        """Write one (deduped) frame into flow state; caller holds the
        lock."""
        off = meta.get("off")
        if off is None:
            # Whole-payload frame: replaces staging wholesale and
            # cancels any in-progress assembly (the serial fallback
            # after a pipelined attempt must win outright).
            if in_place:
                # shm commit: the bytes are already in the segment.
                # Re-take the view under THIS lock hold — the segment
                # could have been remapped since the caller sliced it.
                if f.seg_map is None or f.seg_size < len(payload):
                    return "rejected"
                f.staged = f.seg_view(len(payload))
            else:
                f.staged = bytes(payload)
            f.frame_bytes = len(payload)
            f.rx_bytes += len(payload)
            f.discard_assembly()
            if in_place:
                # Stamp the committing transfer's xid so offset-sends
                # of the same transfer match this frame (the sender's
                # stale-frame guard on reused flows).
                f.asm_xid = meta.get("xid") or None
                f.asm_total = len(payload)
            return "landed"
        off = int(off)
        tot = int(meta.get("tot") or 0)
        xid = meta.get("xid") or ""
        if tot <= 0 or off < 0 or off + len(payload) > tot:
            counters.inc("dcn.chunks.rejected")
            log.error("rejecting chunk with bad geometry: flow=%s "
                      "off=%d len=%d tot=%d", flow, off,
                      len(payload), tot)
            return "rejected"
        if f.asm_xid != xid or f.asm_total != tot or f.asm_buf is None:
            # First chunk of a new logical transfer (or a retry under a
            # fresh xid): discard the old assembly — un-seeing its seqs
            # so that retransmits of the discarded bytes can land again
            # (a stale straggler frame must not be able to wedge the
            # live transfer) — and start clean.  The completed frame is
            # invalidated too: on a reused flow, a reader waiting for
            # THIS transfer must block until it assembles, never be
            # satisfied by last transfer's bytes.
            f.discard_assembly()
            f.staged = b""
            f.frame_bytes = 0
            f.asm_xid = xid
            f.asm_total = tot
            if f.seg_map is not None and f.seg_size >= tot:
                # shm-attached flow: assemble straight into the mmap,
                # so the local reader's shm_read is a buffer reference
                # with no migration copy.
                f.asm_buf = f.seg_view(tot)
            else:
                f.asm_buf = bytearray(tot)
        f.asm_buf[off:off + len(payload)] = payload
        f.asm_chunks[off] = len(payload)
        if seq:
            f.asm_seqs.add(seq)
        f.rx_bytes += len(payload)
        counters.inc("dcn.chunks.landed")
        if (f.range_staged(0, tot, xid)
                and f.staged is not f.asm_buf):
            # Completion = every byte of [0, tot) covered by landed
            # chunks (interval walk, not a length sum: overlapping
            # chunks from an off-grid sender must not mark a gapped
            # buffer complete).  Adopt the assembly buffer as the
            # completed frame without a copy; a same-xid restage keeps
            # writing into it (same bytes), a new xid starts a fresh
            # buffer.  The identity check makes completion fire once
            # per assembly, not once per straggler/replayed chunk
            # after completion.
            f.staged = f.asm_buf
            f.frame_bytes = tot
            counters.inc("dcn.chunks.assembled")
        return "landed"
