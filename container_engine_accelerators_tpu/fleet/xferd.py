"""PyXferd — a protocol-faithful Python dcnxferd with a real data plane.

tests/xferd_stub.py models only the control plane: enough to kill and
restart "a daemon" under a single resilient client, useless for a
fleet.  PyXferd is the fleet's per-node daemon double, faithful to the
native daemon's whole contract (native/dcnxferd/dcnxferd.cc):

- newline-JSON control ops over a UDS (register/record/release/stats/
  ping/version/data_port/send/read), flows owned by their registering
  connection (buffer lifetime == connection lifetime, like rxdm);
- a real TCP data plane: ``put`` frames land over it byte-identical to
  the native daemon's framing, and ``send`` streams a staged flow to a
  peer daemon — directly over TCP when standalone (cross-process
  rigs), or through the :class:`~…fleet.links.FleetNet` link table
  when part of a fleet (per-link faults + accounting);

plus the two protocol extensions this stack adds (ROADMAP "DCN
data-plane idempotence", "trace context across processes"):

- **frame sequencing + dedup**: ``send`` frames carry the client's
  per-flow monotonic ``seq`` in a v2 frame header; the receiver keeps a
  per-flow window of seqs that actually LANDED and drops replays, so a
  retried send after a connection loss cannot double-land a frame —
  while a retransmit of a frame that was genuinely lost (never landed)
  passes.  Dups count as ``dcn.frames.deduped``.
- **trace propagation**: control requests carry the client's active
  (trace, span); data frames carry the sender's — every daemon-side
  span joins the trace of the op that caused it, so one cross-node
  transfer is ONE trace across every process it touched.

Plus the pipelined data-plane extensions (the chunked/striped DCN hot
path, ISSUE 4):

- **chunk assembly**: a v2 frame whose meta carries ``off``/``tot``
  (and a transfer id ``xid``) lands at its offset into a per-flow
  assembly buffer instead of replacing the staging buffer wholesale;
  the flow's completed frame becomes visible (``frame_bytes``) only
  once every byte of ``tot`` has landed.  Each chunk carries its own
  seq, so the dedup window gives exactly-once PER CHUNK.
- **offset send**: the ``send`` control op takes ``offset``/``bytes``
  and streams just that chunk to the peer (waiting briefly for the
  chunk to finish landing locally — this is what lets a client stage
  chunk *k+1* while chunk *k* is in flight).
- **wait op**: a blocking control op (``op:wait``) parks the
  connection thread on a condition variable until a flow's
  ``rx_bytes`` (mode ``rx``) or ``frame_bytes`` (mode ``frame``)
  reaches a target — no more 20 ms poll quantum on the land path.
- **stats flow filter**: ``stats`` with a ``flow`` key returns only
  that flow's entry (O(1) per poll instead of O(flows)).
- **binary read-back**: a ``DXR1`` request on the data plane streams
  staged bytes back raw — the striped reader's escape from base64 on
  the control socket.

Plus the zero-copy same-host staging lane (ISSUE 6; client half in
``parallel/dcn_shm.py`` + ``parallel/dcn_pipeline.py``):

- the daemon advertises ``shm``/``shm_dir``/``host_id`` in the
  ``version`` handshake and hands out per-flow ``mmap``-backed
  segment files under ``shm_dir`` (``shm_attach``); a same-host
  client (exact ``host_id`` match) writes payload memoryviews
  straight into the segment and declares them staged with one
  ``shm_commit`` control op — the whole-frame landing happens **in
  place**, no payload bytes on any socket;
- a flow with a segment keeps ALL its staging storage there: remote
  chunks landing over the data plane assemble directly into the
  mmap, so the local reader's ``shm_read`` op (which migrates any
  heap-staged content into the segment first) is a buffer reference,
  not a copy stream;
- control semantics are untouched: commits are seq-less staging
  (dedup-exempt, like seq-0 frames), sends/waits/stats behave
  identically whether the bytes arrived by socket or by segment, and
  a daemon restart takes the segments with it — clients re-probe the
  handshake on reconnect and transparently drop back to the socket
  lane.

Plus the memcpy-speed same-host plane (ISSUE 13):

- **recv-into-mmap**: a chunk frame's payload is received DIRECTLY
  into the flow's assembly buffer at its offset — a segment view for
  shm-attached flows, the heap assembly otherwise — deleting the
  per-chunk heap bounce the old read-then-copy path paid.  Dedup is
  pre-checked before the receive and re-checked (and only then
  marked) at landing; a torn receive (connection died mid-chunk)
  leaves the chunk unrecorded, so partial-assembly invisibility holds
  byte-for-byte (``dcn.chunks.torn``); a landing whose assembly was
  reset mid-receive drops instead of corrupting the live transfer
  (``dcn.chunks.stale_drop``, guarded by a per-assembly generation).
- **daemon↔daemon shm (the ``shm_direct`` lane)**: when the peer
  daemon's data-plane handshake (``DXH1``) returns OUR boot identity,
  sends skip the TCP payload stream entirely — the sender asks the
  peer to attach the flow's segment (``DXA1``), memcpys staged bytes
  segment→segment through its own mapping of the peer's file, and
  lands them with a descriptor-only commit (``DXC1``) that carries
  seq/off/tot/xid but zero payload bytes.  Dedup, accounting, wait
  wakeups all ride the same ``land_frame``; an inode check makes a
  stale mapping (peer released/recreated the segment) a loud
  ``rejected`` instead of silent corruption; ANY lane trouble —
  handshake mismatch, mapping failure, mid-transfer peer restart —
  falls back to the TCP stream inside the same send
  (``dcn.shm_direct.fallback``).  Per-lane movement is accounted as
  ``dcn.lane.{shm_direct,shm,socket}.bytes`` (+ cumulative
  ``.total_bytes`` gauges); ``xferd.tx.bytes`` stays a SOCKET-lane
  series, which is the counter-level proof co-hosted transfers moved
  zero bytes over TCP.
- **descriptor-ring handoff**: ``shm_attach`` with ``ring`` hands the
  client a per-flow ring file (``parallel/dcn_shm.py`` owns the
  layout); the client posts (off, len, seq) descriptors and issues
  ONE ``shm_post`` doorbell per round instead of per-chunk control
  ops.  A dedicated completer thread drives each descriptor through
  the normal send path (stage-wait, link shim, lane selection,
  verdicts included) and publishes per-slot status + a completion
  cursor the client polls lock-free from shared memory.

Frame wire format (data plane):

    v1 (native-compatible): "DXF1" | u32 LE name_len | u64 LE
        payload_len | name | payload
    v2 (seq + meta):        "DXF2" | u32 LE name_len | u64 LE
        payload_len | u64 LE seq | u32 LE meta_len | name |
        meta (JSON: trace/span/src[/off/tot/xid]) | payload
    read request:           "DXR1" | u32 LE name_len | u64 LE offset |
        u64 LE nbytes | name  →  u64 LE avail | bytes
    peer shm ops:           "DXH1"/"DXA1"/"DXC1" | u32 LE json_len |
        json  →  u32 LE json_len | json  (handshake / segment attach /
        descriptor commit — control-sized JSON both ways, never
        payload bytes)

Receivers accept all of them; v1 frames (the native daemon, local
``put`` staging) have no seq and bypass dedup — exactly what a restage
wants.  A v2 frame with seq 0 (the striped writer staging chunks into
its OWN daemon) also bypasses dedup: local staging is idempotent by
construction, and a restage must be able to overwrite.
"""

import base64
import collections
import hashlib
import itertools
import json
import logging
import mmap
import os
import queue
import shutil
import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple

from container_engine_accelerators_tpu.analysis import lockwatch
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import histo, timeseries, trace
from container_engine_accelerators_tpu.parallel import dcn_shm
from container_engine_accelerators_tpu.utils import netio

# The forward op's reduce landing byte-adds payloads mod 256 — the
# same commutative combine collectives/synth.py simulates, duplicated
# here (like the wire constants) so the daemon stays importable
# without the collectives stack.  numpy when present: routed
# all_reduce legs land O(payload) combines on the daemon's data
# threads, and the byte loop would dominate the measured window.
try:
    import numpy as _np
except ImportError:  # pragma: no cover - baked into the image
    _np = None

log = logging.getLogger(__name__)

VERSION = "pyxferd/3"
SOCKET_NAME = "xferd.sock"
READ_CAP = 512 << 10  # per-call read cap, like the native daemon
# Landed-seq memory per flow.  Sized so one full chunked transfer's
# worth of seqs (a replay re-sends ALL of them under the same numbers)
# fits with 2x headroom: the striped writer caps itself at
# MAX_CHUNKS_PER_TRANSFER = 128 chunks (parallel/dcn_pipeline.py, with
# a cross-test pinning 2 * cap <= window).
DEDUP_WINDOW = 256
# How long an offset-send waits for its chunk to finish landing through
# the local data plane (the stage->send pipeline race is normally
# microseconds; the bound only matters when staging genuinely died).
CHUNK_STAGE_WAIT_S = 5.0
# Per-call cap on the blocking wait op: the client re-issues slices, so
# a daemon thread is never parked longer than this on one request.
MAX_WAIT_SLICE_S = 30.0
# Bounded per-hop retry for the forward op (daemon-routed schedule
# legs): attempts are per forward REQUEST — the coordinator's own
# engine-level retry re-posts the leg under the same seq, so the two
# layers compose without double-landing (the dedup window is the
# exactly-once guarantee either way).
FORWARD_ATTEMPTS = 3
FORWARD_RETRY_BACKOFF_S = 0.05

# Link-shim latency cap, mirroring fleet.links.MAX_INJECT_LATENCY_S
# (deliberately duplicated — the daemon must stay importable without
# the link table): a typo'd delay models relative slowness, not WAN.
LINK_SHIM_MAX_LATENCY_S = 0.25

_MAGIC_V1 = b"DXF1"
_MAGIC_V2 = b"DXF2"
_MAGIC_READ = b"DXR1"
# Daemon↔daemon shm lane (ISSUE 13): JSON request/response ops riding
# the data-plane stream — handshake, peer segment attach, descriptor
# commit.  Control-sized both ways; payload bytes move through the
# segment, never this socket.
_MAGIC_PEER_HELLO = b"DXH1"
_MAGIC_PEER_ATTACH = b"DXA1"
_MAGIC_PEER_COMMIT = b"DXC1"
_PEER_OPS = (_MAGIC_PEER_HELLO, _MAGIC_PEER_ATTACH, _MAGIC_PEER_COMMIT)

# Segment files are at least a page so a 1-byte flow still maps.
SHM_MIN_SEGMENT = 4096

# Process-global assembly-generation source (see _Flow.asm_gen):
# every assembly-identity change anywhere in the daemon gets a value
# no other assembly — past, present, or same-named successor flow —
# has ever carried.
_ASM_GEN = itertools.count(1)

# Descriptor-ring capacity per flow.  Matches the striped writer's
# MAX_CHUNKS_PER_TRANSFER (parallel/dcn_pipeline.py) — deliberately
# duplicated, like the wire constants: the daemon must stay importable
# without the pipeline module, and a cross-test pins the two.
RING_SLOTS = 128


class _Flow:
    __slots__ = ("owner", "peer", "buffer_bytes", "transferred",
                 "rx_bytes", "frame_bytes", "staged", "seen_seqs",
                 "max_seq", "asm_xid", "asm_total", "asm_buf",
                 "asm_chunks", "asm_seqs", "asm_gen", "retired_xids",
                 "seg_path", "seg_map", "seg_size", "seg_ino",
                 "ring_path", "ring_map")

    def __init__(self, owner: int, peer: str, buffer_bytes: int):
        self.owner = owner
        self.peer = peer
        self.buffer_bytes = buffer_bytes
        self.transferred = 0
        self.rx_bytes = 0
        self.frame_bytes = 0
        self.staged = b""
        self.seen_seqs = set()
        self.max_seq = 0
        # Chunk-assembly state (pipelined transfers): one in-progress
        # logical payload, keyed by the sender's transfer id.
        self.asm_xid = None
        self.asm_total = 0
        self.asm_buf = None  # bytearray(asm_total) while assembling
        self.asm_chunks: Dict[int, int] = {}  # landed off -> len
        self.asm_seqs = set()  # seqs whose bytes live in THIS assembly
        # Assembly generation: re-stamped whenever the assembly
        # buffer's identity changes (reset, fresh xid, heap→segment
        # migration).  The recv-into-mmap path captures it with the
        # target view and re-verifies at landing, so bytes received
        # into a buffer the flow no longer assembles into are DROPPED,
        # never recorded.  Values come from a PROCESS-GLOBAL monotonic
        # counter, never a per-flow one: a flow released and
        # re-registered under the same name mid-receive must not be
        # able to repeat a gen the stale receive captured.
        self.asm_gen = next(_ASM_GEN)
        # Transfers this flow has moved PAST: once a new xid starts
        # assembling (or a whole frame replaces staging), the previous
        # xid is retired and its straggler chunks — a ring completer's
        # late send, a delayed retransmit — are dropped as stale
        # instead of discarding the LIVE assembly and re-landing dead
        # bytes.  Abandoning an xid is always caller-intentional (a
        # caller-level retry is a NEW send_pipelined and a NEW xid),
        # so nothing legitimate ever returns under a retired one.
        self.retired_xids = collections.deque(maxlen=8)
        # Shared-memory segment (same-host zero-copy lane).  When set,
        # the flow's staging storage lives IN the mmap: ``staged`` and
        # ``asm_buf`` become memoryviews of ``seg_map``.
        self.seg_path: Optional[str] = None
        self.seg_map = None  # mmap.mmap while attached
        self.seg_size = 0
        # Inode of the segment file at creation: a peer daemon's DXC1
        # commit quotes the inode IT mapped, so a sender holding a
        # mapping of a released-and-recreated segment gets "rejected"
        # instead of marking garbage bytes landed.
        self.seg_ino = 0
        # Descriptor ring (the shm_post handoff): its own file next to
        # the segment, daemon-side mapping kept for status publishing.
        self.ring_path: Optional[str] = None
        self.ring_map = None

    def discard_assembly(self) -> None:
        """Drop the in-progress assembly AND un-see its seqs: a seq is
        only exactly-once while its bytes are retained — keeping seqs
        of discarded chunks would dedup-drop their retransmits and
        wedge the transfer."""
        self.seen_seqs -= self.asm_seqs
        self.asm_seqs = set()
        self.asm_xid = None
        self.asm_buf = None
        self.asm_chunks = {}
        self.asm_gen = next(_ASM_GEN)

    def seg_view(self, nbytes: int) -> memoryview:
        """A writable view of the segment's first ``nbytes``."""
        return memoryview(self.seg_map)[:nbytes]

    def close_segment(self, unlink: bool = True) -> None:
        """Detach the flow's shm segment (and its descriptor ring):
        drop view-backed staging (the bytes die with the flow/daemon,
        same as heap staging), close the mmaps, and unlink the files
        unless this is a crash (SIGKILL leaves files behind; the next
        start() wipes the directory)."""
        path, m = self.seg_path, self.seg_map
        rpath, rm = self.ring_path, self.ring_map
        self.seg_path, self.seg_map, self.seg_size = None, None, 0
        self.seg_ino = 0
        self.ring_path, self.ring_map = None, None
        if isinstance(self.staged, memoryview):
            self.staged = b""
            self.frame_bytes = 0
        if isinstance(self.asm_buf, memoryview):
            self.discard_assembly()
        for mm in (m, rm):
            if mm is None:
                continue
            try:
                mm.close()
            except (BufferError, ValueError):
                pass  # an exported slice keeps it alive until GC
        if m is not None:
            timeseries.gauge_add("dcn.shm.segments", -1)
        if unlink:
            for p in (path, rpath):
                if p:
                    try:
                        os.unlink(p)
                    except OSError:
                        pass

    def range_staged(self, offset: int, nbytes: int,
                     xid: Optional[str] = None) -> bool:
        """True when bytes [offset, offset+nbytes) are readable — from
        the completed frame, or covered by landed assembly chunks.

        With ``xid`` set (a chunked send), only bytes belonging to
        THAT transfer count: a stale completed frame from a previous
        transfer on a reused flow must make the send WAIT for the new
        staging, not silently re-send last transfer's bytes."""
        if (self.frame_bytes and offset + nbytes <= len(self.staged)
                and (xid is None or self.asm_xid == xid)):
            return True
        if self.asm_buf is None or (xid is not None
                                    and self.asm_xid != xid):
            return False
        pos = offset
        for off in sorted(self.asm_chunks):
            if pos >= offset + nbytes:
                break
            if off <= pos < off + self.asm_chunks[off]:
                pos = off + self.asm_chunks[off]
        return pos >= offset + nbytes

    def read_range(self, offset: int, nbytes: int,
                   xid: Optional[str] = None) -> bytes:
        if (self.frame_bytes and offset + nbytes <= len(self.staged)
                and (xid is None or self.asm_xid == xid)):
            # bytes() either way: a memoryview (shm-backed staging)
            # must not escape the lock — the segment can be remapped
            # or closed the moment the caller lets go.
            return bytes(self.staged[offset:offset + nbytes])
        return bytes(self.asm_buf[offset:offset + nbytes])


# Exact reads and capped, short-write-proof sends live in utils/netio
# (the rig's stack truncates very large single-syscall payloads).
_recv_exact = netio.recv_exact


def _combine_into(dst, offset: int, payload) -> None:
    """``dst[offset+i] = (dst[offset+i] + payload[i]) % 256`` in place
    — the forward op's reduce landing.  ``dst`` is the flow's staging
    bytearray or a writable segment view; semantics mirror
    ``collectives.synth.combine`` byte-for-byte (a cross-test pins
    the two)."""
    n = len(payload)
    if _np is not None and n >= 64:
        view = _np.frombuffer(dst, dtype=_np.uint8, count=n,
                              offset=offset)
        view += _np.frombuffer(payload, dtype=_np.uint8, count=n)
        return
    for i in range(n):
        dst[offset + i] = (dst[offset + i] + payload[i]) & 0xFF


def _set_nodelay(sock: socket.socket) -> None:
    """Chunked frames are header+payload pairs and DXR1 replies are
    header+data pairs: Nagle coalescing against delayed ACKs costs
    milliseconds per chunk, which is the whole pipelining budget."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass  # not a TCP socket (UDS in tests)


def encode_frame(flow: str, payload: bytes, seq: Optional[int] = None,
                 meta: Optional[dict] = None) -> bytes:
    """Build a wire frame: v1 when there is neither seq nor meta
    (native-compatible), v2 otherwise.  A v2 frame with meta but no seq
    carries seq 0 on the wire — "no dedup", the staging-chunk case."""
    if seq is None and meta is None:
        name = flow.encode()
        return (_MAGIC_V1 + struct.pack("<I", len(name))
                + struct.pack("<Q", len(payload)) + name + payload)
    return encode_frame_header(flow, len(payload), seq, meta) + payload


def encode_frame_header(flow: str, payload_len: int,
                        seq: Optional[int] = None,
                        meta: Optional[dict] = None) -> bytes:
    """The v2 frame minus its payload — senders pass the payload as a
    separate ``sendmsg`` buffer and skip one full-chunk copy."""
    name = flow.encode()
    meta_b = json.dumps(meta or {}).encode()
    return (_MAGIC_V2 + struct.pack("<I", len(name))
            + struct.pack("<Q", payload_len)
            + struct.pack("<Q", seq or 0)
            + struct.pack("<I", len(meta_b)) + name + meta_b)


def encode_read_request(flow: str, offset: int, nbytes: int) -> bytes:
    """Build a DXR1 data-plane read request (the striped reader's
    binary read-back; the daemon answers u64 LE length + raw bytes)."""
    name = flow.encode()
    return (_MAGIC_READ + struct.pack("<I", len(name))
            + struct.pack("<Q", offset) + struct.pack("<Q", nbytes)
            + name)


class _PeerConn:
    """One cached outbound data-plane stream.  Sends hold the lock for
    the whole frame so concurrent users can never interleave bytes."""

    def __init__(self):
        self.lock = threading.Lock()
        self.sock: Optional[socket.socket] = None

    def send_frame(self, host: str, port: int, parts) -> None:
        # Serializing the whole frame under the lock IS the contract
        # (concurrent stripes interleaving bytes would corrupt the
        # stream) — a deliberate blocking-under-lock, annotated so
        # `make race` books it under `allowed` instead of failing.
        with self.lock, lockwatch.blocking_ok(
                "xferd.peer: frames on one stream must not interleave"):
            if self.sock is None:
                s = socket.create_connection((host, port), timeout=30)
                _set_nodelay(s)
                self.sock = s
            try:
                netio.sendall_parts(self.sock, parts)
            except OSError:
                self.close_locked()
                raise

    def close_locked(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def close(self) -> None:
        with self.lock:
            self.close_locked()


class _PeerSeg:
    """One sender-side mapping of a PEER daemon's segment file."""

    __slots__ = ("path", "size", "ino", "map")

    def __init__(self, path: str, size: int):
        self.path = path
        self.size = int(size)
        fd = os.open(path, os.O_RDWR)
        try:
            self.ino = os.fstat(fd).st_ino
            self.map = mmap.mmap(fd, self.size)
        except ValueError as e:
            raise OSError(f"peer segment {path!r} unmappable: {e}")
        finally:
            os.close(fd)

    def close(self) -> None:
        try:
            self.map.close()
        except (BufferError, ValueError):
            pass


class _PeerShmLane:
    """Cached daemon↔daemon shm state toward one peer data endpoint.

    One control TCP stream (handshake / attach / descriptor commits —
    tiny JSON, never payload) plus per-flow mappings of the peer's
    segment files.  ``usable`` is tri-state: None = not probed yet,
    False = probed and refused (host mismatch, shm off — cached so
    every send doesn't re-handshake a cross-host peer), True = live.
    A transport error resets to None: the next send re-dials and
    re-probes, which is how a peer restart (fresh port, wiped
    segments) is survived — the caller falls back to TCP for the
    frame that hit the error."""

    def __init__(self):
        self.lock = threading.Lock()
        self.sock: Optional[socket.socket] = None
        self.usable: Optional[bool] = None
        self.segs: Dict[str, _PeerSeg] = {}

    def reset_locked(self, usable: Optional[bool]) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        for seg in self.segs.values():
            seg.close()
        self.segs.clear()
        self.usable = usable

    def close(self) -> None:
        with self.lock:
            self.reset_locked(None)

    def request(self, host: str, port: int, magic: bytes,
                req: dict, timeout_s: float = 30.0) -> dict:
        """One JSON round trip on the cached stream (dialing it on
        first use).  Caller holds ``self.lock``."""
        if self.sock is None:
            s = socket.create_connection((host, port),
                                         timeout=timeout_s)
            _set_nodelay(s)
            self.sock = s
        body = json.dumps(req).encode()
        netio.sendall_parts(self.sock,
                            (magic + struct.pack("<I", len(body)),
                             body))
        n = struct.unpack("<I", _recv_exact(self.sock, 4))[0]
        if n > 65536:
            raise OSError("peer shm response out of bounds")
        return json.loads(_recv_exact(self.sock, n))


class PyXferd:
    """One emulated node's transfer daemon."""

    def __init__(self, uds_dir: str, node: str = "", net=None,
                 data_host: str = "127.0.0.1",
                 shm: Optional[bool] = None,
                 host_id: Optional[str] = None,
                 shm_direct: Optional[bool] = None,
                 forward: Optional[bool] = None,
                 ring: Optional[bool] = None):
        self.uds_dir = uds_dir
        self.node = node
        self.net = net
        self.data_host = data_host
        self.sock_path = os.path.join(uds_dir, SOCKET_NAME)
        # Zero-copy same-host lane: per-flow mmap segments under
        # shm_dir, advertised with this daemon's host identity so a
        # client can tell "same address" from "same machine".
        # ``shm``/``host_id`` overrides are the cross-host and
        # capability-less test handles.
        self.shm_enabled = (dcn_shm.shm_enabled() if shm is None
                            else bool(shm))
        self.shm_dir = os.path.join(uds_dir, "shm")
        self.host_id = host_id or dcn_shm.host_identity()
        # Daemon↔daemon same-host lane: this daemon's willingness to
        # SEND through a co-hosted peer's segments (the receive half
        # rides shm_enabled).  Fleet-fabric daemons never take it —
        # with a link table, TCP-or-fabric is the single fault
        # surface the scenarios interpose on.
        self.shm_direct = (dcn_shm.shm_direct_enabled()
                           if shm_direct is None else bool(shm_direct))
        # Daemon-routed forwarding (the collective engine's routed
        # execution mode): willingness to serve ``forward`` ops —
        # re-sending a staged flow range straight to a peer daemon.
        # ``forward=False`` is the capability-less test handle: the
        # op vanishes from the version handshake AND the dispatch
        # table ("unknown op"), which is the client's mid-schedule
        # downgrade signal.
        self.forward_enabled = (True if forward is None
                                else bool(forward))
        # Universal submission ring: willingness to serve descriptor
        # rings on ANY lane (ring_attach + shm_post doorbells whose
        # descriptors the completer drives through the normal
        # lane-selection send path).  Independent of shm_enabled —
        # a socket-only daemon still mmaps the ring file (descriptors
        # and cursors, not payload) so the client's hot path stays
        # lock-free.  ``ring=False`` is the capability-less handle.
        self.ring_enabled = (dcn_shm.shm_ring_enabled() if ring is None
                             else bool(ring))
        # Grey-fault hook (soak "slow_ring"): per-descriptor delay the
        # completer sleeps before driving each posted descriptor — a
        # completer that is slow, not dead.
        self._ring_delay_s = 0.0
        # Grey-fault hook (soak "slow_shm"): per-frame delay the shm
        # commit path pays before landing — a throttled staging
        # memcpy, the shm lane's slow-not-dead sibling.
        self._shm_delay_s = 0.0
        self.data_port = 0
        self.generation = 0
        self._flows: Dict[str, _Flow] = {}
        self._total_transferred = 0
        self._unmatched = 0
        self._lock = threading.Lock()
        # Landing notifications: wait ops and offset-sends park here
        # until land_frame advances the flow they watch.
        self._landed = threading.Condition(self._lock)
        self._server: Optional[socket.socket] = None
        self._data_server: Optional[socket.socket] = None
        self._conns = set()
        # Persistent outbound data-plane connections, keyed by
        # (control conn, host, port): chunked sends reuse one TCP
        # stream per stripe instead of dialing per chunk, and distinct
        # stripes (distinct control connections) get distinct streams
        # — the FlexLink point of striping one logical transfer.
        self._peer_conns: Dict[tuple, "_PeerConn"] = {}
        # Daemon↔daemon shm lane state per peer data endpoint.  Lock
        # order: a lane's lock is ALWAYS taken before self._lock
        # (the copy step), never after — _peer_lane() releases
        # self._lock before the caller enters the lane.
        self._peer_lanes: Dict[Tuple[str, int], _PeerShmLane] = {}
        # Descriptor-ring doorbells (shm_post) queue here; a dedicated
        # completer thread (one per daemon incarnation, joined on
        # stop) drives each descriptor through the normal send path
        # and publishes status into the flow's ring.
        self._ring_q: Optional[queue.Queue] = None
        self._ring_thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        # SIGKILL modeling: stop(crash=True) raises this BEFORE
        # severing connections, so the conn threads' release path
        # leaves segment files behind exactly like a real process
        # death would (the next start() wipes them).
        self._crashing = False
        # Test hook: {op: n} — process the next n requests of `op`, then
        # sever the connection BEFORE responding (a daemon that did the
        # work but whose answer was lost: the replay-dedup scenario).
        self._drop_response: Dict[str, int] = {}
        # Proc-mode link-fault shim (netem analog): per-destination
        # (host, port) fault state consulted by the SEND path when
        # there is no in-process fabric to interpose (net is None).
        # Armed over the worker RPC by the fleet controller, so
        # `sel<->sel` link faults work against real OS-process nodes
        # too.  Keyed by the peer's CURRENT data port: a respawned
        # peer binds a fresh port and starts with a clean link —
        # the same reset its flows and dedup windows get.
        self._link_faults: Dict[Tuple[str, int], dict] = {}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "PyXferd":
        os.makedirs(self.uds_dir, exist_ok=True)
        if os.path.exists(self.sock_path):
            os.unlink(self.sock_path)  # the real daemon unlinks-then-binds
        # Crash-lingering segment files belong to the dead incarnation;
        # wipe them the same way the socket path is unlinked.
        shutil.rmtree(self.shm_dir, ignore_errors=True)
        if self.shm_enabled or self.ring_enabled:
            os.makedirs(self.shm_dir, exist_ok=True)
        self._stopping.clear()
        self._crashing = False
        # A fresh incarnation starts with clean links, like its flows.
        with self._lock:
            self._link_faults.clear()
        self._ring_q = queue.Queue()
        self._ring_thread = threading.Thread(
            target=self._ring_completer, args=(self._ring_q,),
            name=f"pyxferd-ring-{self.node}", daemon=True)
        self._ring_thread.start()
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(self.sock_path)
        srv.listen(16)
        self._server = srv
        dsrv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        dsrv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        dsrv.bind((self.data_host, 0))
        dsrv.listen(16)
        self._data_server = dsrv
        self.data_port = dsrv.getsockname()[1]
        self.generation += 1
        for target, name in ((self._accept_loop, "pyxferd-ctl"),
                             (self._data_accept_loop, "pyxferd-data")):
            threading.Thread(target=target, name=f"{name}-{self.node}",
                             daemon=True).start()
        return self

    def stop(self, *, crash: bool = False) -> None:
        """``crash=True`` models SIGKILL: connections die, the socket
        path AND segment files linger until the next start() unlinks
        them (the flag below keeps the conn threads' release path from
        cleaning up on the dead incarnation's behalf)."""
        self._stopping.set()
        self._crashing = crash
        q, t = self._ring_q, self._ring_thread
        self._ring_q, self._ring_thread = None, None
        if q is not None:
            q.put(None)  # completer sentinel
        for attr in ("_server", "_data_server"):
            srv = getattr(self, attr)
            if srv is not None:
                try:
                    try:
                        srv.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    srv.close()
                finally:
                    setattr(self, attr, None)
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()
        if not crash and os.path.exists(self.sock_path):
            os.unlink(self.sock_path)
        # Process death: all staging buffers, seqs windows, accounting
        # die with it — exactly what the restart chaos scenarios need.
        # Segments go too: on a clean stop the files are unlinked, on a
        # crash they linger (like the socket path) until the next
        # start() wipes the directory — either way a client holding a
        # stale mapping writes into an orphaned inode the next daemon
        # can never see, which is why the client remaps per transfer.
        with self._lock:
            for f in self._flows.values():
                f.close_segment(unlink=not crash)
            self._flows.clear()
            self._total_transferred = 0
            self._unmatched = 0
            self._publish_flow_gauges_locked()
            self._landed.notify_all()  # unpark any blocked wait op
            peer_conns = list(self._peer_conns.values())
            self._peer_conns.clear()
            peer_lanes = list(self._peer_lanes.values())
            self._peer_lanes.clear()
        for pc in peer_conns:
            pc.close()
        for lane in peer_lanes:
            lane.close()
        if t is not None:
            t.join(timeout=5.0)

    # -- control plane -------------------------------------------------------

    def _accept_loop(self) -> None:
        srv = self._server
        while not self._stopping.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            if self._stopping.is_set():
                conn.close()
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name=f"pyxferd-conn-{self.node}",
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        conn_id = id(conn)
        with self._lock:
            self._conns.add(conn)
        rfile = conn.makefile("r")
        try:
            for line in rfile:
                req = None
                try:
                    req = json.loads(line)
                    resp = self._handle(conn_id, req)
                except (ValueError, KeyError, TypeError) as e:
                    resp = {"ok": False, "error": f"bad request: {e}"}
                op = req.get("op") if isinstance(req, dict) else None
                if op and self._drop_response.get(op, 0) > 0:
                    # The work is DONE; the answer is lost.  Sever so
                    # the client's retry exercises the dedup window.
                    self._drop_response[op] -= 1
                    try:
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    break
                try:
                    netio.sendall(conn,
                                  (json.dumps(resp) + "\n").encode())
                except OSError:
                    break
        finally:
            rfile.close()
            conn.close()
            with self._lock:
                self._conns.discard(conn)
            self._release_owned(conn_id)

    def drop_response_once(self, op: str, times: int = 1) -> None:
        """Arm the lost-response hook for the next ``times`` ``op``
        requests (chaos tests)."""
        self._drop_response[op] = self._drop_response.get(op, 0) + times

    # -- link-fault shim (proc-mode netem analog) ----------------------------

    def set_link_fault(self, host: str, port: int, action: str,
                       param: float = 0.0) -> int:
        """Arm one outbound link fault toward ``(host, port)`` —
        ``partition`` (sends fail like a null route), ``heal`` (clear
        everything), ``latency`` (per-frame one-way delay, seconds,
        capped), ``drop`` (eat the next ``param`` frames in flight:
        the sender believes they left, the peer never sees them).
        Consulted by the send path only when this daemon has no
        in-process fabric (``net is None``) — with a fabric the
        LinkTable is the single fault surface."""
        key = (host, int(port))
        with self._lock:
            st = self._link_faults.get(key)
            if st is None:
                st = self._link_faults[key] = {
                    "up": True, "latency_s": 0.0, "drop_next": 0}
            if action == "partition":
                st["up"] = False
            elif action == "heal":
                self._link_faults.pop(key, None)
            elif action == "latency":
                st["latency_s"] = min(max(float(param), 0.0),
                                      LINK_SHIM_MAX_LATENCY_S)
            elif action == "drop":
                st["drop_next"] += max(1, int(param or 1))
            else:
                raise ValueError(f"unknown link-fault action "
                                 f"{action!r}")
        log.warning("link shim: %s toward %s:%d armed on node %s",
                    action, host, port, self.node or "?")
        return 1

    def set_ring_delay(self, seconds: float) -> float:
        """Grey-fault handle (soak "slow_ring"): make the ring
        completer sleep this long before driving EACH posted
        descriptor — a completer that is slow, not dead.  Partial
        progress keeps publishing into the cursor, so clients see a
        crawling round rather than a wedged one.  0 disarms."""
        self._ring_delay_s = min(max(float(seconds), 0.0), 2.0)
        log.warning("ring completer delay %.3fs armed on node %s",
                    self._ring_delay_s, self.node or "?")
        return self._ring_delay_s

    def set_shm_delay(self, seconds: float) -> float:
        """Grey-fault handle (soak "slow_shm"): make every shm commit
        pay this delay before landing — a throttled per-frame staging
        memcpy on the zero-copy lane, slow, not dead.  Commits still
        land and account normally, so no health check fires; only the
        ``xferd.shm.commit`` latency histogram carries the evidence.
        0 disarms."""
        self._shm_delay_s = min(max(float(seconds), 0.0), 2.0)
        log.warning("shm commit delay %.3fs armed on node %s",
                    self._shm_delay_s, self.node or "?")
        return self._shm_delay_s

    def _shim_consult(self, host: str, port: int):
        """One frame's verdict from the shim: (action, delay_s) where
        action is None / "blocked" / "dropped".  The latency sleep
        happens in the CALLER, outside the lock."""
        with self._lock:
            st = self._link_faults.get((host, int(port)))
            if st is None:
                return None, 0.0
            if not st["up"]:
                return "blocked", 0.0
            if st["drop_next"] > 0:
                st["drop_next"] -= 1
                return "dropped", st["latency_s"]
            return None, st["latency_s"]

    def _publish_flow_gauges_locked(self) -> None:
        """Flow accounting as gauges (caller holds the lock): what the
        in-process aggregator reads via ``_stats()``, the process-mode
        HTTP aggregator scrapes as ``agent_gauge`` — same numbers,
        different transport."""
        timeseries.gauge("xferd.active_flows", float(len(self._flows)))
        timeseries.gauge("xferd.total_transferred",
                         float(self._total_transferred))

    def _release_owned(self, conn_id: int) -> None:
        with self._lock:
            released = [n for n, f in self._flows.items()
                        if f.owner == conn_id]
            for name in released:
                # On a crash-stop the conn threads race the stop():
                # SIGKILL runs zero cleanup lines, so neither may this
                # path unlink the dead incarnation's segment files.
                self._flows[name].close_segment(
                    unlink=not self._crashing)
                del self._flows[name]
            self._publish_flow_gauges_locked()
            self._landed.notify_all()  # waiters re-check released flows
            # Ring completer and forward-op streams are keyed by flow
            # (pseudo conn ids), not by the owning control connection
            # — release them with the flows they served.
            flow_ids = ({f"ring:{n}" for n in released}
                        | {f"fwd:{n}" for n in released})
            stale = [k for k in self._peer_conns
                     if k[0] == conn_id or k[0] in flow_ids]
            conns = [self._peer_conns.pop(k) for k in stale]
            lanes = list(self._peer_lanes.values()) if released else []
        for pc in conns:
            pc.close()
        # Drop this side's mappings of the released flows' PEER
        # segments too (outside self._lock — lane.lock comes first in
        # the documented order): a released flow's segment is about to
        # be unlinked on the peer, and a cached mapping of the orphan
        # inode would pin 4 MiB of dead pages per transfer.
        for lane in lanes:
            with lane.lock:
                for name in released:
                    seg = lane.segs.pop(name, None)
                    if seg is not None:
                        seg.close()

    def _handle(self, conn_id: int, req: dict) -> dict:
        op = req.get("op")
        # Join the client's trace: daemon-side work hangs off the
        # control round trip that asked for it, across the process
        # boundary.
        with trace.attach(req.get("trace"), req.get("span")):
            with trace.span("xferd.op", op=op, node=self.node):
                return self._dispatch(conn_id, op, req)

    def _dispatch(self, conn_id: int, op: str, req: dict) -> dict:
        if op == "version":
            resp = {"ok": True, "version": VERSION, "frame_version": 2,
                    "pipeline": 1}
            if self.forward_enabled:
                # Daemon-routed forwarding: coordinators only post
                # forwarding programs to daemons that advertise it and
                # downgrade legs on daemons that do not.
                resp["forward"] = 1
            if self.shm_enabled:
                # The zero-copy lane's capability triple: clients take
                # it only on an exact host_id match (boot identity —
                # same ADDRESS is not same MACHINE), and only if the
                # advertised segment paths actually map.
                resp.update(shm=1, shm_dir=self.shm_dir,
                            host_id=self.host_id)
            if self.ring_enabled:
                # Universal-ring capability: advertised independently
                # of shm (a socket-lane daemon still serves descriptor
                # rings).  host_id rides along because ring files are
                # mmapped — same-MACHINE is the gate, as for shm.
                resp.update(ring=1, host_id=self.host_id)
            return resp
        if op == "ping":
            return {"ok": True}
        if op == "data_port":
            return {"ok": True, "port": self.data_port}
        if op == "register_flow":
            flow = req["flow"]
            with self._lock:
                if flow in self._flows:
                    return {"ok": False,
                            "error": f"flow already exists: {flow}"}
                nbytes = int(req.get("bytes") or 4096)
                self._flows[flow] = _Flow(conn_id, req.get("peer", ""),
                                          nbytes)
                self._publish_flow_gauges_locked()
            return {"ok": True, "flow": flow, "buffer_bytes": nbytes}
        if op == "record_transfer":
            nbytes = req.get("bytes")
            if not isinstance(nbytes, int) or nbytes < 0:
                return {"ok": False, "error": "invalid 'bytes'"}
            with self._lock:
                f = self._flows.get(req["flow"])
                if f is None:
                    return {"ok": False, "error": "unknown flow"}
                if f.owner != conn_id:
                    return {"ok": False,
                            "error": "flow owned by another client"}
                f.transferred += nbytes
                self._total_transferred += nbytes
                self._publish_flow_gauges_locked()
                return {"ok": True, "flow_bytes": f.transferred}
        if op == "release_flow":
            with self._lock:
                f = self._flows.get(req["flow"])
                if f is None:
                    return {"ok": False, "error": "unknown flow"}
                if f.owner != conn_id:
                    return {"ok": False,
                            "error": "flow owned by another client"}
                f.close_segment()
                del self._flows[req["flow"]]
                self._publish_flow_gauges_locked()
                flow_ids = (f"ring:{req['flow']}",
                            f"fwd:{req['flow']}")
                stale = [k for k in self._peer_conns
                         if k[0] in flow_ids]
                conns = [self._peer_conns.pop(k) for k in stale]
                lanes = list(self._peer_lanes.values())
            for pc in conns:
                pc.close()
            for lane in lanes:  # drop mappings of the peer's segment
                with lane.lock:
                    seg = lane.segs.pop(req["flow"], None)
                    if seg is not None:
                        seg.close()
            return {"ok": True}
        if op == "read":
            return self._read(req)
        if op == "send":
            return self._send(conn_id, req)
        if op == "wait":
            return self._wait(req)
        if op == "stats":
            return self._stats(req.get("flow"))
        if op == "shm_attach":
            return self._shm_attach(req)
        if op == "shm_commit":
            return self._shm_commit(req)
        if op == "shm_read":
            return self._shm_read(req)
        if op == "shm_post":
            return self._shm_post(req)
        if op == "ring_attach":
            return self._ring_attach(req)
        if op == "forward" and self.forward_enabled:
            # Gated on the capability flag so a forward-less daemon
            # answers "unknown op" — byte-identical to a daemon that
            # predates the op, which is what the client's downgrade
            # path keys on.
            return self._forward(req)
        return {"ok": False, "error": f"unknown op: {op}"}

    def _wait(self, req: dict) -> dict:
        """Blocking wait: park this connection's thread until the flow
        reaches ``bytes`` of rx (mode ``rx``) or a completed frame of
        at least ``bytes`` (mode ``frame``), or the slice times out.
        The client loops slices against its own deadline, so a daemon
        thread is never held hostage by a dead client's deadline."""
        flow = req["flow"]
        nbytes = int(req.get("bytes") or 0)
        mode = req.get("mode", "rx")
        if mode not in ("rx", "frame"):
            return {"ok": False, "error": f"unknown wait mode: {mode}"}
        timeout_ms = req.get("timeout_ms")
        if timeout_ms is None:
            timeout_ms = 1000
        timeout_s = min(max(float(timeout_ms), 0.0) / 1e3,
                        MAX_WAIT_SLICE_S)

        def done() -> bool:
            f = self._flows.get(flow)
            if f is None:
                return True  # released/never registered: report, don't hang
            have = f.frame_bytes if mode == "frame" else f.rx_bytes
            return have >= nbytes

        with self._landed:
            reached = self._landed.wait_for(done, timeout=timeout_s)
            f = self._flows.get(flow)
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            return {"ok": True, "done": bool(reached),
                    "rx_bytes": f.rx_bytes, "frame_bytes": f.frame_bytes}

    def _read(self, req: dict) -> dict:
        nbytes = int(req.get("bytes") or 0)
        offset = int(req.get("offset") or 0)
        with self._lock:
            f = self._flows.get(req["flow"])
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            frame_bytes = f.frame_bytes
            if offset > len(f.staged):
                return {"ok": False,
                        "error": f"'offset' beyond staged data "
                                 f"(frame_bytes={frame_bytes})"}
            # Copy under the lock: shm-backed staging is a memoryview
            # whose mapping must not outlive this critical section.
            chunk = bytes(f.staged[offset:offset + min(nbytes, READ_CAP)])
        return {"ok": True, "data": base64.b64encode(chunk).decode(),
                "frame_bytes": frame_bytes}

    def _send(self, conn_id: int, req: dict) -> dict:
        flow = req["flow"]
        host = req.get("host", "127.0.0.1")
        port = int(req["port"])
        seq = req.get("seq")
        seq = int(seq) if seq is not None else None
        offset = req.get("offset")
        xid = None
        tot = 0
        payload = None  # materialized lazily: the direct lane never needs it
        if offset is None:
            with self._lock:
                f = self._flows.get(flow)
                if f is None:
                    return {"ok": False, "error": "unknown flow"}
                # bytes() under the lock: shm-backed staging is a view
                # of a mapping that may be remapped once we let go.
                nbytes = int(req.get("bytes") or len(f.staged))
                payload = bytes(f.staged[:nbytes])
            if not payload:
                return {"ok": False,
                        "error": f"nothing staged for flow {flow!r}"}
            nbytes = len(payload)
            meta_extra = {}
        else:
            # Chunked send: stream staged[offset:offset+bytes] as one
            # chunk frame.  The chunk may still be in flight on the
            # local data plane (the stage->send pipeline), so wait
            # briefly for it to land rather than racing it.
            offset = int(offset)
            nbytes = int(req.get("bytes") or 0)
            if offset < 0 or nbytes <= 0:
                return {"ok": False,
                        "error": "chunked send needs offset >= 0 and "
                                 "bytes > 0"}
            stage_wait_s = min(
                float(req.get("stage_wait_ms")
                      or CHUNK_STAGE_WAIT_S * 1e3) / 1e3,
                CHUNK_STAGE_WAIT_S,
            )
            xid = req.get("xid") or ""
            tot = int(req.get("total") or 0)
            with self._landed:
                staged = self._landed.wait_for(
                    lambda: (self._flows.get(flow) is None
                             or self._flows[flow].range_staged(
                                 offset, nbytes, xid)),
                    timeout=stage_wait_s,
                )
                f = self._flows.get(flow)
                if f is None:
                    return {"ok": False, "error": "unknown flow"}
                if not staged:
                    return {"ok": False,
                            "error": f"chunk not staged for flow "
                                     f"{flow!r} [{offset}:"
                                     f"{offset + nbytes}]"}
            meta_extra = {"off": offset, "tot": tot, "xid": xid}
        # The daemon↔daemon segment lane is in play when there is no
        # fleet fabric (the fabric IS the fault surface then), the env
        # kill switch is on, and the client did not pin the frame to
        # TCP (the bench's socket series, the parity scenarios).
        direct_ok = (self.net is None and self.shm_direct
                     and req.get("direct") not in (0, "0", False))
        # Proc-mode link shim: when there is no in-process fabric, the
        # armed per-destination faults interpose here — the one point
        # every outbound frame passes, like FleetNet.deliver.
        shim = None
        if self.net is None:
            shim, shim_delay_s = self._shim_consult(host, port)
            if shim == "blocked":
                counters.inc("fleet.link.blocked")
                return {"ok": False,
                        "error": f"send failed: link to {host}:{port} "
                                 f"partitioned (injected)"}
            if shim_delay_s > 0:
                time.sleep(shim_delay_s)
        t0 = time.monotonic()
        with trace.span("xferd.send", histogram="xferd.send", flow=flow,
                        node=self.node, dst=f"{host}:{port}", seq=seq,
                        bytes=nbytes) as span:
            meta = {"src": self.node}
            meta.update(meta_extra)
            ctx = trace.context()
            if ctx is not None:
                meta.update(ctx)
            verdict = None
            lane = "socket"
            try:
                if shim == "dropped":
                    # Loss injection: the sender believes the frame
                    # left; the peer never sees it.  The verdict lets
                    # the striped writer retransmit without a timeout,
                    # exactly like the fleet fabric's answer.
                    counters.inc("fleet.link.dropped")
                    verdict = "dropped"
                    span.annotate(verdict=verdict)
                elif self.net is not None:
                    # Fleet mode: EVERY frame goes through the link
                    # table — a port the fabric doesn't know (stale
                    # after a peer restart, node down) is a dead link,
                    # never a raw TCP dial around the fault surface.
                    payload = self._materialize(flow, offset, nbytes,
                                                xid, payload)
                    if payload is None:
                        return {"ok": False,
                                "error": f"chunk not staged for flow "
                                         f"{flow!r}"}
                    verdict = self.net.deliver(self.node, host, port,
                                               flow, payload, seq, meta)
                    span.annotate(verdict=verdict)
                else:
                    if direct_ok:
                        verdict = self._shm_direct_try(
                            flow, host, port, offset, nbytes, tot,
                            xid, seq, meta, payload)
                        if verdict is not None:
                            lane = "shm_direct"
                            span.annotate(verdict=verdict, lane=lane)
                    if verdict is None:
                        # TCP fallback (or the plain socket lane).
                        payload = self._materialize(
                            flow, offset, nbytes, xid, payload)
                        if payload is None:
                            return {"ok": False,
                                    "error": f"chunk not staged for "
                                             f"flow {flow!r}"}
                        if offset is None:
                            # Whole-payload send: a fresh dial per
                            # send, so a dead peer surfaces as an
                            # immediate error (the serial contract).
                            self._tcp_send(host, port, flow, payload,
                                           seq, meta)
                        else:
                            # Chunked send: a persistent stream per
                            # (control connection, peer) — dialing per
                            # chunk costs more than the chunk.  A frame
                            # lost in a stale stream's buffer when the
                            # peer dies is re-sent by the striped
                            # writer's retry round (same seq, dedup).
                            self._peer_conn(conn_id, host,
                                            port).send_frame(
                                host, port,
                                [encode_frame_header(
                                    flow, len(payload), seq, meta),
                                 payload],
                            )
            except OSError as e:
                return {"ok": False, "error": f"send failed: {e}"}
        micros = max(1.0, (time.monotonic() - t0) * 1e6)
        # Per-lane movement accounting.  ``xferd.tx.bytes`` is the
        # SOCKET lane's series on purpose: "co-hosted transfers move
        # zero bytes over the peer TCP stream" is provable exactly
        # because the direct lane never touches it.
        timeseries.record(f"dcn.lane.{lane}.bytes", nbytes)
        timeseries.gauge_add(f"dcn.lane.{lane}.total_bytes", nbytes)
        if lane == "socket":
            timeseries.record("xferd.tx.bytes", nbytes)
        with self._lock:
            f = self._flows.get(flow)
            if f is not None:
                f.transferred += nbytes
                self._total_transferred += nbytes
                self._publish_flow_gauges_locked()
        resp = {"ok": True, "bytes": nbytes,
                "micros": round(micros, 1),
                "gbps": round(nbytes * 8 / micros / 1e3, 3),
                "lane": lane}
        if verdict is not None:
            # The striped sender uses this to retransmit chunks the
            # link ate without waiting for a timeout.
            resp["verdict"] = verdict
        return resp

    def _forward(self, req: dict) -> dict:
        """One routed schedule leg: re-send staged bytes
        ``[offset, offset+bytes)`` of ``flow`` straight to the peer
        daemon at (host, port) as a forward frame — the coordinator
        posts the program and collects this verdict; the payload never
        crosses its clients.

        The frame's seq is COORDINATOR-ASSIGNED (required, > 0): the
        destination flow's dedup window is shared by every source
        daemon forwarding into it, so only the schedule's author can
        hand out non-colliding numbers.  A re-post of the same leg
        re-sends the same seq and lands exactly once — the "dup"
        verdict IS success (the bytes are already there), and the
        chaos tests scrape it as the dedup evidence.  Retries here are
        PER-HOP and bounded (link drops, a redialed peer stream);
        terminal verdicts surface to the coordinator, whose
        engine-level retry re-posts the leg or downgrades it."""
        flow = req["flow"]
        host = req.get("host", "127.0.0.1")
        port = int(req["port"])
        seq = int(req.get("seq") or 0)
        offset = int(req.get("offset") or 0)
        nbytes = int(req.get("bytes") or 0)
        total = int(req.get("total") or 0)
        red = 1 if req.get("reduce") else 0
        attempts = max(1, int(req.get("attempts")
                              or FORWARD_ATTEMPTS))
        if seq <= 0 or offset < 0 or nbytes <= 0:
            return {"ok": False,
                    "error": "forward needs seq > 0, offset >= 0 "
                             "and bytes > 0"}
        stage_wait_s = min(
            float(req.get("stage_wait_ms")
                  or CHUNK_STAGE_WAIT_S * 1e3) / 1e3,
            CHUNK_STAGE_WAIT_S)
        # The source range may still be landing (an earlier leg of
        # the same round targets this daemon): park on the landing CV
        # like an offset send, then copy under the lock.
        with self._landed:
            staged = self._landed.wait_for(
                lambda: (self._flows.get(flow) is None
                         or self._flows[flow].range_staged(offset,
                                                           nbytes)),
                timeout=stage_wait_s)
            f = self._flows.get(flow)
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            if not staged:
                return {"ok": False,
                        "error": f"range not staged for flow "
                                 f"{flow!r} [{offset}:"
                                 f"{offset + nbytes}]"}
            payload = f.read_range(offset, nbytes)
        meta = {"src": self.node, "fwd": 1, "off": offset,
                "tot": total, "red": red}
        ctx = trace.context()
        if ctx is not None:
            meta.update(ctx)
        t0 = time.monotonic()
        verdict = None
        used = 0
        last_err = None
        with trace.span("xferd.forward", histogram="xferd.forward",
                        flow=flow, node=self.node,
                        dst=f"{host}:{port}", seq=seq,
                        bytes=nbytes) as span:
            for attempt in range(attempts):
                used = attempt + 1
                if attempt:
                    counters.inc("xferd.forward.retries")
                    time.sleep(FORWARD_RETRY_BACKOFF_S * attempt)
                try:
                    if self.net is not None:
                        # Fleet mode: through the link table, the
                        # landing verdict coming straight back.  Only
                        # "dropped" is retryable — the retransmit
                        # carries the SAME seq, so a frame that
                        # actually landed cannot double-land.
                        verdict = self.net.deliver(
                            self.node, host, port, flow, payload,
                            seq, meta)
                        if verdict != "dropped":
                            break
                    else:
                        # Proc mode: the link shim interposes per
                        # attempt, then the frame rides a persistent
                        # peer stream keyed by the SOURCE flow
                        # (shared by every leg this daemon forwards
                        # for it; _PeerConn redials after a break).
                        shim, delay_s = self._shim_consult(host,
                                                           port)
                        if shim == "blocked":
                            counters.inc("fleet.link.blocked")
                            span.annotate(verdict="blocked")
                            return {"ok": False,
                                    "verdict": "blocked",
                                    "error": f"forward failed: link "
                                             f"to {host}:{port} "
                                             f"partitioned "
                                             f"(injected)"}
                        if delay_s > 0:
                            time.sleep(delay_s)
                        if shim == "dropped":
                            counters.inc("fleet.link.dropped")
                            verdict = "dropped"
                            continue  # retransmit under the same seq
                        self._peer_conn(f"fwd:{flow}", host,
                                        port).send_frame(
                            host, port,
                            [encode_frame_header(flow, len(payload),
                                                 seq, meta),
                             payload])
                        verdict = "sent"
                        break
                except OSError as e:
                    # Peer stream died (or the fabric reports the
                    # link down): _PeerConn already reset itself, so
                    # the next attempt redials.  LinkPartitioned is
                    # an OSError too — one more look costs nothing
                    # and heals a mid-schedule repartition race.
                    last_err = e
                    verdict = None
            span.annotate(verdict=verdict or "error", attempts=used)
        if verdict not in ("landed", "dup", "sent"):
            # Terminal for THIS hop: the coordinator re-posts the leg
            # (same seq — dedup keeps it exactly-once) or downgrades
            # it to a coordinator-routed leg.
            detail = verdict or last_err or "undeliverable"
            return {"ok": False, "verdict": verdict,
                    "attempts": used,
                    "error": f"forward not landed: {detail}"}
        micros = max(1.0, (time.monotonic() - t0) * 1e6)
        # Forwarded legs are their own lane: never ``xferd.tx.bytes``
        # (the socket-lane proof series) and never a coordinator
        # client's dcn.tx/rx — which is exactly how the routed runner
        # PROVES zero payload bytes crossed the coordinator.
        counters.inc("xferd.forward.frames")
        timeseries.record("dcn.lane.forward.bytes", nbytes)
        timeseries.gauge_add("dcn.lane.forward.total_bytes", nbytes)
        with self._lock:
            f = self._flows.get(flow)
            if f is not None:
                f.transferred += nbytes
                self._total_transferred += nbytes
                self._publish_flow_gauges_locked()
        resp = {"ok": True, "bytes": nbytes,
                "micros": round(micros, 1),
                "gbps": round(nbytes * 8 / micros / 1e3, 3),
                "lane": "forward", "verdict": verdict,
                "attempts": used}
        return resp

    def _materialize(self, flow: str, offset: Optional[int],
                     nbytes: int, xid: Optional[str],
                     payload: Optional[bytes]) -> Optional[bytes]:
        """The staged bytes for a send that is about to ride a socket
        — copied under the lock (shm staging is a view of a mapping
        that may be remapped once we let go).  None when the flow or
        its staged range vanished since the stage-wait."""
        if payload is not None:
            return payload
        with self._lock:
            f = self._flows.get(flow)
            if f is None or not f.range_staged(offset or 0, nbytes,
                                               xid):
                return None
            return f.read_range(offset or 0, nbytes, xid)

    def _tcp_send(self, host: str, port: int, flow: str, payload: bytes,
                  seq: Optional[int], meta: dict) -> None:
        with socket.create_connection((host, port), timeout=30) as s:
            _set_nodelay(s)
            netio.sendall_parts(
                s, (encode_frame_header(flow, len(payload), seq, meta),
                    payload))

    def _peer_conn(self, conn_id: int, host: str, port: int) -> _PeerConn:
        key = (conn_id, host, port)
        with self._lock:
            pc = self._peer_conns.get(key)
            if pc is None:
                pc = self._peer_conns[key] = _PeerConn()
            return pc

    def _peer_lane(self, host: str, port: int) -> _PeerShmLane:
        key = (host, int(port))
        with self._lock:
            lane = self._peer_lanes.get(key)
            if lane is None:
                lane = self._peer_lanes[key] = _PeerShmLane()
            return lane

    def _range_view_locked(self, f: _Flow, offset: int, nbytes: int,
                           xid: Optional[str]):
        """A zero-copy view of staged bytes [offset, offset+nbytes)
        for the direct lane's segment→segment copy; None when not
        staged.  Caller holds the lock and must not let the view
        escape it — the backing mapping can be remapped the moment
        the lock is released."""
        if not f.range_staged(offset, nbytes, xid):
            return None
        if (f.frame_bytes and offset + nbytes <= len(f.staged)
                and (xid is None or not xid or f.asm_xid == xid)):
            return memoryview(f.staged)[offset:offset + nbytes]
        if f.asm_buf is None:
            return None
        return memoryview(f.asm_buf)[offset:offset + nbytes]

    def _lane_attach_locked(self, lane: _PeerShmLane, host: str,
                            port: int, flow: str,
                            need: int) -> Optional[_PeerSeg]:
        """The daemon↔daemon lane's shared preamble — one for BOTH
        handoff shapes, so the single-frame and batched paths can
        never diverge: probe the peer's co-hosted-ness once per
        endpoint (``DXH1``, cached tri-state), then hand back a mapped
        ``_PeerSeg`` of at least ``need`` bytes for the flow,
        attaching/re-attaching (``DXA1``) as required.  Returns None
        on refusals (cross-host peer cached un-counted; flow-level
        refusals counted as fallbacks); raises ``OSError`` upward for
        transport trouble — the caller owns the lane reset.  Caller
        holds ``lane.lock``."""
        if lane.usable is False:
            return None  # probed: cross-host or shm-less peer
        if lane.usable is None:
            resp = lane.request(host, port, _MAGIC_PEER_HELLO,
                                {"host_id": self.host_id,
                                 "node": self.node})
            if not (resp.get("ok") and resp.get("shm")
                    and resp.get("host_id") == self.host_id):
                # Not an error: the peer is simply not co-hosted (or
                # opted out).  Cache the verdict so every send does
                # not re-ask; a transport break later resets to
                # unprobed.
                lane.reset_locked(False)
                return None
            lane.usable = True
        seg = lane.segs.get(flow)
        if seg is None or seg.size < need:
            if seg is not None:
                seg.close()
                lane.segs.pop(flow, None)
            resp = lane.request(host, port, _MAGIC_PEER_ATTACH,
                                {"flow": flow, "bytes": need})
            if not resp.get("ok"):
                # Flow-level refusal (peer has no such flow yet, shm
                # disabled for it): this frame rides TCP and earns
                # its "unmatched" there.
                counters.inc("dcn.shm_direct.fallback")
                return None
            seg = _PeerSeg(resp.get("path", ""),
                           int(resp.get("bytes") or 0))
            if seg.size < need:
                seg.close()
                counters.inc("dcn.shm_direct.fallback")
                return None
            lane.segs[flow] = seg
        return seg

    def _shm_direct_try(self, flow: str, host: str, port: int,
                        offset: Optional[int], nbytes: int, tot: int,
                        xid: Optional[str], seq: Optional[int],
                        meta: dict,
                        payload: Optional[bytes] = None
                        ) -> Optional[str]:
        """One frame over the daemon↔daemon segment lane: memcpy the
        staged bytes into the co-hosted peer's segment through our own
        mapping of its file, then land them with a descriptor-only
        ``DXC1`` commit — zero payload bytes on any socket.  Returns
        the peer's landing verdict, or None when the lane is not
        available / broke, which is the caller's signal to ride TCP
        for THIS frame (transparent fallback; next frame re-probes
        when the failure was transport-shaped)."""
        lane = self._peer_lane(host, port)
        # Serializing the peer control stream is the contract, same as
        # _PeerConn: request/response pairs must not interleave.
        with lane.lock, lockwatch.blocking_ok(
                "xferd.shm_direct: peer control ops on one stream "
                "must not interleave"):
            verdict = None
            try:
                seg = self._lane_attach_locked(lane, host, port, flow,
                                               tot if tot else nbytes)
                if seg is None:
                    return None
                dst_off = offset or 0
                if payload is not None:
                    seg.map[dst_off:dst_off + nbytes] = payload
                else:
                    with self._lock:
                        f = self._flows.get(flow)
                        src = (None if f is None else
                               self._range_view_locked(f, dst_off,
                                                       nbytes, xid))
                        if src is None:
                            return None  # vanished since stage-wait
                        # Segment→segment memcpy, under the lock so
                        # the source view cannot be remapped mid-copy.
                        seg.map[dst_off:dst_off + nbytes] = src
                resp = lane.request(host, port, _MAGIC_PEER_COMMIT,
                                    {"flow": flow, "len": nbytes,
                                     "seq": seq, "ino": seg.ino,
                                     "meta": meta})
                if not resp.get("ok"):
                    counters.inc("dcn.shm_direct.fallback")
                    return None
                verdict = resp.get("verdict", "landed")
                if verdict == "rejected":
                    # Stale mapping (the peer released/recreated the
                    # segment — the inode check refused the landing)
                    # or refused geometry: drop the cached segment so
                    # the next attempt re-attaches, ride TCP now.
                    seg.close()
                    lane.segs.pop(flow, None)
                    counters.inc("dcn.shm_direct.fallback")
                    return None
            except (OSError, ConnectionError, ValueError) as e:
                # Transport or mapping trouble — the peer died, its
                # respawn wiped the segments, the stream broke.  Reset
                # to unprobed (the next send re-dials and re-probes;
                # a respawned peer binds a fresh port anyway) and let
                # THIS frame ride TCP.
                lane.reset_locked(None)
                counters.inc("dcn.shm_direct.fallback")
                log.warning("shm_direct lane to %s:%d failed (%s); "
                            "falling back to TCP", host, port, e)
                return None
        counters.inc("dcn.shm_direct.frames")
        return verdict

    def _stats(self, flow: Optional[str] = None) -> dict:
        """Daemon stats.  With ``flow`` set, the flows list holds just
        that flow's entry (one dict lookup) — the rx-wait poll path
        stops paying O(flows) per poll."""
        with self._lock:
            if flow is not None:
                f = self._flows.get(flow)
                items = [(flow, f)] if f is not None else []
            else:
                items = list(self._flows.items())
            return {
                "ok": True,
                "active_flows": len(self._flows),
                "total_transferred": self._total_transferred,
                "unmatched_frames": self._unmatched,
                "generation": self.generation,
                "node": self.node,
                "flows": [
                    {"flow": name, "peer": f.peer,
                     "transferred": f.transferred,
                     "rx_bytes": f.rx_bytes,
                     "frame_bytes": f.frame_bytes,
                     "max_seq": f.max_seq,
                     "shm": f.seg_map is not None}
                    for name, f in items
                ],
            }

    # -- shm lane (zero-copy same-host staging) ------------------------------

    def _ensure_segment_locked(self, flow: str, f: _Flow,
                               nbytes: int) -> None:
        """Create (or grow) ``flow``'s mmap segment to >= ``nbytes``
        and move every live staging buffer into the current mapping —
        heap content is copied once, old-mapping views are repointed
        (same inode, same bytes).  After this, "the flow has a
        segment" always implies "the flow's bytes are readable through
        it".  Caller holds the lock; raises ``OSError`` on filesystem
        trouble (the client's fallback signal)."""
        need = max(int(nbytes), SHM_MIN_SEGMENT)
        old_map = None
        remapped = False
        if f.seg_map is None or f.seg_size < need:
            os.makedirs(self.shm_dir, exist_ok=True)
            path = f.seg_path or os.path.join(
                self.shm_dir,
                hashlib.sha1(flow.encode()).hexdigest()[:16] + ".seg")
            size = max(need, f.seg_size)
            fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
            try:
                os.ftruncate(fd, size)
                ino = os.fstat(fd).st_ino
                new_map = mmap.mmap(fd, size)
            finally:
                os.close(fd)
            f.seg_ino = ino
            if f.seg_map is None:
                timeseries.gauge_add("dcn.shm.segments", 1)
            old_map = f.seg_map
            f.seg_map, f.seg_path, f.seg_size = new_map, path, size
            remapped = True
        view = memoryview(f.seg_map)
        if f.asm_buf is not None and f.asm_total <= f.seg_size:
            staged_is_asm = f.staged is f.asm_buf
            if isinstance(f.asm_buf, bytearray):
                view[:f.asm_total] = f.asm_buf  # heap -> segment, once
                f.asm_buf = view[:f.asm_total]
                # The buffer identity changed: in-flight recv-into
                # landings against the heap buffer must drop as stale
                # (their bytes moved out from under them).
                f.asm_gen = next(_ASM_GEN)
            elif remapped:  # old-mapping view: repoint, no copy
                f.asm_buf = view[:f.asm_total]
                f.asm_gen = next(_ASM_GEN)
            if staged_is_asm:
                f.staged = f.asm_buf
        if isinstance(f.staged, (bytes, bytearray)) and f.frame_bytes \
                and f.frame_bytes <= f.seg_size:
            view[:f.frame_bytes] = f.staged
            f.staged = view[:f.frame_bytes]
        elif (isinstance(f.staged, memoryview) and remapped
                and f.staged is not f.asm_buf):
            f.staged = view[:len(f.staged)]
        if old_map is not None:
            try:
                old_map.close()
            except (BufferError, ValueError):
                pass  # an exported slice keeps it alive until GC

    def _shm_attach(self, req: dict) -> dict:
        """Hand the client a per-flow segment (path + mapped size).
        Idempotent; growing re-truncates the same inode so existing
        content — and existing client mappings of the old range —
        stay valid.  ``ring: 1`` additionally creates (or reuses) the
        flow's descriptor-ring file for the shm_post handoff; daemons
        that predate the ring simply never return ``ring_path``, which
        is the client's signal to fall back to per-chunk sends."""
        if not self.shm_enabled:
            return {"ok": False, "error": "shm lane disabled"}
        flow = req["flow"]
        nbytes = int(req.get("bytes") or 0)
        if nbytes < 0:
            return {"ok": False, "error": "invalid 'bytes'"}
        with self._lock:
            f = self._flows.get(flow)
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            try:
                self._ensure_segment_locked(flow, f, nbytes)
            except OSError as e:
                return {"ok": False, "error": f"shm attach failed: {e}"}
            resp = {"ok": True, "path": f.seg_path,
                    "bytes": f.seg_size, "frame_bytes": f.frame_bytes}
            if req.get("ring") and self.ring_enabled:
                try:
                    self._ensure_ring_locked(flow, f)
                except OSError as e:
                    # The segment is fine — only the handoff is not.
                    # The client runs per-chunk control ops instead.
                    log.warning("ring for flow %r unavailable: %s",
                                flow, e)
                else:
                    resp.update(ring_path=f.ring_path,
                                ring_slots=RING_SLOTS)
            return resp

    def _ring_attach(self, req: dict) -> dict:
        """Universal-ring attach: the descriptor ring WITHOUT a data
        segment — the socket lane's entry point, where payload bytes
        still ride the data plane but submission and completion ride
        the mmapped ring.  Daemons that predate the op answer
        "unknown op", the client's classic-path downgrade signal."""
        if not self.ring_enabled:
            return {"ok": False, "error": "ring disabled"}
        flow = req["flow"]
        with self._lock:
            f = self._flows.get(flow)
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            try:
                self._ensure_ring_locked(flow, f)
            except OSError as e:
                return {"ok": False, "error": f"ring attach failed: {e}"}
            return {"ok": True, "ring_path": f.ring_path,
                    "ring_slots": RING_SLOTS}

    def _ensure_ring_locked(self, flow: str, f: _Flow) -> None:
        """Create and map the flow's descriptor-ring file under
        shm_dir (RING_SLOTS slots).  The path is derived from the
        flow name, NOT the segment path — the universal ring exists
        on lanes that never attach a segment.  Caller holds the
        lock."""
        if f.ring_map is not None:
            return
        os.makedirs(self.shm_dir, exist_ok=True)
        path = os.path.join(
            self.shm_dir,
            hashlib.sha1(flow.encode()).hexdigest()[:16] + ".ring")
        size = dcn_shm.ring_bytes(RING_SLOTS)
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            os.ftruncate(fd, size)
            m = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        dcn_shm.RingView(m).init(RING_SLOTS)
        f.ring_path, f.ring_map = path, m

    def _shm_commit(self, req: dict) -> dict:
        """Declare ``[0, bytes)`` of the flow's segment a completed
        staged frame — the zero-copy analog of a whole-payload ``put``.
        The landing happens IN PLACE: no payload bytes cross a socket,
        but the bookkeeping (rx accounting, wait wakeups, assembly
        invalidation) is the same ``land_frame`` every other staging
        path uses.  Commits are seq-less staging, dedup-exempt and
        idempotent by construction — a restage after a failed round
        simply commits again.

        Range mode (``offset`` + ``total``): declare just
        ``[offset, offset+bytes)`` staged — the producer-fed overlap
        path commits each chunk as it is produced, and the chunk
        lands through the same in-place assembly bookkeeping the
        daemon↔daemon DXC1 lane uses."""
        if not self.shm_enabled:
            return {"ok": False, "error": "shm lane disabled"}
        flow = req["flow"]
        nbytes = int(req.get("bytes") or 0)
        xid = req.get("xid") or ""
        offset = req.get("offset")
        if nbytes <= 0:
            return {"ok": False, "error": "shm commit needs bytes > 0"}
        if offset is not None:
            offset = int(offset)
            total = int(req.get("total") or 0)
            if offset < 0 or total <= 0 or offset + nbytes > total:
                return {"ok": False,
                        "error": f"commit range out of bounds: "
                                 f"[{offset}:{offset + nbytes}) "
                                 f"of {total}"}
            need = total
        else:
            need = nbytes
        with self._lock:
            f = self._flows.get(flow)
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            if f.seg_map is None or f.seg_size < need:
                return {"ok": False,
                        "error": "no shm segment attached for "
                                 f"{need} bytes; shm_attach first"}
            view = f.seg_view(need)
        # The per-node attribution histogram the grey-failure detector
        # compares across peers (obs/anomaly.py) — the commit INCLUDING
        # any armed slow_shm throttle, so a throttled node's windowed
        # p99 separates from its peers' while every health check stays
        # green.
        commit_t0 = time.monotonic()
        delay_s = min(max(self._shm_delay_s, 0.0), 2.0)
        if delay_s:
            time.sleep(delay_s)
        if offset is not None:
            meta = {"off": offset, "tot": need}
            if xid:
                meta["xid"] = xid
            verdict = self.land_frame(
                flow, view[offset:offset + nbytes], None, meta,
                in_place=True)
            ok = verdict in ("landed", "dup")
        else:
            verdict = self.land_frame(flow, view, None,
                                      {"xid": xid} if xid else {},
                                      in_place=True)
            ok = verdict == "landed"
        histo.observe("xferd.shm.commit",
                      time.monotonic() - commit_t0)
        if not ok:
            return {"ok": False,
                    "error": f"shm commit not landed: {verdict}"}
        counters.inc("dcn.shm.commits")
        return {"ok": True, "bytes": nbytes, "verdict": verdict}

    def _shm_read(self, req: dict) -> dict:
        """Make the flow's completed frame readable through its
        segment and say where: frames that landed into heap buffers
        (the flow was never attached, or the segment was too small)
        are migrated in with one copy — still one copy fewer than any
        socket read-back.  The client maps the returned path and
        slices; no payload bytes cross the control socket."""
        if not self.shm_enabled:
            return {"ok": False, "error": "shm lane disabled"}
        flow = req["flow"]
        nbytes = int(req.get("bytes") or 0)
        with self._lock:
            f = self._flows.get(flow)
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            if not f.frame_bytes:
                return {"ok": False,
                        "error": "no completed frame staged"}
            try:
                self._ensure_segment_locked(
                    flow, f, max(nbytes, f.frame_bytes))
            except OSError as e:
                return {"ok": False, "error": f"shm read failed: {e}"}
            return {"ok": True, "path": f.seg_path,
                    "bytes": f.seg_size, "frame_bytes": f.frame_bytes}

    def _shm_post(self, req: dict) -> dict:
        """The descriptor-ring doorbell: ONE control op per round
        instead of one per chunk.  Validates the posted descriptors
        out of the daemon's own ring mapping, hands them to the
        completer thread, and returns immediately — completion is
        published INTO the ring (per-slot verdict codes + a cursor)
        for the client to poll out of shared memory.  Gated on the
        UNIVERSAL ring capability, not shm: socket-lane rounds post
        through the same doorbell."""
        if not self.ring_enabled:
            return {"ok": False, "error": "ring disabled"}
        flow = req["flow"]
        count = int(req.get("count") or 0)
        rnd = int(req.get("round") or 0)
        total = int(req.get("total") or 0)
        if count <= 0 or count > RING_SLOTS or total <= 0:
            return {"ok": False, "error": "invalid ring post geometry"}
        q = self._ring_q
        if q is None:
            return {"ok": False, "error": "daemon stopping"}
        with self._lock:
            f = self._flows.get(flow)
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            if f.ring_map is None:
                return {"ok": False,
                        "error": "no ring attached; shm_attach with "
                                 "ring first"}
            try:
                descs = dcn_shm.RingView(f.ring_map).read_descs(count)
            except (OSError, struct.error) as e:
                return {"ok": False, "error": f"bad ring: {e}"}
            for off, ln, _seq in descs:
                if ln <= 0 or off + ln > total:
                    return {"ok": False,
                            "error": f"descriptor out of bounds: "
                                     f"[{off}:{off + ln}) of {total}"}
        post = {
            "flow": flow, "descs": descs, "round": rnd,
            "total": total, "xid": req.get("xid") or "",
            "host": req.get("host", "127.0.0.1"),
            "port": int(req["port"]),
            "direct": req.get("direct"),
            "stage_wait_ms": req.get("stage_wait_ms"),
            "ctx": trace.context(),
        }
        q.put(post)
        counters.inc("dcn.shm.ring.posts")
        return {"ok": True, "accepted": count, "round": rnd}

    def _ring_completer(self, q: "queue.Queue") -> None:
        """The handoff's work loop: drain posted rounds, drive every
        descriptor through the NORMAL send path — stage-wait, link
        shim, lane selection (shm_direct included), verdicts — and
        publish per-slot status + the completion cursor into the
        flow's ring.  Ring writes are lock-free by layout contract
        (single writer per field); flow state is only ever touched
        through _send's own locking."""
        while True:
            post = q.get()
            if post is None or self._stopping.is_set():
                return
            ctx = post["ctx"] or {}
            with trace.attach(ctx.get("trace"), ctx.get("span")):
                self._complete_post(post)

    def _complete_post(self, post: dict) -> None:
        flow = post["flow"]
        with self._lock:
            f = self._flows.get(flow)
            ring = f.ring_map if f is not None else None
        if ring is None:
            return  # flow released between doorbell and completion
        view = dcn_shm.RingView(ring)
        try:
            view.begin_round(post["round"])
        except (ValueError, struct.error):
            return  # ring unmapped under us (release/stop race)
        # ONE stage-wait budget for the whole round, batch attempt
        # included: a dead stager must cost this thread at most one
        # budget, never batch-budget + fallback-budget (every other
        # flow's posted rounds queue behind this one).
        budget_s = min(float(post.get("stage_wait_ms")
                             or CHUNK_STAGE_WAIT_S * 1e3) / 1e3,
                       CHUNK_STAGE_WAIT_S)
        deadline = time.monotonic() + budget_s
        # Grey-fault hook: a SLOW completer (soak "slow_ring") pays
        # the delay per descriptor on the serial path — partial
        # progress stays visible in the cursor, which is exactly what
        # the sentinels must distinguish from a dead completer.  The
        # batch fast path is skipped while armed (a busy completer
        # does not get the one-copy shortcut).
        delay_s = min(max(self._ring_delay_s, 0.0), 2.0)
        # Whole-round fast path: when the peer is co-hosted, the round
        # completes as ONE segment→segment copy plus ONE batched DXC1
        # — zero per-chunk round trips end to end, which is the
        # descriptor-handoff promise kept on the daemon→daemon leg
        # too.  Any trouble falls through to the per-descriptor path.
        verdicts = (None if delay_s
                    else self._ring_batch_direct(post, deadline))
        if verdicts is not None:
            done = 0
            for i, verdict in enumerate(verdicts):
                done += 1
                status = dcn_shm.RING_STATUS_BY_VERDICT.get(
                    verdict, dcn_shm.RING_ERROR)
                try:
                    view.complete(i, status, done)
                except (ValueError, struct.error):
                    return
            return
        # Per-descriptor fallback, still under the SAME deadline: once
        # the budget is spent, every remaining descriptor fails fast
        # instead of re-paying the wait serially.
        done = 0
        for i, (off, ln, seq) in enumerate(post["descs"]):
            if self._stopping.is_set():
                return
            # Per-descriptor drive latency, slow_ring throttle
            # included: the ring plane's attribution histogram for the
            # grey-failure detector — a crawling completer's p99
            # separates from its peers' while the cursor stays green.
            drive_t0 = time.monotonic()
            if delay_s:
                time.sleep(delay_s)
            remaining_ms = max(1, int((deadline - time.monotonic())
                                      * 1e3))
            req = {"op": "send", "flow": flow, "host": post["host"],
                   "port": post["port"], "seq": seq, "offset": off,
                   "bytes": ln, "total": post["total"],
                   "xid": post["xid"],
                   "stage_wait_ms": remaining_ms}
            if post.get("direct") is not None:
                req["direct"] = post["direct"]
            try:
                resp = self._send(f"ring:{flow}", req)
            except Exception:  # noqa: BLE001 — status must publish
                log.exception("ring send failed (flow %r chunk %d)",
                              flow, i)
                resp = {"ok": False}
            histo.observe("xferd.ring.drive",
                          time.monotonic() - drive_t0)
            if resp.get("ok"):
                status = dcn_shm.RING_STATUS_BY_VERDICT.get(
                    resp.get("verdict", "sent"), dcn_shm.RING_ERROR)
            else:
                status = dcn_shm.RING_ERROR
            done += 1
            try:
                view.complete(i, status, done)
            except (ValueError, struct.error):
                return  # ring unmapped (flow released mid-round)

    def _ring_batch_direct(self, post: dict, deadline: float):
        """Complete one posted round over the daemon↔daemon lane as a
        single unit: wait once for the whole frame to stage, memcpy
        every descriptor's range segment→segment, and land them all
        with ONE multi-descriptor DXC1.  Returns the per-descriptor
        verdict list (aligned with the post), or None when the batch
        path does not apply — no direct lane, link faults armed (the
        shim is per-frame; the per-descriptor path owns that), the
        staging never completed — in which case the caller runs the
        per-descriptor completion instead (under the SAME deadline:
        the two paths share one stage-wait budget)."""
        if self.net is not None or not self.shm_direct \
                or post.get("direct") in (0, "0", False):
            return None
        flow, total, xid = post["flow"], post["total"], post["xid"]
        host, port = post["host"], post["port"]
        with self._lock:
            if self._link_faults:
                return None  # injected faults are per-frame territory
        with self._landed:
            staged = self._landed.wait_for(
                lambda: (self._flows.get(flow) is None
                         or self._flows[flow].range_staged(0, total,
                                                           xid)),
                timeout=max(0.0, deadline - time.monotonic()),
            )
            if self._flows.get(flow) is None or not staged:
                return None
        meta = {"src": self.node, "tot": total, "xid": xid}
        ctx = trace.context()
        if ctx is not None:
            meta.update(ctx)
        descs = post["descs"]
        nbytes = sum(ln for _off, ln, _seq in descs)
        t0 = time.monotonic()
        with trace.span("xferd.send", histogram="xferd.send",
                        flow=flow, node=self.node,
                        dst=f"{host}:{port}", bytes=nbytes,
                        chunks=len(descs)) as span:
            verdicts = self._shm_direct_try_batch(flow, host, port,
                                                  descs, total, xid,
                                                  meta)
            if verdicts is None:
                return None
            span.annotate(lane="shm_direct")
        micros = max(1.0, (time.monotonic() - t0) * 1e6)
        timeseries.record("dcn.lane.shm_direct.bytes", nbytes)
        timeseries.gauge_add("dcn.lane.shm_direct.total_bytes", nbytes)
        with self._lock:
            f = self._flows.get(flow)
            if f is not None:
                f.transferred += nbytes
                self._total_transferred += nbytes
                self._publish_flow_gauges_locked()
        log.debug("ring batch of %d chunks (%d bytes) completed in "
                  "%.0f us", len(descs), nbytes, micros)
        return verdicts

    def _shm_direct_try_batch(self, flow: str, host: str, port: int,
                              descs, total: int, xid: str,
                              meta: dict):
        """The batched sibling of _shm_direct_try: one handshake/
        attach (cached), one copy pass over every descriptor range,
        ONE DXC1 carrying the descriptor list.  Returns the verdict
        list or None (caller falls back per-descriptor)."""
        lane = self._peer_lane(host, port)
        with lane.lock, lockwatch.blocking_ok(
                "xferd.shm_direct: peer control ops on one stream "
                "must not interleave"):
            try:
                seg = self._lane_attach_locked(lane, host, port, flow,
                                               total)
                if seg is None:
                    return None
                with self._lock:
                    f = self._flows.get(flow)
                    if f is None:
                        return None
                    for off, ln, _seq in descs:
                        src = self._range_view_locked(f, off, ln, xid)
                        if src is None:
                            return None
                        seg.map[off:off + ln] = src
                resp = lane.request(
                    host, port, _MAGIC_PEER_COMMIT,
                    {"flow": flow, "ino": seg.ino, "meta": meta,
                     "descs": [{"off": off, "len": ln, "seq": seq}
                               for off, ln, seq in descs]})
                if not resp.get("ok"):
                    counters.inc("dcn.shm_direct.fallback")
                    return None
                verdicts = resp.get("verdicts")
                if (not isinstance(verdicts, list)
                        or len(verdicts) != len(descs)):
                    counters.inc("dcn.shm_direct.fallback")
                    return None
                if all(v == "rejected" for v in verdicts):
                    # Stale mapping: drop the cached segment, let the
                    # per-descriptor path re-attach and retry.
                    seg.close()
                    lane.segs.pop(flow, None)
                    counters.inc("dcn.shm_direct.fallback")
                    return None
            except (OSError, ConnectionError, ValueError) as e:
                lane.reset_locked(None)
                counters.inc("dcn.shm_direct.fallback")
                log.warning("shm_direct batch to %s:%d failed (%s); "
                            "falling back", host, port, e)
                return None
        counters.inc("dcn.shm_direct.frames", len(descs))
        return verdicts

    # -- data plane ----------------------------------------------------------

    def _data_accept_loop(self) -> None:
        srv = self._data_server
        while not self._stopping.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            if self._stopping.is_set():
                conn.close()
                return
            threading.Thread(target=self._serve_data_conn, args=(conn,),
                             name=f"pyxferd-dconn-{self.node}",
                             daemon=True).start()

    def _serve_data_conn(self, conn: socket.socket) -> None:
        _set_nodelay(conn)
        with self._lock:
            self._conns.add(conn)
        try:
            while not self._stopping.is_set():
                try:
                    magic = _recv_exact(conn, 4)
                except (ConnectionError, OSError):
                    return
                if magic == _MAGIC_READ:
                    if not self._serve_data_read(conn):
                        return
                    continue
                if magic in _PEER_OPS:
                    if not self._serve_peer_op(conn, magic):
                        return
                    continue
                try:
                    hdr = self._read_frame_header(conn, magic)
                except (ConnectionError, OSError, ValueError) as e:
                    log.error("bad data-plane frame: %s", e)
                    return
                try:
                    self._recv_and_land(conn, *hdr)
                except (ConnectionError, OSError):
                    # Died mid-payload: the chunk was never recorded,
                    # so partial bytes stay invisible (see
                    # _recv_and_land).
                    return
        finally:
            conn.close()
            with self._lock:
                self._conns.discard(conn)

    def _serve_data_read(self, conn: socket.socket) -> bool:
        """Answer one DXR1 read request: u64 LE length + raw staged
        bytes (bounded by the last COMPLETED frame — an assembling flow
        reads empty, exactly like the control-plane read's contract).
        Raw TCP instead of base64-over-JSON is what makes the striped
        reader's read-back leg cheap.  Returns False on a dead conn."""
        try:
            name_len = struct.unpack("<I", _recv_exact(conn, 4))[0]
            offset = struct.unpack("<Q", _recv_exact(conn, 8))[0]
            nbytes = struct.unpack("<Q", _recv_exact(conn, 8))[0]
            if name_len > 4096 or nbytes > (1 << 31):
                raise ValueError("read request out of bounds")
            flow = _recv_exact(conn, name_len).decode()
        except (ConnectionError, OSError, ValueError) as e:
            log.error("bad data-plane read request: %s", e)
            return False
        with self._lock:
            f = self._flows.get(flow)
            if f is None or not f.frame_bytes:
                data = b""
            else:
                end = min(offset + nbytes, f.frame_bytes,
                          len(f.staged))
                # bytes() under the lock — shm staging is a view.
                data = bytes(f.staged[offset:end]) if offset < end \
                    else b""
        try:
            netio.sendall_parts(conn, (struct.pack("<Q", len(data)),
                                       data))
        except OSError:
            return False
        return True

    def _serve_peer_op(self, conn: socket.socket, magic: bytes) -> bool:
        """One daemon↔daemon shm-lane request/response pair (DXH1 /
        DXA1 / DXC1): u32 LE length + JSON both ways, control-sized —
        the payload bytes these ops are ABOUT move through the shared
        segment, never this socket.  Returns False on a dead conn."""
        try:
            n = struct.unpack("<I", _recv_exact(conn, 4))[0]
            if n > 65536:
                raise ValueError("peer op request out of bounds")
            req = json.loads(_recv_exact(conn, n))
        except (ConnectionError, OSError, ValueError) as e:
            log.error("bad peer shm op: %s", e)
            return False
        try:
            if magic == _MAGIC_PEER_HELLO:
                resp = {"ok": True, "host_id": self.host_id,
                        "shm": 1 if self.shm_enabled else 0}
            elif magic == _MAGIC_PEER_ATTACH:
                resp = self._peer_attach(req)
            else:
                resp = self._peer_commit(req)
        except (KeyError, TypeError, ValueError) as e:
            resp = {"ok": False, "error": f"bad peer request: {e}"}
        body = json.dumps(resp).encode()
        try:
            netio.sendall_parts(conn, (struct.pack("<I", len(body)),
                                       body))
        except OSError:
            return False
        return True

    def _peer_attach(self, req: dict) -> dict:
        """A co-hosted peer daemon asks for the flow's segment so it
        can land frames by memcpy.  Same machinery as the client-side
        shm_attach, plus the segment's inode — the commit-time
        staleness check that makes a released-and-recreated segment a
        loud ``rejected`` instead of silent corruption."""
        if not self.shm_enabled:
            return {"ok": False, "error": "shm lane disabled"}
        flow = req["flow"]
        nbytes = int(req.get("bytes") or 0)
        if nbytes <= 0:
            return {"ok": False, "error": "invalid 'bytes'"}
        with self._lock:
            f = self._flows.get(flow)
            if f is None:
                return {"ok": False, "error": "unknown flow"}
            try:
                self._ensure_segment_locked(flow, f, nbytes)
            except OSError as e:
                return {"ok": False,
                        "error": f"peer attach failed: {e}"}
            return {"ok": True, "path": f.seg_path,
                    "bytes": f.seg_size, "ino": f.seg_ino}

    def _peer_commit(self, req: dict) -> dict:
        """Land frame(s) whose bytes a co-hosted peer daemon already
        memcpy'd into this flow's segment.  All the authority —
        dedup, geometry checks, accounting, wait wakeups — is the
        same ``land_frame`` every other path uses; only the payload
        copy is skipped.  The quoted inode must match the segment the
        flow currently owns.  A ``descs`` list lands a whole posted
        round in one request — per-descriptor verdicts come back as
        ``verdicts`` (aligned), so exactly-once stays chunk-granular
        while the control cost is one round trip."""
        if not self.shm_enabled:
            return {"ok": False, "error": "shm lane disabled"}
        flow = req["flow"]
        meta = req.get("meta") or {}
        ino = int(req.get("ino") or 0)
        descs = req.get("descs")
        if descs is not None:
            tot = int(meta.get("tot") or 0)
            xid = meta.get("xid") or ""
            verdicts = []
            for d in descs:
                seq = d.get("seq")
                verdicts.append(self._peer_commit_chunk(
                    flow, int(d.get("off", -1)),
                    int(d.get("len") or 0),
                    int(seq) if seq is not None else None,
                    tot, xid, meta, ino))
            return {"ok": True, "verdicts": verdicts}
        nbytes = int(req.get("len") or 0)
        seq = req.get("seq")
        seq = int(seq) if seq is not None else None
        if nbytes <= 0:
            return {"ok": False, "error": "invalid 'len'"}
        off = meta.get("off")
        if off is not None:
            verdict = self._peer_commit_chunk(
                flow, int(off), nbytes, seq,
                int(meta.get("tot") or 0), meta.get("xid") or "",
                meta, ino)
            return {"ok": True, "verdict": verdict}
        with self._lock:
            f = self._flows.get(flow)
            if f is None:
                # land_frame would also answer unmatched, but without
                # a flow there is no segment to have written into.
                self._unmatched += 1
                return {"ok": True, "verdict": "unmatched"}
            if (f.seg_map is None or ino != f.seg_ino
                    or f.seg_size < nbytes):
                return {"ok": True, "verdict": "rejected"}
            payload = f.seg_view(nbytes)
        verdict = self.land_frame(flow, payload, seq, meta,
                                  in_place=True)
        return {"ok": True, "verdict": verdict}

    def _peer_commit_chunk(self, flow: str, off: int, nbytes: int,
                           seq: Optional[int], tot: int, xid: str,
                           meta: dict, ino: int) -> str:
        """One chunk's descriptor-only landing (bytes already in the
        segment): verify inode + geometry, make the assembly
        segment-backed under the SAME lock hold that captures the
        generation, then let land_frame referee dedup and record."""
        if nbytes <= 0:
            return "rejected"
        with self._lock:
            f = self._flows.get(flow)
            if f is None:
                self._unmatched += 1
                return "unmatched"
            if f.seg_map is None or ino != f.seg_ino:
                return "rejected"
            if (tot <= 0 or off < 0 or off + nbytes > tot
                    or f.seg_size < tot):
                return "rejected"
            if xid in f.retired_xids:
                # Straggler commit for a transfer this flow moved
                # past: refuse before it can reset the live assembly.
                return "rejected"
            buf = self._ensure_assembly_locked(f, xid, tot)
            if not isinstance(buf, memoryview):
                return "rejected"
            payload = buf[off:off + nbytes]
            gen = f.asm_gen
        meta_d = dict(meta, off=off, tot=tot, xid=xid)
        return self.land_frame(flow, payload, seq, meta_d,
                               preloaded_gen=gen)

    def _read_frame_header(self, conn: socket.socket, magic: bytes
                           ) -> Tuple[str, int, Optional[int], dict]:
        """Everything BEFORE the payload: (flow, payload_len, seq,
        meta).  The payload itself is received by _recv_and_land —
        straight into the flow's assembly buffer when it can be."""
        if magic == _MAGIC_V1:
            name_len, payload_len = struct.unpack(
                "<IQ", _recv_exact(conn, 12))
            seq, meta_len = None, 0
        elif magic == _MAGIC_V2:
            name_len, payload_len, seq, meta_len = struct.unpack(
                "<IQQI", _recv_exact(conn, 24))
        else:
            raise ValueError(f"unknown frame magic {magic!r}")
        if name_len > 4096 or payload_len > (1 << 31) or meta_len > 65536:
            raise ValueError("frame header out of bounds")
        flow = _recv_exact(conn, name_len).decode()
        meta = {}
        if meta_len:
            try:
                meta = json.loads(_recv_exact(conn, meta_len))
            except ValueError:
                meta = {}
        return flow, payload_len, seq, meta

    def _recv_and_land(self, conn: socket.socket, flow: str,
                       payload_len: int, seq: Optional[int],
                       meta: dict) -> None:
        """Receive one frame's payload and land it.

        The recv-into-mmap path (ISSUE 13): a chunk frame whose flow
        can assemble it is received DIRECTLY into the assembly buffer
        at its offset — a segment view for shm-attached flows, the
        heap bytearray otherwise — deleting the per-chunk heap bounce.
        Safety is two-phase: the dedup window is pre-checked (without
        marking) when the target view is carved out, and re-checked —
        then marked — when the landing is recorded, so two streams
        racing the same seq still land exactly once (both writes carry
        identical bytes).  A receive that DIES mid-chunk leaves the
        chunk unrecorded: its partial bytes sit in a region
        ``range_staged`` does not count, so the frame can never
        complete around them and the retransmit overwrites them — the
        same partial-assembly invisibility the copy path had
        (``dcn.chunks.torn``).  A landing whose assembly was reset
        mid-receive (new xid, segment migration) is dropped via the
        generation check, never recorded into the wrong transfer
        (``dcn.chunks.stale_drop``).

        Everything that can't target an assembly — v1 frames, whole-
        payload frames, unknown flows (which must still drain the
        stream), dup-in-advance chunks, bad geometry — takes the old
        receive-then-land path unchanged."""
        target = None
        gen = None
        off = meta.get("off")
        # Forward frames carry off/tot too, but they land into the
        # flow's COMPLETED staging (possibly combining), never into an
        # assembly — the copy path below is their only correct route.
        if off is not None and seq is not None and not meta.get("fwd"):
            try:
                off = int(off)
                tot = int(meta.get("tot") or 0)
            except (TypeError, ValueError):
                off, tot = -1, 0
            xid = meta.get("xid") or ""
            with self._lock:
                f = self._flows.get(flow)
                if (f is not None and tot > 0 and 0 <= off
                        and off + payload_len <= tot
                        and xid not in f.retired_xids
                        and not (seq and (seq in f.seen_seqs
                                          or (f.max_seq - seq)
                                          >= DEDUP_WINDOW))):
                    buf = self._ensure_assembly_locked(f, xid, tot)
                    target = memoryview(buf)[off:off + payload_len]
                    gen = f.asm_gen
        if target is None:
            payload = _recv_exact(conn, payload_len)
            self.land_frame(flow, payload, seq, meta)
            return
        try:
            netio.recv_exact_into(conn, target)
        except (ConnectionError, OSError):
            counters.inc("dcn.chunks.torn")
            raise
        self.land_frame(flow, target, seq, meta, preloaded_gen=gen)

    def land_frame(self, flow: str, payload,
                   seq: Optional[int] = None, meta: Optional[dict] = None,
                   link: Optional[Tuple[str, str]] = None,
                   in_place: bool = False,
                   preloaded_gen: Optional[int] = None) -> str:
        """Land one frame into a flow's staging buffer.

        Returns "landed", "dup" (seq already landed — dropped without
        touching accounting, the exactly-once half of frame
        sequencing), "rejected" (malformed chunk geometry), or
        "unmatched" (no such flow registered here).  A frame whose meta
        carries ``off``/``tot`` is a CHUNK: it lands at its offset into
        the flow's assembly buffer, and the completed frame becomes
        visible only once every byte of ``tot`` has landed — a reader
        can never observe a half-assembled payload.  Seq 0 (or a v1
        frame) bypasses dedup: that is local staging, idempotent by
        construction.  Landing joins the SENDER's trace via the frame
        meta.

        ``in_place=True`` (the shm commit path) means the payload
        bytes already live in the flow's segment: the landing does all
        the bookkeeping — accounting, wait wakeups, assembly
        invalidation — without ever copying the payload.

        ``preloaded_gen`` (the recv-into-mmap and DXC1 paths) means a
        CHUNK's bytes were already written into the assembly buffer of
        generation ``preloaded_gen``: the landing skips the copy and,
        when the assembly has moved on since (reset, new xid, buffer
        migration), DROPS the record instead of attributing foreign
        bytes to the live transfer ("stale").
        """
        meta = meta or {}
        # Waiters are woken AFTER the span context closes (the finally
        # below, a second short lock hold): the span's JSONL record is
        # written at context exit, so anything a wait-op client does
        # after its wakeup — including scraping this daemon's trace
        # file — happens-after the record exists.  Notifying inside
        # the span (the old shape) let a woken reader race the flush,
        # the cross-process trace test's flake.
        notify = False
        try:
            with trace.attach(meta.get("trace"), meta.get("span")):
                with trace.span("xferd.land", histogram="xferd.land",
                                flow=flow, node=self.node, seq=seq,
                                bytes=len(payload),
                                src=meta.get("src", "")) as span:
                    with self._lock:
                        f = self._flows.get(flow)
                        if f is None:
                            self._unmatched += 1
                            span.annotate(verdict="unmatched")
                            return "unmatched"
                        if preloaded_gen is not None \
                                and (f.asm_gen != preloaded_gen
                                     or f.asm_buf is None):
                            # The assembly this chunk was received
                            # into no longer exists (reset, new xid,
                            # migration): drop BEFORE the seq is
                            # marked seen, so the retransmit of these
                            # bytes can still land.
                            counters.inc("dcn.chunks.stale_drop")
                            span.annotate(verdict="stale")
                            return "stale"
                        if (meta.get("off") is not None
                                and not meta.get("fwd")
                                and (meta.get("xid") or "")
                                in f.retired_xids):
                            # A straggler from a transfer this flow
                            # moved past (a ring completer's late
                            # send, a slow retransmit): dropping it —
                            # seq unmarked — keeps the LIVE assembly
                            # intact instead of letting the dead xid
                            # reset it.
                            counters.inc("dcn.chunks.stale_drop")
                            span.annotate(verdict="stale")
                            return "stale"
                        if seq:  # seq 0 == staging, dedup-exempt
                            if (seq in f.seen_seqs
                                    or (f.max_seq - seq)
                                    >= DEDUP_WINDOW):
                                span.annotate(verdict="dup")
                                counters.inc("dcn.frames.deduped")
                                return "dup"
                            f.seen_seqs.add(seq)
                            f.max_seq = max(f.max_seq, seq)
                            # Bound the window: forget fallen-out
                            # seqs.
                            if len(f.seen_seqs) > 2 * DEDUP_WINDOW:
                                floor = f.max_seq - DEDUP_WINDOW
                                f.seen_seqs = {s for s in f.seen_seqs
                                               if s >= floor}
                        verdict = self._land_locked(flow, f, payload,
                                                    meta, seq,
                                                    in_place,
                                                    preloaded_gen)
                        notify = True
                    span.annotate(verdict=verdict)
                    if verdict == "landed":
                        # Goodput = bytes that landed USEFULLY: dups
                        # and link-eaten frames never reach here.  A
                        # frame is remote-origin when it rode the
                        # fleet fabric or carries a sender's node
                        # stamp; everything else is local staging,
                        # tracked as its own series so the stage rate
                        # never inflates goodput.
                        remote = (link is not None
                                  or bool(meta.get("src")))
                        if remote:
                            # Cumulative landed-frame count: the
                            # scrapeable denominator for fleet
                            # dedup/retransmit ratios when there is no
                            # link table to read (the process-mode
                            # aggregator's HTTP path).
                            counters.inc("xferd.frames.landed")
                            timeseries.record("xferd.rx.bytes",
                                              len(payload))
                            timeseries.record(f"goodput.flow.{flow}",
                                              len(payload))
                            if self.node:
                                timeseries.record(
                                    f"goodput.node.{self.node}",
                                    len(payload))
                            if link is not None:
                                timeseries.record(
                                    f"goodput.link."
                                    f"{link[0]}->{link[1]}",
                                    len(payload))
                        else:
                            timeseries.record("xferd.stage.bytes",
                                              len(payload))
                    return verdict
        finally:
            if notify:
                with self._lock:
                    self._landed.notify_all()

    def _ensure_assembly_locked(self, f: _Flow, xid: str,
                                tot: int):
        """The flow's assembly buffer for transfer ``xid`` of ``tot``
        bytes, creating it (and discarding a stale one — un-seeing its
        seqs, invalidating the completed frame, bumping the
        generation) when the flow is not already assembling exactly
        that.  Caller holds the lock."""
        if f.asm_xid != xid or f.asm_total != tot or f.asm_buf is None:
            # First chunk of a new logical transfer (or a retry under a
            # fresh xid): discard the old assembly — un-seeing its seqs
            # so that retransmits of the discarded bytes can land again
            # (a stale straggler frame must not be able to wedge the
            # live transfer) — and start clean.  The completed frame is
            # invalidated too: on a reused flow, a reader waiting for
            # THIS transfer must block until it assembles, never be
            # satisfied by last transfer's bytes.  A replaced xid
            # whose frame COMPLETED is RETIRED: the transfer finished,
            # so anything still arriving under it (a ring completer's
            # late send, a slow retransmit) is a straggler that must
            # not reset the new live assembly.  An INCOMPLETE xid is
            # not retired — its displacement may itself be the work
            # of a straggler, and the live transfer's retransmits
            # must be able to land again (the un-seen seqs below).
            if (f.asm_xid and f.asm_xid != xid and f.frame_bytes
                    and f.frame_bytes == f.asm_total):
                f.retired_xids.append(f.asm_xid)
            f.discard_assembly()
            f.staged = b""
            f.frame_bytes = 0
            f.asm_xid = xid
            f.asm_total = tot
            if f.seg_map is not None and f.seg_size >= tot:
                # shm-attached flow: assemble straight into the mmap,
                # so the local reader's shm_read is a buffer reference
                # with no migration copy.
                f.asm_buf = f.seg_view(tot)
            else:
                f.asm_buf = bytearray(tot)
        return f.asm_buf

    def _land_locked(self, flow: str, f: _Flow, payload,
                     meta: dict, seq, in_place: bool = False,
                     preloaded_gen: Optional[int] = None) -> str:
        """Write one (deduped) frame into flow state; caller holds the
        lock."""
        off = meta.get("off")
        if meta.get("fwd"):
            # Forward frame (a routed schedule leg): lands INTO the
            # flow's completed staging at its offset — combining when
            # the leg reduces, overwriting when it gathers — never
            # into an assembly.  The baseline frame (the coordinator's
            # setup put) must already be staged: schedule legs write
            # regions of a buffer whose geometry the schedule fixed
            # up front, so a missing baseline is a protocol error the
            # source daemon surfaces for the coordinator to re-post.
            off = int(off or 0)
            n = len(payload)
            if (not f.frame_bytes or off < 0
                    or off + n > len(f.staged)):
                counters.inc("dcn.chunks.rejected")
                log.error("rejecting forward frame with bad geometry:"
                          " flow=%s off=%d len=%d staged=%d", flow,
                          off, n, len(f.staged))
                return "rejected"
            if not isinstance(f.staged, (bytearray, memoryview)):
                # First forward into this flow: staging becomes
                # writable in place (segment-backed staging already
                # is).
                f.staged = bytearray(f.staged)
            if meta.get("red"):
                _combine_into(f.staged, off, payload)
            else:
                f.staged[off:off + n] = payload
            f.rx_bytes += n
            counters.inc("xferd.forward.landed")
            return "landed"
        if off is None:
            # Whole-payload frame: replaces staging wholesale and
            # cancels any in-progress assembly (the serial fallback
            # after a pipelined attempt must win outright).
            if in_place:
                # shm commit: the bytes are already in the segment.
                # Re-take the view under THIS lock hold — the segment
                # could have been remapped since the caller sliced it.
                if f.seg_map is None or f.seg_size < len(payload):
                    return "rejected"
                f.staged = f.seg_view(len(payload))
            else:
                f.staged = bytes(payload)
            f.frame_bytes = len(payload)
            f.rx_bytes += len(payload)
            new_xid = meta.get("xid") or None
            if f.asm_xid and f.asm_xid != new_xid:
                f.retired_xids.append(f.asm_xid)
            f.discard_assembly()
            if in_place:
                # Stamp the committing transfer's xid so offset-sends
                # of the same transfer match this frame (the sender's
                # stale-frame guard on reused flows).
                f.asm_xid = new_xid
                f.asm_total = len(payload)
            return "landed"
        off = int(off)
        tot = int(meta.get("tot") or 0)
        xid = meta.get("xid") or ""
        if tot <= 0 or off < 0 or off + len(payload) > tot:
            counters.inc("dcn.chunks.rejected")
            log.error("rejecting chunk with bad geometry: flow=%s "
                      "off=%d len=%d tot=%d", flow, off,
                      len(payload), tot)
            return "rejected"
        self._ensure_assembly_locked(f, xid, tot)
        if preloaded_gen is not None or in_place:
            # The bytes are already where they belong: received
            # straight into the assembly buffer (recv-into-mmap; the
            # generation was verified by the caller under THIS lock
            # hold), or memcpy'd into the segment by a co-hosted peer
            # daemon (DXC1).  For the latter the assembly must
            # actually be segment-backed, or the "already there"
            # premise is false — refuse, the sender retries over TCP.
            if in_place and not isinstance(f.asm_buf, memoryview):
                counters.inc("dcn.chunks.rejected")
                return "rejected"
        else:
            f.asm_buf[off:off + len(payload)] = payload
        f.asm_chunks[off] = len(payload)
        if seq:
            f.asm_seqs.add(seq)
        f.rx_bytes += len(payload)
        counters.inc("dcn.chunks.landed")
        if (f.range_staged(0, tot, xid)
                and f.staged is not f.asm_buf):
            # Completion = every byte of [0, tot) covered by landed
            # chunks (interval walk, not a length sum: overlapping
            # chunks from an off-grid sender must not mark a gapped
            # buffer complete).  Adopt the assembly buffer as the
            # completed frame without a copy; a same-xid restage keeps
            # writing into it (same bytes), a new xid starts a fresh
            # buffer.  The identity check makes completion fire once
            # per assembly, not once per straggler/replayed chunk
            # after completion.
            f.staged = f.asm_buf
            f.frame_bytes = tot
            counters.inc("dcn.chunks.assembled")
        return "landed"
