"""Continuous soak world: composed workloads, seeded chaos, invariant
sentinels.

Every fleet gate so far is an *episode*: one workload, a handful of
rounds, a scripted fault, a verdict.  Episodes are how you prove a
mechanism; they are structurally blind to the failures that define
node infrastructure in production — the fd that leaks one per respawn,
the counter that quietly regresses across a worker generation, the
AIMD controller that never settles after the fifth heal.  This module
is the repo's long-horizon gate (ROADMAP "one continuous soak world"),
and the standing evidence behind the ``TPU_DCN_TUNE`` default flip:
the closed loop ships ON because this world proves, on every
presubmit, that it converges and never limit-cycles under sustained
mixed load.

The composition model (one proc-mode fleet, everything at once):

- **serving** — a ServingFrontend spraying batched/hedged requests
  (its own client pool, per-node breakers);
- **collective** — the topology-aware engine synthesizing and
  executing schedules against the live comm graph (its own pool);
- **pipelined exchange** — the classic ring legs on each node's
  control client, chunked/striped through the same daemons;

all three run CONCURRENTLY each window (safe by construction: the
frontend and the engine own their pooled clients, the exchange thread
is the only user of ``node.client``), with the per-destination tuner
(parallel/dcn_tune.py) and the continuous profiler on — the exact
contention mix an episodic gate can never produce.

Faults come from a **seeded, reproducible schedule**
(:class:`SoakSchedule`): a pure function of ``(seed, window)``, so the
same seed replays the same chaos byte-for-byte — the property that
turns "it failed at 3am after six hours" into a one-line repro.  The
grammar is the scenario fault grammar (kill/restart, link
latency/drop/partition with ``for:`` lifetimes) plus one new literal:

    {"grey": "<node>", "for": K}

a **grey failure** — slow, not dead: the node's links to every peer
(both directions) get shim latency and the worker spins a CPU-burn
thread, but nothing crashes, no port changes, no health check fires.
Grey nodes are the classic blind spot of crash-detector-shaped
chaos, and the tuner/SLO machinery has to ride them out.

The verdict layer is the point: **invariant sentinels** judged over
the whole run, not per round —

- :class:`MonotonicitySentinel` — cumulative worker counters may
  never decrease within one worker generation (respawns are
  generation-aware, riding telemetry's ``_accumulate`` misread log);
- :class:`LeakSentinel` — per-window resource censuses (fds,
  threads, shm segments, rss via the workers' ``resources`` RPC) are
  fitted with a least-squares slope per generation segment, after a
  short per-generation warm-up allowance (a freshly respawned
  worker's boot ramp is not a leak); a slope past its per-window
  budget is a leak, whatever its wobble;
- :func:`judge_tuner_convergence` — after the last heal (plus a
  settle allowance) the tuner's reactive move rate must decay to
  zero; a grid still being corrected every window is a limit cycle;
- the windowed SLO verdict — the same telemetry SLO table, evaluated
  over the full soak history.

Exit contract (``cmd/fleet_soak.py``, ``make soak``): 0 clean, 2
non-convergence, 3 invariant-or-SLO breach.
"""

import logging
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from container_engine_accelerators_tpu.fleet.controller import (
    FleetController,
)
from container_engine_accelerators_tpu.fleet.telemetry import (
    SLO_KEYS,
    parse_slo_spec,
)
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import (
    history,
    timeseries,
    trace,
)
from container_engine_accelerators_tpu.parallel import dcn_tune
from container_engine_accelerators_tpu.serving.frontend import (
    ServingConfig,
    ServingFrontend,
)

log = logging.getLogger(__name__)

# Grey-failure shim latency, per frame, both directions: well under
# the 0.25 s shim cap, well over loopback RTT — slow enough to stretch
# every leg through the grey node, never enough to trip a timeout by
# itself.
GREY_LATENCY_S = 0.05

# Slow-ring-completer grey fault, per posted descriptor: the ring
# completer sleeps this long before driving each descriptor — a round
# crawls (the cursor keeps advancing) without ever tripping the 5 s
# stage-wait budget, which is exactly the slow-not-dead shape the
# sentinels must catch without a transfer wedging.
RING_DELAY_S = 0.08

# Slow-shm-commit grey fault, per staged frame: every shm commit pays
# this before landing — a throttled staging memcpy on the zero-copy
# lane.  Commits still land and account, so only the xferd.shm.commit
# latency histogram (and the anomaly detector reading it) sees it.
SHM_DELAY_S = 0.06

# The deterministic coverage prologue: window 1 SIGKILL (+respawn),
# window 2 grey (+ungrey), window 3 link degrade (+heal), window 4
# slow ring completer (+unslow), and — on shm-lane scenarios — window
# 5 slow shm commit (+unslow) — every soak run exercises every fault
# family and its heal even at the shortest CI duration; later windows
# draw from the seeded RNG.
LAST_DETERMINISTIC_WINDOW = 4

# Post-fault settle allowance, in windows, the closed-loop detection
# judge grants after every scheduled fault's lifetime before a flag on
# that window counts as a false positive: the anomaly EWMA decays over
# several windows by design (hysteresis is the anti-flap contract —
# from the score cap it takes ~4 windows to fall under clear_z plus
# clear_windows more to step down), and the goodput rate windows smear
# the evidence one further — decay after chaos is the detector
# working, not a false alarm.
ANOMALY_SETTLE_WINDOWS = 5

# Tuner decisions that count as REACTIVE moves for the convergence
# sentinel: the loss-response axis (and its recovery).  Exploration
# probes (grow/narrow/keep/revert) are the controller's steady-state
# behavior on a clean link and judging them would fail every healthy
# run.
REACTIVE_DECISIONS = ("shrink_chunk", "backoff_stripe", "grow_chunk")

# Leak-slope budgets, per metric per window — deliberately generous:
# a clean run must never flake on scheduling noise, and the planted
# tests use slopes an order of magnitude past these.
DEFAULT_LEAK_LIMITS = {
    "fds": 2.0,
    "threads": 1.5,
    "shm_segments": 1.5,
    "rss_bytes": float(8 << 20),
}

DEFAULT_SOAK_SCENARIO = {
    "name": "soak",
    "workload": "soak",
    "proc": True,
    "pipelined": True,
    "tuned": True,
    # Socket lane pinned on BOTH tiers: the link shim (grey latency,
    # scheduled drops) interposes on the TCP send path, so the soak's
    # chaos must not be bypassed by the same-host segment lanes.
    "shm": False,
    "shm_direct": False,
    "nodes": 3,
    "payload_bytes": 32768,
    "chunk_bytes": 8192,
    "stripes": 2,
    # Soak kills repeatedly by design: the restart budget models
    # permanent hardware loss, which is not this world's question.
    "restart_budget": 1000,
    "leg_attempts": 4,
    "serving": {"requests_per_round": 6, "round_deadline_s": 20.0},
    "collective": {"op": "all_reduce", "bytes": 16384},
    "slo": {
        "min_final_goodput_bps": 1024,
        "max_dedup_ratio": 0.9,
    },
}


# ---------------------------------------------------------------------------
# seeded schedule
# ---------------------------------------------------------------------------


class SoakSchedule:
    """The seeded fault schedule: a PURE function of ``(seed,
    window)`` over a fixed node list — no shared RNG state between
    windows, so any window's draw can be recomputed in isolation and
    the whole schedule replays from the seed alone."""

    def __init__(self, seed: int, node_names: List[str],
                 shm: bool = False):
        self.seed = int(seed)
        self.names = list(node_names)
        # shm-lane scenarios (scenario "shm": true) extend the grammar
        # with the slow_shm grey fault: a throttled per-frame commit
        # on the staging lane.  Gated on the flag because a socket-
        # only scenario never commits — the fault would be a no-op and
        # the detection judge would count an undetectable truth.
        self.shm = bool(shm)
        # The last window of the deterministic coverage prologue —
        # shm scenarios add the window-5 slow_shm leg.
        self.last_deterministic = (5 if self.shm
                                   else LAST_DETERMINISTIC_WINDOW)

    def _rng(self, window: int) -> random.Random:
        return random.Random(f"{self.seed}:{window}")

    def faults_for(self, window: int) -> List[dict]:
        """Schedule entries to inject at ``window`` (scenario fault
        grammar plus the ``grey:`` literal).  Window 0 is always a
        clean baseline; windows 1-3 are the deterministic coverage
        prologue; later windows draw probabilistically."""
        if not self.names or window <= 0:
            return []
        rng = self._rng(window)
        if window == 1:
            return [{"action": "kill", "node": rng.choice(self.names),
                     "for": 1}]
        if window == 2:
            return [{"grey": rng.choice(self.names), "for": 1}]
        if window == 3 and len(self.names) > 1:
            a, b = rng.sample(self.names, 2)
            return [{"link": f"node:{a}<->node:{b}:latency:20",
                     "for": 1}]
        if window == 4:
            return [{"slow_ring": rng.choice(self.names), "for": 1}]
        if window == 5 and self.shm:
            return [{"slow_shm": rng.choice(self.names), "for": 1}]
        draws: List[dict] = []
        r = rng.random()
        if r < 0.15:
            draws.append({"action": "kill",
                          "node": rng.choice(self.names), "for": 1})
        elif r < 0.30:
            draws.append({"grey": rng.choice(self.names), "for": 1})
        elif r < 0.50 and len(self.names) > 1:
            a, b = rng.sample(self.names, 2)
            action = rng.choice(["latency:20", "drop:2"])
            draws.append({"link": f"node:{a}<->node:{b}:{action}",
                          "for": rng.randint(1, 2)})
        elif r < 0.60:
            # The ring lane's grey fault: a slow completer on one
            # node's universal ring — every descriptor costs a sleep,
            # no descriptor is lost.
            draws.append({"slow_ring": rng.choice(self.names),
                          "for": 1})
        elif r < 0.65 and self.shm:
            # The staging lane's grey fault: a throttled shm commit —
            # drawn from the band the non-shm grammar leaves clean, so
            # flipping shm on never perturbs an existing seed's other
            # draws.
            draws.append({"slow_shm": rng.choice(self.names),
                          "for": 1})
        return draws


# ---------------------------------------------------------------------------
# sentinels (pure — unit-tested with synthetic inputs)
# ---------------------------------------------------------------------------


class MonotonicitySentinel:
    """Cumulative counters may never decrease within one worker
    generation.  A respawn (generation bump) legitimately restarts a
    counter at zero; a same-generation decrease is a correctness
    violation, full stop — exactly the event telemetry's
    ``_accumulate`` records into its misread log."""

    def __init__(self):
        self.violations: List[dict] = []
        self._last: Dict[Tuple[str, str], Tuple[Optional[int],
                                                float]] = {}

    def observe(self, node: str, key: str, value: float,
                gen: Optional[int] = None) -> None:
        prev = self._last.get((node, key))
        if prev is not None:
            pgen, pval = prev
            if gen == pgen and value < pval:
                self.violations.append({
                    "node": node, "key": key,
                    "last": pval, "current": value, "gen": gen,
                })
        self._last[(node, key)] = (gen, float(value))

    def fold(self, misreads: List[dict]) -> None:
        """Adopt telemetry's ``_accumulate`` misread log — the scrape
        path's same-generation decreases, recorded where they were
        detected."""
        self.violations.extend(dict(m) for m in misreads)

    def report(self) -> dict:
        return {"ok": not self.violations,
                "violations": list(self.violations)}


class LeakSentinel:
    """Per-window resource censuses, judged by fitted slope.  Series
    are segmented by worker generation — a respawn resets fds/threads/
    rss legitimately, and stitching across it would either hide a leak
    or invent one.  Each segment's first ``warmup_samples`` censuses
    are discarded: a freshly (re)spawned worker legitimately ramps
    fds/threads/rss while its stagers and handlers spin up, and that
    boot ramp fitted as a slope reads exactly like a leak.  Only
    segments with ``min_samples`` post-warm-up points judge (two
    points fit any line); the budgets are per window."""

    def __init__(self, limits: Optional[dict] = None,
                 min_samples: int = 4, warmup_samples: int = 2,
                 learned: Optional[Dict[str, dict]] = None):
        self.limits = dict(DEFAULT_LEAK_LIMITS)
        if limits:
            self.limits.update(limits)
        # History-learned slope budgets (obs/history.learned_limit
        # shapes, keyed by metric): a learned limit replaces the
        # pinned one — by construction it can only TIGHTEN it (the
        # learner's hard ceiling is the pinned constant), so a fleet
        # whose demonstrated slopes sit near zero flags a creep the
        # generous pinned budget alone would wave through.
        self.limit_sources: Dict[str, dict] = {}
        for metric, ll in (learned or {}).items():
            if metric in self.limits \
                    and ll.get("source") == "learned":
                self.limits[metric] = min(self.limits[metric],
                                          float(ll["limit"]))
                self.limit_sources[metric] = {
                    "source": "learned", "n": ll.get("n"),
                    "median": ll.get("median"),
                    "pinned": DEFAULT_LEAK_LIMITS.get(metric)}
        self.min_samples = max(2, int(min_samples))
        self.warmup_samples = max(0, int(warmup_samples))
        self._series: Dict[Tuple[str, str, Optional[int]],
                           List[Tuple[int, float]]] = {}
        self._seen: Dict[Tuple[str, str, Optional[int]], int] = {}

    def observe(self, window: int, node: str, resources: dict,
                gen: Optional[int] = None) -> None:
        for metric in self.limits:
            if metric not in resources:
                continue
            key = (node, metric, gen)
            seen = self._seen.get(key, 0)
            self._seen[key] = seen + 1
            if seen < self.warmup_samples:
                continue  # boot ramp, not evidence
            self._series.setdefault(key, []).append(
                (int(window), float(resources[metric])))

    def report(self) -> dict:
        breaches: List[dict] = []
        series: Dict[str, dict] = {}
        # Worst judged slope per metric across every node/generation
        # segment — what the history ledger persists, and what the
        # NEXT run's learned thresholds are fitted over.
        max_slopes: Dict[str, float] = {}
        for (node, metric, gen), pts in sorted(self._series.items(),
                                               key=lambda kv: str(kv[0])):
            slope = timeseries.least_squares_slope(pts)
            limit = self.limits[metric]
            if len(pts) >= self.min_samples:
                max_slopes[metric] = max(
                    max_slopes.get(metric, slope), slope)
            entry = {
                "node": node, "metric": metric, "gen": gen,
                "samples": len(pts),
                "slope_per_window": round(slope, 4),
                "limit_per_window": limit,
            }
            if metric in self.limit_sources:
                entry["limit_source"] = "learned"
            series[f"{node}.{metric}.gen{gen}"] = entry
            if len(pts) >= self.min_samples and slope > limit:
                breaches.append(entry)
        return {"ok": not breaches, "breaches": breaches,
                "series": series,
                "max_slopes": {m: round(s, 4)
                               for m, s in max_slopes.items()},
                "learned_limits": dict(self.limit_sources)}


def judge_tuner_convergence(moves_per_window: List[int],
                            heal_windows: List[int], *,
                            settle_windows: int = 3,
                            max_tail_moves: int = 1) -> dict:
    """The oscillation sentinel: after the LAST heal plus a settle
    allowance, the tuner's reactive move rate must decay — a bounded
    straggler move is tolerated (``max_tail_moves``), but any tail
    window past it, or a tail that never goes quiet at all, is a limit
    cycle.  No heals observed judges nothing (vacuously ok): decay is
    only defined relative to a disturbance."""
    heals = sorted({int(h) for h in heal_windows})
    out = {"ok": True, "heal_windows": heals, "tail_start": None,
           "tail_moves": [], "reason": "no heals observed"}
    if not heals:
        return out
    tail_start = heals[-1] + max(0, int(settle_windows))
    out["tail_start"] = tail_start
    tail = [int(m) for m in moves_per_window[tail_start:]]
    out["tail_moves"] = tail
    if not tail:
        out["reason"] = "run ended inside the settle window"
        return out
    if any(m > max_tail_moves for m in tail):
        out["ok"] = False
        out["reason"] = (
            f"reactive move rate did not decay after the last heal "
            f"(window {heals[-1]}): tail {tail} exceeds "
            f"{max_tail_moves}/window")
        return out
    if len(tail) >= 3 and all(m > 0 for m in tail):
        out["ok"] = False
        out["reason"] = (
            f"limit cycle: every post-settle window kept correcting "
            f"the grid (tail {tail})")
        return out
    out["reason"] = "converged"
    return out


def history_learned_limits(cfg_key: str,
                           slo_spec: Optional[dict] = None,
                           ledger: Optional["history.RunLedger"]
                           = None) -> Tuple[Dict[str, dict],
                                            Dict[str, dict]]:
    """Fit this config's sentinel thresholds from prior soak runs in
    the history ledger (``TPU_HISTORY_DIR``): per-metric leak-slope
    budgets from the runs' recorded ``max_slopes`` and per-key SLO
    limits from their measured values — each ``median + k·MAD``
    (floors mirrored), pinned-constant fallback when history is
    thinner than ``MIN_BASELINE_RUNS``, and the pinned constant as
    the hard bound the learned value can never relax past.  No
    ledger, an unreadable one, or thin history all degrade to empty
    mappings: the pinned constants judge alone, exactly as before
    this layer existed."""
    ledger = history.RunLedger() if ledger is None else ledger
    leak: Dict[str, dict] = {}
    slo: Dict[str, dict] = {}
    if not ledger.enabled:
        return leak, slo
    try:
        prior = ledger.records(kind="fleet_soak", cfg_key=cfg_key)
    except history.LedgerError as e:
        log.error("history ledger unreadable (%s); soak thresholds "
                  "stay pinned", e)
        return leak, slo
    for metric, pinned in DEFAULT_LEAK_LIMITS.items():
        slopes = [
            float(r["sentinels"]["leak_slopes"][metric])
            for r in prior
            if isinstance((r.get("sentinels") or {})
                          .get("leak_slopes"), dict)
            and metric in r["sentinels"]["leak_slopes"]
        ][-history.BASELINE_N:]
        ll = history.learned_limit(slopes, pinned)
        if ll["source"] == "learned":
            leak[metric] = ll
    for key, pinned in parse_slo_spec(slo_spec).items():
        kind = SLO_KEYS[key][0]
        values = [
            float(r["slo"]["measured"][key]) for r in prior
            if isinstance((r.get("slo") or {}).get("measured"), dict)
            and key in r["slo"]["measured"]
        ][-history.BASELINE_N:]
        ll = history.learned_limit(values, pinned, kind=kind)
        if ll["source"] == "learned":
            slo[key] = ll
    return leak, slo


def exit_code_for(report: dict) -> int:
    """The soak exit contract: 0 clean, 2 non-convergence, 3
    invariant-or-SLO breach — shared by the CLI and the planted-fault
    tests so the verdict→exit mapping is pinned in one place."""
    if not report.get("converged"):
        return 2
    sentinels = (report.get("soak") or {}).get("sentinels") or {}
    slo = report.get("slo") or {}
    if not sentinels.get("ok", True) or not slo.get("ok", True):
        return 3
    return 0


# ---------------------------------------------------------------------------
# the soak world
# ---------------------------------------------------------------------------


class SoakWorld(FleetController):
    """A FleetController whose run is wall-clock-bounded and whose
    three workloads run concurrently each window, with the seeded
    schedule injecting faults and the sentinel layer judging the whole
    run.  Everything episodic is inherited: fault application,
    deferred ``for:`` inverses, leg mechanics, telemetry, the
    convergence report."""

    def __init__(self, scenario: Optional[dict] = None,
                 workdir: Optional[str] = None, *,
                 duration_s: Optional[float] = None,
                 window_s: Optional[float] = None,
                 seed: Optional[int] = None,
                 cooldown_s: Optional[float] = None):
        merged = dict(DEFAULT_SOAK_SCENARIO)
        if scenario:
            merged.update(scenario)
        merged["workload"] = "soak"  # neither serving nor collective:
        # the base boot() must not claim either — this world composes
        # both itself, on top of the ring substrate.
        super().__init__(merged, workdir=workdir)
        self.duration_s = float(
            duration_s if duration_s is not None
            else merged.get("duration_s", 45.0))
        self.window_s = float(
            window_s if window_s is not None
            else merged.get("window_s", 2.0))
        self.seed = int(seed if seed is not None
                        else merged.get("seed", 1234))
        # The quiet tail: no NEW faults inside the final cooldown
        # (pending heals still fire), so convergence and the tuner
        # sentinel always get an undisturbed run-out.
        self.cooldown_s = float(
            cooldown_s if cooldown_s is not None
            else merged.get("cooldown_s", 3 * self.window_s))
        self.min_windows = int(merged.get("min_windows", 6))
        self.settle_windows = int(merged.get("settle_windows", 3))
        self.max_tail_moves = int(merged.get("max_tail_moves", 1))
        self.grey_latency_s = float(
            merged.get("grey_latency_s", GREY_LATENCY_S))
        self.ring_delay_s = float(
            merged.get("ring_delay_s", RING_DELAY_S))
        self.shm_delay_s = float(
            merged.get("shm_delay_s", SHM_DELAY_S))
        self.schedule = SoakSchedule(
            self.seed, [s.name for s in self.topology.specs.values()],
            shm=bool(merged.get("shm")))
        self.mono = MonotonicitySentinel()
        # History-learned thresholds: prior soak runs of this SAME
        # config (ledger under TPU_HISTORY_DIR) tighten the leak
        # budgets and SLO limits toward the fleet's demonstrated
        # baseline — pinned constants stay the fallback AND the hard
        # bound, so no history and thin history behave exactly as
        # before this layer existed.
        self.history_key = history.config_key(
            "soak", merged.get("name", "soak"),
            f"n{merged.get('nodes')}")
        self._learned_leak, self._learned_slo = \
            history_learned_limits(self.history_key,
                                   merged.get("slo"))
        self.leak = LeakSentinel(merged.get("leak_limits"),
                                 learned=self._learned_leak)
        self._moves_per_window: List[int] = []
        self._last_moves = 0
        self._heal_windows: set = set()
        self._schedule_log: List[dict] = []
        self._kills = 0
        self._greys = 0
        self._heals = 0

    # -- lifecycle -----------------------------------------------------------

    def boot(self) -> "SoakWorld":
        if self._booted:
            return self
        super().boot()
        # The SLO sentinel judges with the history-learned limits
        # (tighten-only; telemetry clamps them to the pinned spec).
        self.telemetry.learned_slo.update(self._learned_slo)
        # Compose ALL the workloads on the booted substrate.  The
        # frontend and the engine keep their own pooled clients, so
        # they are safe to drive concurrently with the exchange legs
        # (the only user of node.client); close() tears both down.
        try:
            self.frontend = ServingFrontend(
                self.nodes,
                ServingConfig.from_scenario(
                    self.scenario.get("serving")),
            ).start()
            from container_engine_accelerators_tpu.collectives.runner \
                import CollectiveConfig, CollectiveEngine

            self.collective = CollectiveEngine(
                self.nodes, self.topology, links=self.links,
                cfg=CollectiveConfig.from_scenario(
                    self.scenario.get("collective")),
                pipe_cfg=self.pipe_cfg if self.pipelined else None,
            )
        except Exception:
            self.close()  # no orphan workers behind a half boot
            raise
        return self

    # -- grey faults ---------------------------------------------------------

    def _apply_fault(self, rnd: int, entry: dict) -> dict:
        if "grey" in entry or "ungrey" in entry:
            return self._apply_grey(rnd, entry)
        if "slow_ring" in entry or "unslow_ring" in entry:
            return self._apply_slow_ring(rnd, entry)
        if "slow_shm" in entry or "unslow_shm" in entry:
            return self._apply_slow_shm(rnd, entry)
        return super()._apply_fault(rnd, entry)

    def _apply_slow_shm(self, rnd: int, entry: dict) -> dict:
        """Arm (or heal) the staging lane's grey fault: every shm
        commit on the node pays a per-frame throttle before landing —
        a slow memcpy, not a slow completer.  Commits still land and
        account, so nothing but the xferd.shm.commit latency histogram
        (the anomaly detector's attribution stream) carries the
        evidence."""
        healing = "unslow_shm" in entry
        name = entry["unslow_shm"] if healing else entry["slow_shm"]
        record = dict(entry)
        record["round"] = rnd
        record["applied"] = 0
        node = self.nodes.get(name)
        if node is None:
            log.error("slow_shm fault names unknown node: %r", entry)
            record["skipped"] = f"unknown node {name!r}"
            return record
        try:
            node.shm_delay(0.0 if healing else self.shm_delay_s)
            record["applied"] = 1
        except (OSError, AttributeError) as e:
            record["skipped"] = f"shm_delay {name}: {e}"
        if not healing and record["applied"]:
            counters.inc("soak.fault.slow_shm")
            lifetime = int(entry.get("for", 0))
            if lifetime > 0:
                self._deferred.setdefault(rnd + lifetime, []).append(
                    {"unslow_shm": name})
        return record

    def _apply_slow_ring(self, rnd: int, entry: dict) -> dict:
        """Arm (or heal) the ring lane's grey fault: the node's ring
        completer sleeps per posted descriptor — rounds crawl with a
        visibly advancing cursor, no descriptor is dropped, no
        stage-wait budget trips.  The sentinels (latency histograms,
        exposed-comm ratio, SLO round deadlines) must catch the
        degradation without any transfer wedging."""
        healing = "unslow_ring" in entry
        name = entry["unslow_ring"] if healing else entry["slow_ring"]
        record = dict(entry)
        record["round"] = rnd
        record["applied"] = 0
        node = self.nodes.get(name)
        if node is None:
            log.error("slow_ring fault names unknown node: %r", entry)
            record["skipped"] = f"unknown node {name!r}"
            return record
        try:
            node.ring_delay(0.0 if healing else self.ring_delay_s)
            record["applied"] = 1
        except (OSError, AttributeError) as e:
            record["skipped"] = f"ring_delay {name}: {e}"
        if not healing and record["applied"]:
            counters.inc("soak.fault.slow_ring")
            lifetime = int(entry.get("for", 0))
            if lifetime > 0:
                self._deferred.setdefault(rnd + lifetime, []).append(
                    {"unslow_ring": name})
        return record

    def _apply_grey(self, rnd: int, entry: dict) -> dict:
        """Arm (or heal) a grey failure: shim latency on every link
        touching the node, both directions, plus a worker-side CPU
        burn — slow, not dead.  A dark node degrades the record, never
        the schedule (the standard fault rule)."""
        healing = "ungrey" in entry
        name = entry["ungrey"] if healing else entry["grey"]
        record = dict(entry)
        record["round"] = rnd
        record["applied"] = 0
        node = self.nodes.get(name)
        if node is None:
            log.error("grey fault names unknown node: %r", entry)
            record["skipped"] = f"unknown node {name!r}"
            return record
        action = "heal" if healing else "latency"
        param = 0.0 if healing else self.grey_latency_s
        applied = 0
        errs = []
        for peer in self.nodes.values():
            if peer.name == name:
                continue
            for src, dst in ((node, peer), (peer, node)):
                try:
                    applied += src.apply_link_fault(
                        dst.daemon.data_port, action, param)
                except (OSError, AttributeError) as e:
                    errs.append(f"{src.name}->{dst.name}: {e}")
        try:
            if healing:
                node.stop_burn()
            else:
                lifetime = max(1, int(entry.get("for", 1)))
                node.burn_cpu(lifetime * self.window_s * 2.0)
        except (OSError, AttributeError) as e:
            errs.append(f"burn {name}: {e}")
        record["applied"] = applied
        if errs:
            record["skipped"] = "; ".join(errs)
        if not healing:
            counters.inc("soak.fault.grey")
            lifetime = int(entry.get("for", 0))
            if lifetime > 0:
                self._deferred.setdefault(rnd + lifetime, []).append(
                    {"ungrey": name})
        return record

    @staticmethod
    def _is_heal(record: dict) -> bool:
        if record.get("skipped") and not record.get("applied"):
            return False
        if "ungrey" in record or "unslow_ring" in record \
                or "unslow_shm" in record:
            return True
        if record.get("action") == "restart":
            return True
        link = record.get("link")
        return bool(link) and ":heal" in str(link)

    # -- the windowed run ----------------------------------------------------

    def run(self) -> dict:
        self.boot()
        per_node_ok: Dict[str, int] = {n: 0 for n in self.nodes}
        per_node_failed: Dict[str, int] = {n: 0 for n in self.nodes}
        round_log: List[dict] = []
        start = time.monotonic()
        deadline = start + self.duration_s
        w = 0
        with trace.span("fleet.scenario",
                        scenario=self.scenario.get("name", "soak"),
                        nodes=len(self.nodes), rounds=0):
            while w < self.min_windows \
                    or time.monotonic() < deadline:
                t0 = time.monotonic()
                fired = []
                for entry in self._deferred.pop(w, []):
                    rec = self._apply_fault(w, entry)
                    fired.append(rec)
                    if self._is_heal(rec):
                        self._heal_windows.add(w)
                        self._heals += 1
                        counters.inc("soak.fault.heal")
                # The quiet tail: inside the final cooldown no NEW
                # fault is drawn — the deterministic prologue is
                # exempt so even the shortest run keeps its coverage
                # guarantee (its heals land by window 4, well before
                # any sane cooldown).
                injecting = (w <= getattr(self.schedule,
                                          "last_deterministic",
                                          LAST_DETERMINISTIC_WINDOW)
                             or (deadline - time.monotonic())
                             > self.cooldown_s)
                if injecting:
                    for entry in self.schedule.faults_for(w):
                        rec = self._apply_fault(w, entry)
                        fired.append(rec)
                        self._schedule_log.append(
                            {"window": w,
                             **{k: v for k, v in rec.items()
                                if k != "round"}})
                        if rec.get("action") == "kill" \
                                and rec.get("applied"):
                            self._kills += 1
                        if "grey" in rec and rec.get("applied"):
                            self._greys += 1
                        self._record_truth(w, rec)
                legs = self._window_workloads(w, per_node_ok,
                                              per_node_failed)
                for node in self.nodes.values():
                    node.recover()
                self.telemetry.sample_round(w)
                self._sample_resources(w)
                moves = self._reactive_moves()
                self._moves_per_window.append(
                    max(0, moves - self._last_moves))
                self._last_moves = moves
                counters.inc("soak.windows")
                round_log.append(
                    {"round": w, "faults": fired, "legs": legs})
                # Pace to the window cadence (never past the
                # deadline): the leak series' x axis is the window
                # index, so windows should tick at comparable
                # wall-clock spacing.
                pace = self.window_s - (time.monotonic() - t0)
                if w + 1 >= self.min_windows:
                    pace = min(pace, deadline - time.monotonic())
                if pace > 0:
                    time.sleep(pace)
                w += 1
        return self._soak_report(round_log, per_node_ok,
                                 per_node_failed, windows=w,
                                 start=start)

    def _window_workloads(self, w: int,
                          per_node_ok: Dict[str, int],
                          per_node_failed: Dict[str, int]) -> list:
        """One window's composed traffic: serving + collective +
        pipelined exchange, concurrently.  Each thread folds into its
        OWN per-node dicts (merged after the join) and appends its leg
        entries under the lock — the inherited round helpers are
        single-thread code and stay that way."""
        legs: List[dict] = []
        folds: List[Tuple[Dict[str, int], Dict[str, int]]] = []
        lock = threading.Lock()

        def _serving(ok, failed):
            return [self._serving_round(w, ok, failed)]

        def _collective(ok, failed):
            return [self._collective_round(w, ok, failed)]

        def _exchange(ok, failed):
            out = []
            for src, dst in self._ring():
                if src.down or dst.down:
                    out.append({"src": src.name, "dst": dst.name,
                                "skipped": "node down"})
                    continue
                leg = self._leg(w, src, dst)
                out.append(leg)
                if leg["ok"]:
                    ok[src.name] += 1
                else:
                    failed[src.name] += 1
            return out

        def _drive(kind, fn):
            ok = {n: 0 for n in self.nodes}
            failed = {n: 0 for n in self.nodes}
            try:
                entries = fn(ok, failed)
            except Exception as e:  # noqa: BLE001 — a workload crash
                # is a failed window entry, never a wedged soak
                log.error("soak %s workload failed in window %d: %s",
                          kind, w, e)
                entries = [{"workload": kind, "ok": False,
                            "error": str(e)}]
            with lock:
                legs.extend(entries)
                folds.append((ok, failed))

        threads = [
            # daemon=True: joined before this window returns; the flag
            # only matters if a workload wedges, and then it must not
            # pin interpreter shutdown.
            threading.Thread(target=_drive, args=(kind, fn),
                             name=f"soak-{kind}", daemon=True)
            for kind, fn in (("serving", _serving),
                             ("collective", _collective),
                             ("exchange", _exchange))
        ]
        with trace.span("fleet.round", round=w):
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        for ok, failed in folds:
            for n, v in ok.items():
                per_node_ok[n] += v
            for n, v in failed.items():
                per_node_failed[n] += v
        return legs

    # -- sentinel feeds ------------------------------------------------------

    def _record_truth(self, w: int, rec: dict) -> None:
        """Feed the anomaly detector's closed-loop judge DURING the
        run (telemetry.evaluate runs inside _report, before the soak
        section exists): every APPLIED grey-family fault becomes a
        ground-truth entry the detection recall is judged over, and
        every scheduled fault of ANY kind marks its window footprint
        (lifetime + the hysteresis settle allowance) so decay after
        chaos never counts as a false positive."""
        if not rec.get("applied"):
            return
        lifetime = max(1, int(rec.get("for", 1)))
        for wx in range(w, w + lifetime + ANOMALY_SETTLE_WINDOWS + 1):
            self.telemetry.anomaly_chaos.add(wx)
        for kind in ("grey", "slow_ring", "slow_shm"):
            if kind in rec:
                self.telemetry.anomaly_truth.append(
                    {"node": rec[kind], "window": w,
                     "lifetime": lifetime, "kind": kind})
                return

    def _sample_resources(self, w: int) -> None:
        """One resource census per live node per window — the leak
        sentinel's series.  A dark worker contributes NOTHING (no
        cached fallback: a stale census fakes a flat series), counted
        so the report can say how observable the run actually was."""
        for name, node in self.nodes.items():
            if getattr(node, "down", False):
                counters.inc("soak.resources.stale")
                continue
            try:
                res = node.resources()
            except (OSError, AttributeError):
                counters.inc("soak.resources.stale")
                continue
            gen = getattr(getattr(node, "daemon", None),
                          "generation", None)
            self.leak.observe(w, name, res, gen)

    def _reactive_moves(self) -> int:
        return sum(counters.get(f"dcn.tune.{d}")
                   for d in REACTIVE_DECISIONS)

    # -- verdict -------------------------------------------------------------

    def _soak_report(self, round_log, per_node_ok, per_node_failed,
                     *, windows: int, start: float) -> dict:
        report = self._report(round_log, per_node_ok, per_node_failed)
        self.mono.fold(self.telemetry.misreads)
        sentinels = {
            "monotonicity": self.mono.report(),
            "leaks": self.leak.report(),
            "tuner": judge_tuner_convergence(
                self._moves_per_window, sorted(self._heal_windows),
                settle_windows=self.settle_windows,
                max_tail_moves=self.max_tail_moves),
        }
        sentinels["ok"] = all(
            sentinels[k]["ok"]
            for k in ("monotonicity", "leaks", "tuner"))
        if not sentinels["ok"]:
            counters.inc("soak.sentinel.breach")
        report["soak"] = {
            "seed": self.seed,
            "history": {
                "config_key": self.history_key,
                "learned_leak": self._learned_leak,
                "learned_slo": self._learned_slo,
            },
            "windows": windows,
            "window_s": self.window_s,
            "duration_s": round(time.monotonic() - start, 3),
            "schedule": self._schedule_log,
            "kills": self._kills,
            "greys": self._greys,
            "heals": self._heals,
            "heal_windows": sorted(self._heal_windows),
            "moves_per_window": self._moves_per_window,
            "sentinels": sentinels,
            # Bounded per-destination decision tail: the evidence
            # behind the tuner verdict, small enough for the JSON
            # report line.
            "tuner_history": {
                key: hist[-64:]
                for key, hist in dcn_tune.decision_history().items()
            },
        }
        return report


def run_soak(scenario: Optional[dict] = None,
             workdir: Optional[str] = None, **kw) -> dict:
    """One-shot convenience: boot, soak, close, return the report."""
    world = SoakWorld(scenario, workdir=workdir, **kw)
    try:
        return world.run()
    finally:
        world.close()
