"""FleetController: declarative chaos scenarios over N emulated nodes.

A scenario is a plain dict (or JSON/YAML file — ``load_scenario``)::

    name: rack-partition
    nodes: 4            # or an explicit list of node dicts:
    racks: 2            #   {name, rack, chips, topology, partition_size}
    chips: 4
    topology: 2x2x1
    rounds: 6           # workload rounds; the fault schedule is keyed
    payload_bytes: 2048 # to rounds, so runs are reproducible
    metrics: false      # per-node MetricServer on an ephemeral port
    faults:
      - {round: 2, link: "rack:r0<->rack:r1:partition", for: 2}
      - {round: 1, action: chip_fault, node: n1, chip: accel0}
      - {round: 3, action: chip_recover, node: n1}
      - {round: 2, action: kill, node: n3, for: 1}

Workload: each round runs a ring of one-way DCN transfers (node i
stages a payload, streams it to node i+1's daemon through the link
table, node i+1 lands + reads it back) — every leg retried under a
bounded budget, so a leg that dies mid-partition re-converges after the
heal the way a real collective caller would.  ``for: K`` on a fault
schedules its inverse K rounds later (partition→heal, kill→restart).

The run returns one report: per-node (device health, daemon
generation, legs ok/failed), per-link (frames/bytes/drops/dups/blocked,
tier-annotated by the production scheduler distance), the round log,
the fleet-wide ``agent_events`` / ``agent_latency`` deltas, a
``telemetry`` section (per-round windowed goodput per ``{node, link}``
from fleet/telemetry.py), and an ``slo`` section evaluating the
scenario's declarative SLOs (``slo:`` mapping — p99 leg-latency
ceiling, goodput floor, retransmit/dedup ratio caps).  A scenario can
therefore *converge* and still FAIL: ``cmd/fleet_sim.py`` exits
non-zero on SLO breach, not just on non-convergence.

**Process mode** (``proc: true``): every node boots as its own OS
process (fleet/proc.py) — the scenario ``kill`` action delivers a real
``SIGKILL``, ``restart`` respawns under a supervisor with RetryPolicy
backoff and a bounded per-scenario budget (``restart_budget``, default
3; exhaustion marks the node permanently down and the scenario
non-converged), and telemetry aggregates by scraping each worker's
MetricServer over HTTP (``stale`` verdicts instead of hangs).  The
report schema is the same in both modes.  Link-table faults need the
in-process delivery fabric and are logged-and-skipped in proc mode;
endpoint chaos (kill / chip faults) is the point there.
"""

import json
import logging
import os
import tempfile
import time
from typing import Dict, List, Optional

from container_engine_accelerators_tpu.fleet.links import (
    FleetNet,
    LinkFault,
    LinkTable,
    parse_link_fault,
)
from container_engine_accelerators_tpu.fleet.node import EmulatedNode
from container_engine_accelerators_tpu.fleet.proc import ProcNode
from container_engine_accelerators_tpu.fleet.telemetry import FleetTelemetry
from container_engine_accelerators_tpu.fleet.topology import (
    FleetTopology,
    NodeSpec,
    build_specs,
)
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import (
    critpath,
    histo,
    profiler,
    trace,
)
from container_engine_accelerators_tpu.parallel import (
    dcn,
    dcn_pipeline,
    dcn_tune,
)
from container_engine_accelerators_tpu.parallel.dcn_client import (
    DcnXferError,
)
from container_engine_accelerators_tpu.serving.frontend import (
    RequestShed,
    ServingConfig,
    ServingFrontend,
)
from container_engine_accelerators_tpu.utils.retry import RetryPolicy

log = logging.getLogger(__name__)

DEFAULT_SCENARIO = {
    "name": "rack-partition",
    "nodes": 4,
    "racks": 2,
    "chips": 4,
    "topology": "2x2x1",
    "rounds": 6,
    "payload_bytes": 2048,
    "metrics": False,
    "faults": [
        {"round": 1, "action": "chip_fault", "node": "n1",
         "chip": "accel0"},
        {"round": 2, "link": "rack:r0<->rack:r1:partition", "for": 2},
        {"round": 3, "action": "chip_recover", "node": "n1"},
    ],
}

# The `--proc` headline: real OS-process nodes, a real SIGKILL
# mid-scenario, supervised respawn two rounds later, pipelined
# multi-chunk legs so the kill lands against in-flight transfer state,
# and a chip fault recovering through a worker's own health checker.
DEFAULT_PROC_SCENARIO = {
    "name": "proc-sigkill",
    "proc": True,
    "nodes": 3,
    "racks": 1,
    "chips": 2,
    "topology": "1x2x1",
    "rounds": 5,
    "payload_bytes": 16384,
    "pipelined": True,
    "chunk_bytes": 4096,
    "stripes": 2,
    "faults": [
        {"round": 1, "action": "kill", "node": "n1", "for": 2},
        {"round": 2, "action": "chip_fault", "node": "n2",
         "chip": "accel0"},
        {"round": 3, "action": "chip_recover", "node": "n2"},
    ],
}


# The serving headline (`--workload serving`): a ServingFrontend
# spraying batched requests across the fleet while a node is SIGKILLed
# mid-load — hedged retries + the per-node breaker steer traffic away
# from the corpse, the supervisor (in-process: the `for:` inverse)
# brings it back, and the serving SLOs gate the exit code.
DEFAULT_SERVING_SCENARIO = {
    "name": "serving-node-kill",
    "workload": "serving",
    "nodes": 3,
    "racks": 1,
    "chips": 2,
    "topology": "1x2x1",
    "rounds": 5,
    "payload_bytes": 2048,
    "serving": {
        "requests_per_round": 16,
        "max_batch": 4,
        "max_wait_ms": 4.0,
        "hedge_after_ms": 500.0,
        "breaker_cooldown_s": 0.5,
    },
    "faults": [
        {"round": 1, "action": "kill", "node": "n1", "for": 2},
    ],
    "slo": {
        "min_qps": 1.0,
        "max_error_ratio": 0.5,
        "p99_e2e_ms": 30000,
    },
}


# The collective headline (`--workload collective`): the topology-aware
# engine synthesizes the schedule from the measured comm graph (2 racks
# -> hierarchical reduce-scatter / exchange / all-gather), the
# cross-rack tier degrades mid-run and heals (`for:`), the engine
# re-synthesizes on both edges of the fault (`collective.resynth`), and
# the recovery floor SLO gates that post-heal bus bandwidth is back.
DEFAULT_COLLECTIVE_SCENARIO = {
    "name": "collective-xrack-latency",
    "workload": "collective",
    "nodes": 4,
    "racks": 2,
    "chips": 2,
    "topology": "1x2x1",
    "rounds": 6,
    "payload_bytes": 65536,
    "collective": {
        "op": "all_reduce",
        "bytes": 65536,
    },
    "faults": [
        {"round": 2, "link": "rack:r0<->rack:r1:latency:30", "for": 2},
    ],
    "slo": {
        "min_final_busbw_bps": 50000,
    },
}


def load_scenario(path: str) -> dict:
    """Read a scenario file: YAML when the extension says so (and
    PyYAML is importable), JSON otherwise."""
    with open(path) as f:
        raw = f.read()
    if path.endswith((".yaml", ".yml")):
        import yaml

        return yaml.safe_load(raw)
    return json.loads(raw)


def _scenario_specs(scenario: dict) -> List[NodeSpec]:
    nodes = scenario.get("nodes", 4)
    if isinstance(nodes, int):
        return build_specs(
            nodes,
            racks=int(scenario.get("racks", 1)),
            chips=int(scenario.get("chips", 4)),
            topology=scenario.get("topology", "2x2x1"),
            partition_size=scenario.get("partition_size", ""),
        )
    return [
        NodeSpec(
            name=n["name"],
            rack=n.get("rack", "r0"),
            chips=int(n.get("chips", scenario.get("chips", 4))),
            topology=n.get("topology", scenario.get("topology", "2x2x1")),
            partition_size=n.get("partition_size", ""),
            # Multi-host slices: explicit node lists may pin a shared
            # slice id and per-host mesh coords, so the production
            # distance function (and every tier/ring decision built on
            # it) sees real ICI structure.
            slice_id=n.get("slice"),
            coords=n.get("coords", "0,0,0"),
        )
        for n in nodes
    ]


class FleetController:
    def __init__(self, scenario: Optional[dict] = None,
                 workdir: Optional[str] = None):
        self.scenario = dict(DEFAULT_SCENARIO if scenario is None
                             else scenario)
        self.workdir = workdir or tempfile.mkdtemp(prefix="fleet-sim-")
        self.topology = FleetTopology(_scenario_specs(self.scenario))
        self.links = LinkTable(self.topology)
        self.net = FleetNet(self.links)
        self.nodes: Dict[str, EmulatedNode] = {}
        self.rounds = int(self.scenario.get("rounds", 6))
        self.payload_bytes = int(self.scenario.get("payload_bytes", 2048))
        # Process mode: one OS process per node, real SIGKILL chaos,
        # HTTP-scraped telemetry (fleet/proc.py).
        self.proc_mode = bool(self.scenario.get("proc", False))
        # Pipelined ring legs: chunked/striped transfers through the
        # same link-table fault surface.  Chunk/stripe/shm knobs come
        # from the scenario first, the TPU_DCN_* env second.  Emulated
        # nodes are same-host by construction, so `shm: false` is how
        # a scenario pins the socket lane (fault-parity runs).
        self.pipelined = bool(self.scenario.get("pipelined", False))
        # `tuned: true` closes the loop: the chunk/stripe grid above
        # becomes only the BASE — parallel/dcn_tune.py adapts it per
        # destination from the legs' own telemetry (the no-operator-
        # knobs scenarios).  Learned state is dropped at boot so every
        # run starts from the declared grid, reproducibly.
        self.pipe_cfg = dcn_pipeline.PipelineConfig(
            chunk_bytes=self.scenario.get("chunk_bytes"),
            stripes=self.scenario.get("stripes"),
            shm=self.scenario.get("shm"),
            tuned=self.scenario.get("tuned"),
            # `shm_direct: false` pins every daemon→peer leg to TCP —
            # the lane-parity handle for proc scenarios, where real
            # co-hosted worker daemons would otherwise take the
            # daemon↔daemon segment lane (in-process fleets route
            # through the fabric and never take it).
            shm_direct=self.scenario.get("shm_direct"),
            ring=self.scenario.get("shm_ring"),
        )
        self.leg_retry = RetryPolicy(
            max_attempts=int(self.scenario.get("leg_attempts", 3)),
            initial_backoff_s=float(
                self.scenario.get("leg_backoff_ms", 30)) / 1e3,
            max_backoff_s=0.2,
            deadline_s=float(self.scenario.get("leg_deadline_s", 8.0)),
        )
        self.land_timeout_s = float(self.scenario.get("land_timeout_s", 2.0))
        # Workload: "ring" (the classic transfer legs), "serving" (a
        # ServingFrontend spraying batched/hedged requests across the
        # fleet — serving/frontend.py), or "collective" (the
        # topology-aware engine synthesizing and executing collective
        # schedules from the fleet's comm graph — collectives/).
        self.workload = str(self.scenario.get("workload", "ring"))
        self.frontend: Optional[ServingFrontend] = None
        # A collectives.runner.CollectiveEngine when workload is
        # "collective" (imported at boot — the engine plans against
        # fleet.topology, so a module-level import would be circular).
        self.collective = None
        # round -> list of deferred inverse faults ("for: K" entries)
        self._deferred: Dict[int, List[dict]] = {}
        self._booted = False
        self._counters0: Dict[str, int] = {}
        self.telemetry: Optional[FleetTelemetry] = None
        self._prof_started = False

    # -- lifecycle -----------------------------------------------------------

    def boot(self) -> "FleetController":
        if self._booted:
            return self
        if self.pipe_cfg.tuned:
            # Fresh controller state per scenario run: tuners learned
            # against a previous fleet's ports must not steer this one.
            dcn_tune.reset()
        try:
            for spec in self.topology.specs.values():
                root = os.path.join(self.workdir, spec.name)
                if self.proc_mode:
                    # One OS process per node; MetricServer always on
                    # (it is the aggregation transport).  A worker that
                    # never handshakes raises ProcHandshakeError —
                    # already-spawned siblings are reaped below.
                    self.nodes[spec.name] = ProcNode(
                        spec, root,
                        env=self.child_env(),
                        handshake_timeout_s=float(
                            self.scenario.get("handshake_timeout_s",
                                              60.0)),
                        restart_budget=int(
                            self.scenario.get("restart_budget", 3)),
                    )
                else:
                    self.nodes[spec.name] = EmulatedNode(
                        spec, root,
                        net=self.net,
                        metrics=bool(self.scenario.get("metrics",
                                                       False)),
                    )
        except Exception:
            self.close()  # no orphan workers on a half-booted fleet
            raise
        # CPU attribution for the run: workers sample themselves
        # (fleet/proc.py starts the profiler in every worker); the
        # coordinator — where the transfer clients and the serving
        # frontend live in BOTH modes — samples here.  Only stop at
        # close() what this controller itself started: a bench or
        # test that armed the profiler first keeps it.
        if not profiler.running():
            self._prof_started = profiler.start()
        self._counters0 = counters.snapshot()
        self.telemetry = FleetTelemetry(
            self.nodes, self.links, self.scenario.get("slo"),
            scrape=self.proc_mode,
        )
        if self.workload == "serving":
            self.frontend = ServingFrontend(
                self.nodes,
                ServingConfig.from_scenario(self.scenario.get("serving")),
            ).start()
        elif self.workload == "collective":
            from container_engine_accelerators_tpu.collectives.runner \
                import CollectiveConfig, CollectiveEngine

            # The engine plans against the coordinator's link table in
            # BOTH modes: in-process fleets fault it directly, process
            # fleets mirror their worker-shim faults into it
            # (_apply_proc_link_fault), so the comm graph sees the
            # same evidence either way.
            self.collective = CollectiveEngine(
                self.nodes, self.topology, links=self.links,
                cfg=CollectiveConfig.from_scenario(
                    self.scenario.get("collective")),
                pipe_cfg=self.pipe_cfg if self.pipelined else None,
            )
        self._booted = True
        log.info("fleet booted: %d node(s) in %d rack(s)%s",
                 len(self.nodes),
                 len({s.rack for s in self.topology.specs.values()}),
                 " [one process each]" if self.proc_mode else "")
        return self

    def close(self) -> None:
        if self.frontend is not None:
            self.frontend.close()
            self.frontend = None
        if self.collective is not None:
            self.collective.close()
            self.collective = None
        for node in self.nodes.values():
            node.close()
        if self._prof_started:
            profiler.stop()
            self._prof_started = False

    # -- fault schedule ------------------------------------------------------

    def _apply_fault(self, rnd: int, entry: dict) -> dict:
        """Apply one schedule entry; returns a loggable record."""
        record = dict(entry)
        record["round"] = rnd
        if "link" in entry:
            fault = (entry["link"] if isinstance(entry["link"], LinkFault)
                     else parse_link_fault(entry["link"]))
            if fault is None:
                record["link"] = str(entry["link"])  # JSON-clean log
                record["applied"] = 0
                return record
            record["link"] = fault.spec()  # JSON-clean round log
            if self.proc_mode:
                # The delivery fabric cannot interpose on another
                # process's TCP stack — instead the fault is armed in
                # each source WORKER's daemon over the RPC pipe
                # (PyXferd's netem-like link shim): same selectors,
                # same actions, applied in the send path.
                record["applied"] = self._apply_proc_link_fault(
                    fault, record)
                # Mirror the fault into the coordinator's link table
                # as ANNOTATION state (no frame routes through it in
                # proc mode): the collective engine's comm graph and
                # the scheduler's link-health penalty read the same
                # evidence in both fleet modes.  One honest asymmetry:
                # a mirrored drop BUDGET never decrements here (the
                # frames that spend it cross worker TCP, not this
                # table), so the edge reads degraded until a heal —
                # conservative planning, never the reverse.
                self.links.apply(fault)
            else:
                record["applied"] = len(self.links.apply(fault))
            lifetime = int(entry.get("for", 0))
            inverse = fault.inverse()
            if lifetime > 0 and inverse is not None:
                self._deferred.setdefault(rnd + lifetime, []).append(
                    {"link": inverse}
                )
            return record
        action = entry.get("action", "")
        node = self.nodes.get(entry.get("node", ""))
        if node is None:
            log.error("fault entry names unknown node: %r", entry)
            record["applied"] = 0
            return record
        try:
            if action == "chip_fault":
                node.inject_chip_fault(entry.get("chip", "accel0"),
                                       int(entry.get("code", 48)))
            elif action == "chip_recover":
                record["recovered"] = node.force_recover()
            elif action == "kill":
                node.kill_daemon()
                lifetime = int(entry.get("for", 0))
                if lifetime > 0:
                    self._deferred.setdefault(rnd + lifetime, []).append(
                        {"action": "restart", "node": node.name}
                    )
            elif action == "restart":
                if node.restart_daemon() is False:
                    # Refused (permanently down / budget spent): the
                    # round log must not claim a respawn that never
                    # happened — that's the scenario's whole verdict.
                    record["applied"] = 0
                    record["skipped"] = "restart refused (node " \
                        "permanently down or budget exhausted)"
                    return record
            else:
                log.error("unknown fault action %r", action)
        except OSError as e:
            # A fault aimed at a node whose worker is dark (SIGKILLed
            # earlier in the schedule, or mid-crash): in proc mode the
            # RPC has no one to talk to.  Degrade, don't crash — same
            # rule as link faults above; the round log says why.
            log.error("fault %r on node %s not applied: %s",
                      action, node.name, e)
            record["applied"] = 0
            record["skipped"] = str(e)
            return record
        record["applied"] = 1
        return record

    def _apply_proc_link_fault(self, fault: LinkFault,
                               record: dict) -> int:
        """Arm one parsed link fault across a process-mode fleet: the
        selectors resolve to directed node pairs (the link table's own
        resolution), and each pair becomes a shim entry in the SOURCE
        worker's daemon keyed by the destination's current data port.
        A dark source worker degrades that pair (recorded), never the
        schedule; a destination respawn resets its inbound shim state
        (fresh port — the same reset its flows get)."""
        applied = 0
        skipped = []
        for src, dst in self.links.pairs_for(fault):
            sn, dn = self.nodes.get(src), self.nodes.get(dst)
            if sn is None or dn is None:
                continue
            try:
                applied += sn.apply_link_fault(
                    dn.daemon.data_port, fault.action, fault.param)
            except OSError as e:
                skipped.append(f"{src}->{dst}: {e}")
        if skipped:
            record["skipped"] = "; ".join(skipped)
        return applied

    # -- workload ------------------------------------------------------------

    def _leg(self, rnd: int, src: EmulatedNode, dst: EmulatedNode) -> dict:
        """One one-way transfer src → dst, retried under the leg
        budget.  Flow names are unique per (round, pair) so retries
        never collide with the daemons' duplicate-flow rejection."""
        payload = bytes([(rnd * 31 + len(src.name)) % 256]) \
            * self.payload_bytes
        # ONE name, registered on both daemons: frames land into the
        # flow of the same name on the receiver (the exchange_shard
        # convention); unique per (round, pair) so retries never hit
        # duplicate-flow rejection.
        flow = f"r{rnd}.{src.name}.{dst.name}"
        tx = rx = flow
        result = {"src": src.name, "dst": dst.name, "ok": False,
                  "attempts": 0}
        with trace.span("fleet.leg", histogram="fleet.leg", round=rnd,
                        src=src.name, dst=dst.name,
                        bytes=self.payload_bytes,
                        pipelined=self.pipelined) as span:
            try:
                dst.client.register_flow(rx, peer=src.name,
                                         bytes=self.payload_bytes)
                src.client.register_flow(tx, peer=dst.name,
                                         bytes=self.payload_bytes)
                if not self.pipelined:
                    # Serial leg: whole-payload staging up front.  The
                    # pipelined leg stages chunk-by-chunk inside each
                    # send attempt instead (a retry after a daemon kill
                    # must restage anyway).
                    src.client.put(tx, payload)
                    dcn.wait_flow_rx(src.client, tx, len(payload),
                                     timeout_s=self.land_timeout_s)
                last: Optional[BaseException] = None
                for _attempt in self.leg_retry.attempts():
                    result["attempts"] += 1
                    try:
                        if self.pipelined:
                            dcn_pipeline.send_pipelined(
                                src.client, tx, payload, "127.0.0.1",
                                dst.daemon.data_port, self.pipe_cfg,
                                timeout_s=self.land_timeout_s)
                            got = dcn_pipeline.read_pipelined(
                                dst.client, rx, len(payload),
                                self.pipe_cfg,
                                timeout_s=self.land_timeout_s)
                        else:
                            src.client.send(tx, "127.0.0.1",
                                            dst.daemon.data_port,
                                            len(payload))
                            dcn.wait_flow_rx(dst.client, rx,
                                             len(payload),
                                             timeout_s=self.land_timeout_s)
                            got = dst.client.read(rx, len(payload))
                        if got != payload:
                            raise DcnXferError(
                                f"payload mismatch on {flow}"
                            )
                        result["ok"] = True
                        return result
                    except (DcnXferError, OSError, TimeoutError) as e:
                        last = e
                result["error"] = str(last)
                span.annotate(error=str(last))
                return result
            except (DcnXferError, OSError, TimeoutError) as e:
                result["error"] = str(e)
                span.annotate(error=str(e))
                return result
            finally:
                span.annotate(ok=result["ok"],
                              attempts=result["attempts"])
                for node, flow in ((src, tx), (dst, rx)):
                    try:
                        node.client.release_flow(flow)
                    except (DcnXferError, OSError):
                        pass

    def _serving_round(self, rnd: int, per_node_ok: Dict[str, int],
                       per_node_failed: Dict[str, int]) -> dict:
        """One serving round: spray ``requests_per_round`` requests at
        the frontend, wait for every one to TERMINATE (result, error,
        or shed — a request silently lost fails the round outright),
        and fold the frontend's per-node dispatch deltas into the
        report's per-node accounting.  The round-log entry keeps the
        same ``ok``-bool convergence contract as a ring leg."""
        serving = self.scenario.get("serving") or {}
        n = int(serving.get("requests_per_round", 16))
        wait_s = float(serving.get("round_deadline_s", 20.0))
        stats0 = {name: dict(st)
                  for name, st in self.frontend.node_stats.items()}
        reqs = []
        shed = 0
        entry = {"workload": "serving", "requests": n}
        with trace.span("fleet.serving_round", round=rnd, requests=n):
            for i in range(n):
                payload = bytes([(rnd * 31 + i) % 256]) \
                    * self.payload_bytes
                try:
                    reqs.append((self.frontend.submit(payload),
                                 payload))
                except RequestShed:
                    shed += 1
            ok = errors = lost = 0
            deadline = time.monotonic() + wait_s
            for req, payload in reqs:
                if not req.wait(max(0.0,
                                    deadline - time.monotonic())):
                    lost += 1  # never terminated: the worst verdict
                    continue
                if req.error is None and req.result == payload:
                    ok += 1
                else:
                    errors += 1
        for name, st in self.frontend.node_stats.items():
            per_node_ok[name] += st["ok"] - stats0[name]["ok"]
            per_node_failed[name] += (st["failed"]
                                      - stats0[name]["failed"])
        entry.update(
            accepted=len(reqs), shed=shed, ok_requests=ok,
            errors=errors, lost=lost,
            ok=bool(reqs) and lost == 0 and errors == 0
            and ok == len(reqs),
        )
        return entry

    def _collective_round(self, rnd: int, per_node_ok: Dict[str, int],
                          per_node_failed: Dict[str, int]) -> dict:
        """One collective round: re-plan against the current comm
        graph if the fault state moved (the engine's synthesizer owns
        that), execute the schedule over the rig, and fold the
        per-node leg accounting into the report.  The entry keeps the
        ``ok``-bool convergence contract, and the telemetry layer
        collects the busbw history the `min_busbw_bps` /
        `min_final_busbw_bps` SLOs judge."""
        entry = self.collective.run_round(rnd)
        for name, n in entry.pop("per_node_ok").items():
            per_node_ok[name] += n
        for name, n in entry.pop("per_node_failed").items():
            per_node_failed[name] += n
        tele = {k: entry[k] for k in ("ok", "algorithm", "busbw_bps",
                                      "resynth")}
        if "routed" in entry:
            # Routed-mode lane accounting rides along so the
            # min_forward_bytes / max_coordinator_leg_bytes SLOs can
            # judge the pure-control-plane claim per run.
            tele["routed"] = entry["routed"]
        self.telemetry.collective_rounds.append(tele)
        return entry

    def _ring(self) -> List[tuple]:
        names = list(self.nodes)
        n = len(names)
        return [(self.nodes[names[i]], self.nodes[names[(i + 1) % n]])
                for i in range(n)] if n > 1 else []

    # -- the run -------------------------------------------------------------

    def run(self) -> dict:
        self.boot()
        per_node_ok: Dict[str, int] = {n: 0 for n in self.nodes}
        per_node_failed: Dict[str, int] = {n: 0 for n in self.nodes}
        round_log = []
        with trace.span("fleet.scenario",
                        scenario=self.scenario.get("name", "fleet"),
                        nodes=len(self.nodes), rounds=self.rounds):
            scheduled = list(self.scenario.get("faults", []))
            for rnd in range(self.rounds):
                fired = []
                for entry in self._deferred.pop(rnd, []):
                    fired.append(self._apply_fault(rnd, entry))
                for entry in scheduled:
                    if int(entry.get("round", 0)) == rnd:
                        fired.append(self._apply_fault(rnd, entry))
                legs = []
                with trace.span("fleet.round", round=rnd):
                    if self.frontend is not None:
                        legs.append(self._serving_round(
                            rnd, per_node_ok, per_node_failed))
                    elif self.collective is not None:
                        legs.append(self._collective_round(
                            rnd, per_node_ok, per_node_failed))
                    else:
                        for src, dst in self._ring():
                            if src.down or dst.down:
                                legs.append({"src": src.name,
                                             "dst": dst.name,
                                             "skipped": "node down"})
                                continue
                            leg = self._leg(rnd, src, dst)
                            legs.append(leg)
                            if leg["ok"]:
                                per_node_ok[src.name] += 1
                            else:
                                per_node_failed[src.name] += 1
                    for node in self.nodes.values():
                        node.recover()
                # Scrape every node's registry while the round's
                # traffic is still inside the rate window.
                self.telemetry.sample_round(rnd)
                round_log.append(
                    {"round": rnd, "faults": fired, "legs": legs}
                )
        return self._report(round_log, per_node_ok, per_node_failed)

    def _report(self, round_log, per_node_ok, per_node_failed) -> dict:
        final_legs = round_log[-1]["legs"] if round_log else []
        survivors_converged = all(
            leg.get("ok", False) for leg in final_legs
            if "skipped" not in leg
        ) and bool(final_legs)
        # The serving zero-lost invariant gates the WHOLE run, not
        # just the final round: mid-chaos rounds may ERROR requests
        # (bounded budgets spent — the contract allows it), but a
        # request that never terminated is a correctness failure no
        # amount of later convergence buys back.
        serving_lost = sum(
            leg.get("lost", 0)
            for entry in round_log for leg in entry["legs"]
            if leg.get("workload") == "serving")
        nodes_report = {}
        all_up_healthy = True
        for name, node in self.nodes.items():
            snap = node.snapshot()
            snap["legs_ok"] = per_node_ok[name]
            snap["legs_failed"] = per_node_failed[name]
            nodes_report[name] = snap
            # Judge healthiness from the snapshot in hand: in proc
            # mode all_healthy() would issue a SECOND snapshot RPC per
            # node, and the two could disagree mid-recovery.
            if not snap.get("down") and not (
                    snap.get("total", 0) > 0
                    and snap.get("healthy") == snap.get("total")):
                all_up_healthy = False
        # A node whose restart budget exhausted is permanently down:
        # its legs being "skipped" must not let the scenario converge —
        # capacity is gone and nothing will bring it back.
        none_permanently_down = not any(
            getattr(node, "permanently_down", False)
            for node in self.nodes.values()
        )
        # Observability snapshot: THIS process's counters and latency
        # histograms.  In the one-process rig the process registries
        # ARE the fleet's; in proc mode this is the coordinator side
        # only (client/pipeline healing) — the workers' registries
        # arrive via the telemetry section's HTTP scrapes instead.
        delta = {}
        now = counters.snapshot()
        for k, v in now.items():
            d = v - self._counters0.get(k, 0)
            if d:
                delta[k] = d
        latency = {
            op: {"count": h["count"],
                 "p50_us": (histo.percentile(op, 0.5) or 0) * 1e6,
                 "p99_us": (histo.percentile(op, 0.99) or 0) * 1e6}
            for op, h in histo.snapshot().items()
            if op.startswith(("fleet.", "xferd.", "dcn."))
        }
        links_report = self.links.report()
        # Where did the run's wall-clock go: span trees from the
        # coordinator ring (+ scraped workers in proc mode) rolled up
        # per request shape, with the dominant phase named
        # (obs/critpath.py).  A latency-faulted link shows up HERE as
        # "dcn.chunk.send dominated", not just as a slower p99.
        critical_path = critpath.analyze(self.telemetry.spans())
        critical_path["dropped_spans"] = self.telemetry.spans_dropped
        report_extra = {}
        if self.collective is not None:
            graph = self.collective.graph()
            report_extra["collective"] = {
                "resynth": self.collective.synth.resynth_count,
                "schedule": (
                    self.collective.synth.current().to_dict()
                    if self.collective.synth.current() else None),
                # The placement side of the same evidence: per-node
                # partitioned/degraded link rollup — what the
                # scheduler's link-health penalty steers on.
                "node_health": graph.node_health(),
            }
        if self.frontend is not None:
            report_extra["serving"] = {
                "breakers": self.frontend.breaker.snapshot(),
                "node_stats": {
                    name: dict(st) for name, st
                    in self.frontend.node_stats.items()
                },
                "lost_requests": serving_lost,
            }
        return {
            "scenario": self.scenario.get("name", "fleet"),
            "proc": self.proc_mode,
            "workload": self.workload,
            **report_extra,
            "nodes": nodes_report,
            "links": links_report,
            "rounds": round_log,
            "agent_events_delta": delta,
            "agent_latency": latency,
            "critical_path": critical_path,
            # Where did the CPU go: merged continuous-profiler stacks
            # (per worker via /profile scrapes in proc mode, plus the
            # coordinator's own sampler) — the companion question to
            # critical_path's "where did the wall time go".
            "profile": self.telemetry.profile_report(),
            # Grey-failure verdicts (obs/anomaly.py): live peer-
            # relative suspicion per node, plus the closed-loop
            # precision/recall judgment when the soak world fed its
            # seeded schedule as ground truth.
            "anomaly": self.telemetry.anomaly_report(),
            "telemetry": {"rounds": self.telemetry.history},
            "slo": self.telemetry.evaluate(links_report),
            "converged": (survivors_converged and all_up_healthy
                          and none_permanently_down
                          and serving_lost == 0),
        }

    # -- coordinator env -----------------------------------------------------

    def child_env(self, base: Optional[dict] = None) -> dict:
        """Env for a worker process this coordinator spawns: the active
        trace context rides TPU_TRACE_CONTEXT so the child's spans join
        the coordinator's trace (obs/trace.attach_from_env)."""
        env = dict(os.environ if base is None else base)
        ctx = trace.context_env()
        if ctx:
            env[trace.TRACE_CONTEXT_ENV] = ctx
        return env


def run_scenario(scenario: Optional[dict] = None,
                 workdir: Optional[str] = None) -> dict:
    """One-shot convenience: boot, run, close, return the report."""
    ctl = FleetController(scenario, workdir=workdir)
    try:
        return ctl.run()
    finally:
        ctl.close()
