"""The fleet link table: every inter-node DCN frame routes through here.

The chaos framework so far injects *endpoint* faults — a daemon dies, a
socket drops (utils/faults.py).  Link-level faults are a different
animal: a partitioned rack rejects traffic in BOTH directions while
every endpoint stays healthy; asymmetric loss eats one direction of an
exchange; injected latency stretches a collective without failing it
(TACCL's observation, PAPERS.md).  The :class:`LinkTable` models the
fleet's directed links as explicit state (up/latency/drop-budget) plus
per-link accounting, and :class:`FleetNet` is the delivery fabric the
emulated daemons hand frames to — so EVERY cross-node byte in the rig
passes one inspectable, faultable point.

Link-fault spec grammar (scenario ``link:`` entries, README "Fleet
simulation")::

    <sel><-><sel>:<action>[:<param>]     both directions
    <sel>-><sel>:<action>[:<param>]      one direction (asymmetric)

    sel     = * | node:<name> | rack:<name>
    action  = partition | heal | latency:<ms> | drop:<k>

    rack:r0<->rack:r1:partition    # rack pair falls off the network
    node:n0->node:n2:latency:5     # 5 ms one-way delay
    *->rack:r1:drop:3              # next 3 frames per link are eaten

A malformed spec entry is logged and skipped — the TPU_FAULT_SPEC rule:
bad chaos config must never take the rig (or an agent) down.
"""

import dataclasses
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from container_engine_accelerators_tpu.fleet.topology import FleetTopology
from container_engine_accelerators_tpu.metrics import counters

log = logging.getLogger(__name__)

# Injected one-way latency is capped so a typo ("latency:5000") cannot
# wedge a scenario: the rig models *relative* slowness, not real WAN RTTs.
MAX_INJECT_LATENCY_S = 0.25


class LinkPartitioned(OSError):
    """A frame hit a partitioned link (the sender's daemon surfaces
    this as a daemon-level op failure, like a real dial timeout)."""


@dataclasses.dataclass
class LinkStats:
    frames: int = 0
    bytes: int = 0
    drops: int = 0       # loss-injection ate the frame in flight
    dups: int = 0        # receiver dedup dropped a replayed frame
    blocked: int = 0     # send rejected: link partitioned
    latency_injected_s: float = 0.0


@dataclasses.dataclass
class LinkState:
    up: bool = True
    latency_s: float = 0.0
    drop_next: int = 0
    stats: LinkStats = dataclasses.field(default_factory=LinkStats)


@dataclasses.dataclass
class LinkFault:
    """One parsed spec entry."""

    sel_a: str
    sel_b: str
    bidirectional: bool
    action: str
    param: float = 0.0

    def spec(self) -> str:
        """Back to grammar form (round logs must stay JSON-clean)."""
        arrow = "<->" if self.bidirectional else "->"
        suffix = ""
        if self.action == "latency":
            suffix = f":{self.param * 1e3:g}"
        elif self.action == "drop":
            suffix = f":{int(self.param)}"
        return f"{self.sel_a}{arrow}{self.sel_b}:{self.action}{suffix}"

    def inverse(self) -> Optional["LinkFault"]:
        """The fault that undoes this one (scenario ``for:`` auto-heal);
        None when there is nothing to undo (a drop budget spends
        itself)."""
        if self.action == "partition":
            return dataclasses.replace(self, action="heal")
        if self.action == "latency":
            return dataclasses.replace(self, param=0.0)
        return None


def parse_link_fault(spec: str) -> Optional[LinkFault]:
    """Parse one grammar entry; None (logged) for malformed input."""
    try:
        if "<->" in spec:
            left, rest = spec.split("<->", 1)
            bidi = True
        elif "->" in spec:
            left, rest = spec.split("->", 1)
            bidi = False
        else:
            raise ValueError("expected '<->' or '->'")
        tokens = rest.split(":")
        if tokens[0] == "*":
            sel_b, tokens = "*", tokens[1:]
        elif len(tokens) >= 2 and tokens[0] in ("node", "rack"):
            sel_b, tokens = f"{tokens[0]}:{tokens[1]}", tokens[2:]
        else:
            raise ValueError(f"bad selector at {rest!r}")
        if not tokens:
            raise ValueError("missing action")
        action, params = tokens[0], tokens[1:]
        param = 0.0
        if action in ("partition", "heal"):
            if params:
                raise ValueError(f"{action} takes no parameter")
        elif action == "latency":
            param = float(params[0]) / 1e3 if params else 0.0
            if param < 0:
                raise ValueError("latency must be >= 0")
        elif action == "drop":
            param = float(int(params[0])) if params else 1.0
            if param < 1:
                raise ValueError("drop count must be >= 1")
        else:
            raise ValueError(f"unknown action {action!r}")
        left = left.strip()
        if not (left == "*" or left.startswith(("node:", "rack:"))):
            raise ValueError(f"bad selector {left!r}")
        return LinkFault(sel_a=left, sel_b=sel_b, bidirectional=bidi,
                         action=action, param=param)
    except (ValueError, IndexError) as e:
        log.error("ignoring malformed link-fault spec %r: %s", spec, e)
        return None


class LinkTable:
    """Directed per-(src, dst) link state for one fleet."""

    def __init__(self, topology: FleetTopology):
        self.topology = topology
        self._links: Dict[Tuple[str, str], LinkState] = {}
        self._lock = threading.Lock()

    def state(self, src: str, dst: str) -> LinkState:
        with self._lock:
            link = self._links.get((src, dst))
            if link is None:
                link = self._links[(src, dst)] = LinkState()
            return link

    def pairs_for(self, fault: LinkFault) -> List[Tuple[str, str]]:
        """The directed node pairs a fault touches (self-links never)."""
        a_nodes = self.topology.select(fault.sel_a)
        b_nodes = self.topology.select(fault.sel_b)
        out = []
        for a in a_nodes:
            for b in b_nodes:
                if a == b:
                    continue
                out.append((a, b))
                if fault.bidirectional:
                    out.append((b, a))
        # Dedup while preserving order (rack:r0<->rack:r0 style overlap).
        seen = set()
        uniq = []
        for p in out:
            if p not in seen:
                seen.add(p)
                uniq.append(p)
        return uniq

    def apply(self, fault_or_spec) -> List[Tuple[str, str]]:
        """Arm one fault (a parsed :class:`LinkFault` or a grammar
        string); returns the directed pairs touched."""
        fault = (fault_or_spec if isinstance(fault_or_spec, LinkFault)
                 else parse_link_fault(fault_or_spec))
        if fault is None:
            return []
        pairs = self.pairs_for(fault)
        for src, dst in pairs:
            link = self.state(src, dst)
            if fault.action == "partition":
                link.up = False
            elif fault.action == "heal":
                link.up = True
                link.latency_s = 0.0
                link.drop_next = 0
            elif fault.action == "latency":
                link.latency_s = min(fault.param, MAX_INJECT_LATENCY_S)
            elif fault.action == "drop":
                link.drop_next += int(fault.param)
        if pairs:
            log.warning("link fault %s armed on %d link(s)",
                        fault.action, len(pairs))
        return pairs

    def snapshot_state(self) -> Dict[Tuple[str, str], dict]:
        """Current fault state per touched directed link — the comm
        graph's evidence source (collectives/topo.py).  Only links the
        table has actually seen (faulted or carried traffic) appear;
        an absent pair means "no evidence", which callers read as
        healthy at its tier's defaults."""
        with self._lock:
            return {
                pair: {
                    "up": link.up,
                    "latency_s": link.latency_s,
                    "drop_next": link.drop_next,
                }
                for pair, link in self._links.items()
            }

    def report(self) -> Dict[str, dict]:
        """Per-link accounting for the fleet report, tier-annotated via
        the production scheduler distance."""
        with self._lock:
            items = list(self._links.items())
        out = {}
        for (src, dst), link in items:
            out[f"{src}->{dst}"] = {
                "tier": self.topology.tier(src, dst),
                "up": link.up,
                "frames": link.stats.frames,
                "bytes": link.stats.bytes,
                "drops": link.stats.drops,
                "dups": link.stats.dups,
                "blocked": link.stats.blocked,
                "latency_injected_ms": round(
                    link.stats.latency_injected_s * 1e3, 3
                ),
            }
        return out


class FleetNet:
    """The delivery fabric: routes a sending daemon's frames to the
    destination daemon through the link table.

    Registration is by data port — the same address a real client would
    dial — so the daemons stay protocol-faithful: ``send`` still takes
    (host, port), and the fabric resolves which emulated node owns it.
    """

    def __init__(self, table: LinkTable):
        self.table = table
        self._by_port: Dict[int, Tuple[str, object]] = {}
        self._lock = threading.Lock()

    def register(self, node: str, daemon) -> None:
        with self._lock:
            # A restarted daemon binds a fresh port; drop stale entries
            # for the node so the table never routes to a dead object.
            for port, (name, _d) in list(self._by_port.items()):
                if name == node:
                    del self._by_port[port]
            self._by_port[int(daemon.data_port)] = (node, daemon)

    def unregister(self, node: str) -> None:
        with self._lock:
            for port, (name, _d) in list(self._by_port.items()):
                if name == node:
                    del self._by_port[port]

    def lookup(self, port: int) -> Optional[Tuple[str, object]]:
        with self._lock:
            return self._by_port.get(int(port))

    def deliver(self, src: str, host: str, port: int, flow: str,
                payload: bytes, seq: Optional[int], meta: dict) -> str:
        """Route one frame src → (host, port).  Returns the landing
        verdict ("landed" / "dup" / "dropped" / "unmatched"); raises
        :class:`LinkPartitioned` when the link is down (the sender's op
        fails, exactly like a dial into a null route)."""
        entry = self.lookup(port)
        if entry is None:
            raise LinkPartitioned(
                f"no fleet node listens on port {port} (node down?)"
            )
        dst, daemon = entry
        link = self.table.state(src, dst)
        if not link.up:
            link.stats.blocked += 1
            counters.inc("fleet.link.blocked")
            raise LinkPartitioned(f"link {src}->{dst} partitioned")
        if link.drop_next > 0:
            # Loss: the sender believes the frame left; the receiver
            # never sees it.  The retransmit (same seq) lands cleanly —
            # the dedup window only rejects seqs that actually LANDED.
            link.drop_next -= 1
            link.stats.drops += 1
            counters.inc("fleet.link.dropped")
            return "dropped"
        if link.latency_s > 0:
            time.sleep(link.latency_s)
            link.stats.latency_injected_s += link.latency_s
        verdict = daemon.land_frame(flow, payload, seq, meta,
                                    link=(src, dst))
        if verdict == "dup":
            link.stats.dups += 1
        else:
            link.stats.frames += 1
            link.stats.bytes += len(payload)
        return verdict
