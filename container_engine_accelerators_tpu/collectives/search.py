"""Sketch-guided collective schedule search over the measured graph.

The three hand-written families (ring / tree / hierarchical) each
freeze one communication shape; an asymmetric rig — unequal racks, one
degraded spine link — needs a shape none of them expresses.  TACCL
(PAPERS.md) shows the fix: synthesize the schedule against the
measured alpha-beta topology, guided by a small *communication sketch*
that bounds the search instead of exploring raw send/recv programs.

This module is that synthesis engine.  A :class:`Sketch` is a hint
bundle from a tiny grammar:

- ``ring:<order>`` — run the classic ring family over a *searched*
  node order (rack-major baseline, greedy nearest-neighbor
  construction from measured leg costs, bounded 2-opt descent), so a
  slow edge is routed to where the ring crosses it least;
- ``gateway:<g0,g1,...>`` — pick one *gateway* member per rack (the
  healthiest by measured cross-rack cost, so a degraded spine endpoint
  is steered around), reduce/gather inside each rack onto the
  gateway (``intra`` style ``star`` or ``ring``), exchange between
  gateways only (``xr`` style ``direct`` — a multi-root star over a
  ``chunks``-way granularity — or ``ring``), then fan back out.
  Gateways work on UNEQUAL racks, where the hierarchical family
  refuses to lower.

Every candidate is lowered to plain :class:`synth.TransferStep`
groups, scored with the existing :func:`synth.estimate_cost_s` cost
model over the measured :class:`CommGraph`, and the winner is executed
only after it reproduces :func:`synth.expected_outputs` under the
:func:`synth.simulate` oracle — a searched schedule that cannot prove
itself correct is rejected (``collective.search.rejected``) and the
next-best candidate takes its place.

Plugged into the Synthesizer as ``algorithm: searched`` (pin-only —
auto-selection stays with the free families), which buys the
signature-keyed cache and resynthesis-on-fault for free: a fault or a
heal changes the planning signature and the whole search re-runs
against the new measured costs.
"""

import dataclasses
import itertools
import logging
import math
from typing import Dict, List, Sequence, Tuple

from container_engine_accelerators_tpu.collectives import synth
from container_engine_accelerators_tpu.collectives.topo import CommGraph
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import timeseries, trace

log = logging.getLogger(__name__)

# Search bounds: the sketch grammar keeps the space tiny, these keep
# it tiny even on wide fleets.
GATEWAYS_PER_RACK = 2      # top-k healthiest members enumerated per rack
MAX_GATEWAY_COMBOS = 16    # cap on the per-rack gateway product
TWO_OPT_PASSES = 2         # bounded local descent on ring orders
VERIFY_SEED = 1            # oracle verification input seed


@dataclasses.dataclass(frozen=True)
class Sketch:
    """One point of the sketch grammar the search enumerates."""

    kind: str                        # "ring" | "gateway"
    order: Tuple[str, ...] = ()      # ring: explicit node order
    gateways: Tuple[str, ...] = ()   # gateway: one member per rack
    xr_style: str = "direct"         # gateway: "direct" | "ring"
    intra_style: str = "star"        # gateway: "star" | "ring"
    chunks: int = 0                  # gateway direct: exchange granularity

    def label(self) -> str:
        if self.kind == "ring":
            return "ring:" + ">".join(self.order)
        return (f"gateway:{','.join(self.gateways)}"
                f":xr={self.xr_style}:intra={self.intra_style}"
                f":chunks={self.chunks}")


# -- ring-order search -------------------------------------------------------


def _tour_cost(graph: CommGraph, order: Sequence[str],
               probe: int) -> float:
    total = 0.0
    n = len(order)
    for i in range(n):
        total += graph.leg_cost_s(order[i], order[(i + 1) % n], probe)
    return total


def _greedy_order(graph: CommGraph, start: str, names: Sequence[str],
                  probe: int) -> List[str]:
    """Nearest-neighbor construction: always extend the ring over the
    cheapest measured edge out of the current tail."""
    left = [n for n in names if n != start]
    out = [start]
    while left:
        nxt = min(left,
                  key=lambda n: (graph.leg_cost_s(out[-1], n, probe), n))
        out.append(nxt)
        left.remove(nxt)
    return out


def _two_opt(graph: CommGraph, order: List[str],
             probe: int) -> List[str]:
    """Bounded 2-opt descent: reverse any segment whose reversal
    lowers the directed tour cost, a few passes at most (the rigs are
    small; this is a polish, not an exhaustive TSP solve)."""
    best = list(order)
    cost = _tour_cost(graph, best, probe)
    n = len(best)
    for _ in range(TWO_OPT_PASSES):
        improved = False
        for i in range(1, n - 1):
            for j in range(i + 1, n):
                cand = best[:i] + best[i:j][::-1] + best[j:]
                c = _tour_cost(graph, cand, probe)
                if c < cost:
                    best, cost, improved = cand, c, True
        if not improved:
            break
    return best


def _ring_orders(graph: CommGraph,
                 nbytes: int) -> List[Tuple[str, ...]]:
    base = graph.order()
    probe = max(1, nbytes // len(base))
    cands = [list(base),
             _greedy_order(graph, base[0], base, probe)]
    cands += [_two_opt(graph, c, probe) for c in list(cands)]
    seen, out = set(), []
    for c in cands:
        key = tuple(c)
        if key not in seen:
            seen.add(key)
            out.append(key)
    return out


# -- gateway selection -------------------------------------------------------


def _gateway_choices(graph: CommGraph, racks: List[List[str]],
                     probe: int) -> List[Tuple[str, ...]]:
    """Per rack, the top GATEWAYS_PER_RACK members by summed measured
    cross-rack cost (both directions — a spine fault on either side
    makes that member a bad gateway), then the capped product."""
    per_rack: List[List[str]] = []
    for r, members in enumerate(racks):
        others = [n for r2, ms in enumerate(racks) if r2 != r
                  for n in ms]
        scored = sorted(
            members,
            key=lambda m: (sum(graph.leg_cost_s(m, o, probe)
                               + graph.leg_cost_s(o, m, probe)
                               for o in others), m))
        per_rack.append(scored[:GATEWAYS_PER_RACK])
    combos = []
    for combo in itertools.product(*per_rack):
        combos.append(tuple(combo))
        if len(combos) >= MAX_GATEWAY_COMBOS:
            break
    return combos


def sketches(graph: CommGraph, nbytes: int) -> List[Sketch]:
    """Enumerate the sketch grammar for this fleet shape."""
    out = [Sketch(kind="ring", order=o)
           for o in _ring_orders(graph, nbytes)]
    racks = list(graph.racks().values())
    if len(racks) >= 2:
        g = len(racks)
        probe = max(1, nbytes // max(1, sum(len(r) for r in racks)))
        for gws in _gateway_choices(graph, racks, probe):
            for intra in ("star", "ring"):
                out.append(Sketch(kind="gateway", gateways=gws,
                                  xr_style="ring", intra_style=intra))
                for c in sorted({g, min(2 * g, 8)}):
                    out.append(Sketch(kind="gateway", gateways=gws,
                                      xr_style="direct",
                                      intra_style=intra, chunks=c))
    return out


# -- sketch lowering ---------------------------------------------------------


def _rack_regions(racks: List[List[str]],
                  nbytes: int) -> Tuple[List[Tuple[int, int]],
                                        List[Tuple[int, int]],
                                        Dict[str, int]]:
    """Global n-way chunking (rack-major, matching ``graph.order()``),
    each rack's contiguous region, and each node's global chunk index."""
    n = sum(len(r) for r in racks)
    chunks = synth.partition(nbytes, n)
    regions, owner_chunk = [], {}
    idx = 0
    for members in racks:
        start = idx
        for m in members:
            owner_chunk[m] = idx
            idx += 1
        off = chunks[start][0]
        ln = sum(chunks[i][1] for i in range(start, idx))
        regions.append((off, ln))
    return chunks, regions, owner_chunk


def _intra_reduce(racks: List[List[str]], gws: Sequence[str],
                  style: str, nbytes: int) -> List[List[synth.TransferStep]]:
    """Reduce every rack's buffers onto its gateway.  ``star``: one
    full-buffer fan-in group.  ``ring``: rack-local ring
    reduce-scatter (lockstep across racks) then a chunk gather — more
    groups, but no single endpoint is charged the whole fan-in."""
    steps: List[List[synth.TransferStep]] = []
    if style == "star":
        group = [synth.TransferStep(src=m, dst=gw, offset=0,
                                    nbytes=nbytes, reduce=True,
                                    phase="intra")
                 for members, gw in zip(racks, gws)
                 for m in members if m != gw]
        if group:
            steps.append(group)
        return steps
    local = [synth.partition(nbytes, len(members)) for members in racks]
    max_k = max(len(members) for members in racks)
    for s in range(max_k - 1):
        group = []
        for members, chunks in zip(racks, local):
            k = len(members)
            if s >= k - 1:
                continue
            for i in range(k):
                off, ln = chunks[(i - s - 1) % k]
                if ln == 0:
                    continue
                group.append(synth.TransferStep(
                    src=members[i], dst=members[(i + 1) % k],
                    offset=off, nbytes=ln, reduce=True, phase="intra"))
        if group:
            steps.append(group)
    gather = []
    for members, chunks, gw in zip(racks, local, gws):
        for i, m in enumerate(members):
            off, ln = chunks[i]
            if m == gw or ln == 0:
                continue
            gather.append(synth.TransferStep(
                src=m, dst=gw, offset=off, nbytes=ln, reduce=False,
                phase="intra"))
    if gather:
        steps.append(gather)
    return steps


def _xr_all_reduce(gws: Sequence[str], sk: Sketch,
                   nbytes: int) -> List[List[synth.TransferStep]]:
    if sk.xr_style == "ring":
        return [[dataclasses.replace(t, phase="xr") for t in g]
                for g in synth._ring(list(gws), "all_reduce", nbytes)]
    g = len(gws)
    chunks = synth.partition(nbytes, max(sk.chunks, g))
    up, down = [], []
    for i, (off, ln) in enumerate(chunks):
        if ln == 0:
            continue
        owner = gws[i % g]
        for gw in gws:
            if gw == owner:
                continue
            up.append(synth.TransferStep(src=gw, dst=owner, offset=off,
                                         nbytes=ln, reduce=True,
                                         phase="xr"))
            down.append(synth.TransferStep(src=owner, dst=gw,
                                           offset=off, nbytes=ln,
                                           reduce=False, phase="xr"))
    return [grp for grp in (up, down) if grp]


def _lower_gateway(racks: List[List[str]], sk: Sketch, collective: str,
                   nbytes: int) -> List[List[synth.TransferStep]]:
    gws = list(sk.gateways)
    chunks, regions, owner_chunk = _rack_regions(racks, nbytes)
    steps: List[List[synth.TransferStep]] = []
    if collective in ("all_reduce", "reduce_scatter"):
        steps += _intra_reduce(racks, gws, sk.intra_style, nbytes)
        if collective == "all_reduce":
            steps += _xr_all_reduce(gws, sk, nbytes)
            down = [synth.TransferStep(src=gw, dst=m, offset=0,
                                       nbytes=nbytes, reduce=False,
                                       phase="down")
                    for members, gw in zip(racks, gws)
                    for m in members if m != gw]
            if down:
                steps.append(down)
            return steps
        # reduce_scatter: cross-rack reduce of each rack's region onto
        # its own gateway, then scatter members their own chunks.
        if sk.xr_style == "ring":
            steps += synth._ring_phase(gws, regions, True, "xr")
        else:
            xr = [synth.TransferStep(src=gws[r], dst=gws[r2],
                                     offset=regions[r2][0],
                                     nbytes=regions[r2][1], reduce=True,
                                     phase="xr")
                  for r in range(len(gws))
                  for r2 in range(len(gws))
                  if r2 != r and regions[r2][1] > 0]
            if xr:
                steps.append(xr)
        down = []
        for members, gw in zip(racks, gws):
            for m in members:
                off, ln = chunks[owner_chunk[m]]
                if m == gw or ln == 0:
                    continue
                down.append(synth.TransferStep(
                    src=gw, dst=m, offset=off, nbytes=ln, reduce=False,
                    phase="down"))
        if down:
            steps.append(down)
        return steps
    # all_gather: members hand their own chunk up, gateways exchange
    # whole rack regions, every member gets the full buffer back.
    up = []
    for members, gw in zip(racks, gws):
        for m in members:
            off, ln = chunks[owner_chunk[m]]
            if m == gw or ln == 0:
                continue
            up.append(synth.TransferStep(src=m, dst=gw, offset=off,
                                         nbytes=ln, reduce=False,
                                         phase="intra"))
    if up:
        steps.append(up)
    if sk.xr_style == "ring":
        steps += synth._ring_phase(gws, regions, False, "xr")
    else:
        xr = [synth.TransferStep(src=gws[r], dst=gws[r2],
                                 offset=regions[r][0],
                                 nbytes=regions[r][1], reduce=False,
                                 phase="xr")
              for r in range(len(gws))
              for r2 in range(len(gws))
              if r2 != r and regions[r][1] > 0]
        if xr:
            steps.append(xr)
    down = [synth.TransferStep(src=gw, dst=m, offset=0, nbytes=nbytes,
                               reduce=False, phase="down")
            for members, gw in zip(racks, gws)
            for m in members if m != gw]
    if down:
        steps.append(down)
    return steps


def lower_sketch(graph: CommGraph, sk: Sketch, collective: str,
                 nbytes: int) -> List[List[synth.TransferStep]]:
    """Lower one sketch to barrier-grouped transfer steps.  Every
    lowering here is hazard-free by construction (no node's read
    region overlaps a write aimed at it within one group), which is
    what lets the routed execution plane fire a whole group of
    daemon→daemon forwards concurrently without snapshots."""
    if sk.kind == "ring":
        return synth._ring(list(sk.order), collective, nbytes)
    if sk.kind == "gateway":
        racks = list(graph.racks().values())
        if len(racks) < 2:
            raise synth.SynthesisError("gateway sketch needs >= 2 racks")
        return _lower_gateway(racks, sk, collective, nbytes)
    raise synth.SynthesisError(f"unknown sketch kind {sk.kind!r}")


# -- search + oracle verification --------------------------------------------


def _verified(steps: List[List[synth.TransferStep]], order: List[str],
              collective: str, nbytes: int) -> bool:
    """Run the candidate through the simulate() oracle and compare
    every node's contract region against expected_outputs — the gate
    between "scored well" and "allowed on the wire"."""
    inputs = synth.make_inputs(collective, order, nbytes,
                               seed=VERIFY_SEED)
    want = synth.expected_outputs(collective, order, inputs, nbytes)
    sched = synth.Schedule(collective=collective, algorithm="searched",
                           nbytes=nbytes, order=list(order),
                           steps=steps, est_cost_s=0.0, signature=())
    got = synth.simulate(sched, inputs)
    for node, (off, ln, data) in want.items():
        if bytes(got[node][off:off + ln]) != data:
            return False
    return True


def search_steps(graph: CommGraph, collective: str,
                 nbytes: int) -> List[List[synth.TransferStep]]:
    """The ``algorithm: searched`` entry point synth._lower dispatches
    to: enumerate the sketch grammar, score every lowerable candidate
    with the measured cost model, prune unroutable ones, and emit the
    cheapest candidate that passes oracle verification."""
    order = graph.order()
    with trace.span("collective.search", collective=collective,
                    bytes=nbytes, nodes=len(order)):
        scored = []
        for idx, sk in enumerate(sketches(graph, nbytes)):
            try:
                steps = lower_sketch(graph, sk, collective, nbytes)
            except synth.SynthesisError:
                continue
            counters.inc("collective.search.candidates")
            cost = synth.estimate_cost_s(graph, steps)
            scored.append((cost, idx, sk, steps))
        if not scored:
            raise synth.SynthesisError(
                f"no sketch lowers {collective} over this fleet")
        finite = [c for c in scored if math.isfinite(c[0])]
        if finite and len(finite) < len(scored):
            # Unroutable candidates (a leg through a partition) are
            # pruned — unless everything is partitioned, in which case
            # the least-bad schedule still ships and the heal's
            # re-synthesis fixes it (same contract as the families).
            counters.inc("collective.search.pruned",
                         len(scored) - len(finite))
            scored = finite
        # Primary: modeled cost.  Tie-break: FEWER barrier groups —
        # every group is a coordination round (a barrier wait, and in
        # routed mode a verdict round-trip) the alpha-beta model does
        # not charge, so between cost-equal candidates the shallower
        # schedule wins on the wire.  Enumeration index last keeps the
        # sort total.
        scored.sort(key=lambda c: (c[0], len(c[3]), c[1]))
        for cost, _idx, sk, steps in scored:
            if not _verified(steps, order, collective, nbytes):
                counters.inc("collective.search.rejected")
                log.error("searched candidate %s failed oracle "
                          "verification; trying next-best", sk.label())
                continue
            counters.inc("collective.search.verified")
            _record_margin(graph, collective, nbytes, cost)
            log.info("searched schedule: %s (est %.3f ms, "
                     "%d candidates)", sk.label(), cost * 1e3,
                     len(scored))
            trace.event("collective.search.chosen", sketch=sk.label(),
                        collective=collective,
                        est_cost_ms=round(cost * 1e3, 3))
            return steps
        raise synth.SynthesisError(
            f"every searched candidate for {collective} failed oracle "
            "verification")


def _record_margin(graph: CommGraph, collective: str, nbytes: int,
                   searched_cost: float) -> None:
    """Model-predicted margin over the best auto family, as a gauge —
    the CLI's measured margin is the gate; this is the planning-time
    leading indicator beside it."""
    try:
        family = synth.synthesize(graph, collective, nbytes)
    except synth.SynthesisError:
        return
    if (math.isfinite(family.est_cost_s) and searched_cost > 0
            and math.isfinite(searched_cost)):
        timeseries.gauge("collective.search.margin",
                         family.est_cost_s / searched_cost)
