"""XLA collectives bandwidth rig — the nccl-tests analog, TPU-native.

The reference validates its comms stack with nccl-tests under MPI: a
message-size sweep 1M→512M (×2/step), 100 iters, 5 warmup, reporting bus
bandwidth (gpudirect-tcpx/nccl-config.yaml:17,60-63).  Here the transport
is XLA collectives over ICI/DCN and the launcher is JAX — same sweep
semantics, same bus-bandwidth accounting as nccl-tests:

    all-reduce      busbw = algbw * 2(n-1)/n
    all-gather      busbw = algbw * (n-1)/n      (S = total output bytes)
    reduce-scatter  busbw = algbw * (n-1)/n
    ppermute-ring   busbw = algbw               (point-to-point shift)

Collectives are expressed with shard_map + lax primitives so the exact
collective (not a GSPMD rewrite) is benchmarked.

CLI (the nccl-test pod's entrypoint, deploy/xla-collectives/):

    python -m container_engine_accelerators_tpu.collectives.bench \
        -b 1M -e 512M -f 2 --iters 100 --warmup 5 --op all_reduce \
        [--line-rate-gbps 1600 --pass-threshold 0.9]
"""

import argparse
import dataclasses
import functools
import json
import logging
import sys
import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
try:  # jax >= 0.6 promotes shard_map out of experimental
    from jax import shard_map
except ImportError:  # pragma: no cover — older pinned jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


log = logging.getLogger(__name__)


@dataclasses.dataclass
class CollectiveResult:
    op: str
    size_bytes: int  # total message size S (nccl-tests convention)
    time_us: float
    alg_bw_gbps: float  # GB/s
    bus_bw_gbps: float
    # Filled only by per-iteration timing (--percentiles): tail latency
    # of individual collective rounds, which a mean can't show (one
    # straggler link doubles p99 long before it moves the average).
    p50_us: Optional[float] = None
    p99_us: Optional[float] = None


class DcnBenchAccounting:
    """Mirror sweep traffic into the node dcnxferd's flow accounting.

    When the pod env carries ``DCN_UDS_DIR`` the bench registers a flow
    with the node transfer daemon and records each sweep point's bytes,
    so per-node `stats` (and anything scraping them) sees bench traffic
    exactly like workload traffic.  The client is the *resilient* one:
    a daemon restart mid-sweep reconnects, replays the flow, and the
    sweep finishes.  If the daemon stays gone past the retry budget the
    accounting degrades gracefully — logged once, disabled, bench
    results unaffected.
    """

    # Accounting only: reserve the minimum staging buffer, not the
    # sweep's max message size (the pool belongs to real transfers).
    FLOW_BYTES = 4096

    def __init__(self, client, flow: str):
        self._client = client
        self._flow = flow
        if self._client is not None:
            self._client.register_flow(self._flow, peer="bench",
                                       bytes=self.FLOW_BYTES)

    @classmethod
    def from_env(cls, flow: str) -> "DcnBenchAccounting":
        from container_engine_accelerators_tpu.parallel import dcn
        from container_engine_accelerators_tpu.parallel.dcn_client import (
            DcnXferError,
        )
        from container_engine_accelerators_tpu.utils.retry import RetryPolicy

        client = None
        try:
            # Small budget for the initial probe: optional accounting
            # must not stall bench startup ~30s when the sidecar is
            # down.  Once connected, swap in the full budget so a
            # mid-sweep daemon restart is actually covered.
            client = dcn.make_xfer_client(
                resilient=True,
                retry=RetryPolicy(max_attempts=3, initial_backoff_s=0.1,
                                  max_backoff_s=0.5, deadline_s=2.0),
            )
            acct = cls(client, flow)
            if client is not None:
                from container_engine_accelerators_tpu.parallel.dcn_client \
                    import DEFAULT_DCN_RETRY

                client._retry = DEFAULT_DCN_RETRY
            return acct
        except (DcnXferError, OSError) as e:
            log.error("dcn accounting unavailable: %s", e)
            if client is not None:  # connected but register_flow refused
                try:
                    client.close()
                except OSError:
                    pass
            return cls(None, flow)

    def record(self, result: "CollectiveResult") -> None:
        if self._client is None:
            return
        from container_engine_accelerators_tpu.parallel.dcn_client import (
            DcnXferError,
        )

        try:
            self._client.record_transfer(self._flow, result.size_bytes)
        except (DcnXferError, OSError) as e:
            log.error("dcn accounting disabled after terminal error: %s", e)
            self.close()
            self._client = None

    def close(self) -> None:
        if self._client is not None:
            try:
                self._client.close()
            except OSError:
                pass


def _parse_size(s: str) -> int:
    s = s.strip().upper()
    mult = 1
    if s.endswith("G"):
        mult, s = 2**30, s[:-1]
    elif s.endswith("M"):
        mult, s = 2**20, s[:-1]
    elif s.endswith("K"):
        mult, s = 2**10, s[:-1]
    return int(float(s) * mult)


def _bus_factor(op: str, n: int) -> float:
    # One accounting convention for both rigs: the XLA sweep here and
    # the fleet-rig engine (collectives/synth.py) share the factor.
    from container_engine_accelerators_tpu.collectives.synth import (
        bus_factor,
    )

    return bus_factor(op, n)


def _make_collective(op: str, mesh: Mesh) -> Callable:
    """Build a jitted fn(x, reps) running `reps` chained collectives.

    The chain lives INSIDE shard_map as a fori_loop over per-device local
    blocks, with a data dependency between iterations so XLA can neither
    elide nor overlap them — the same serialization nccl-tests enforces.
    Each iteration is made local-shape-preserving (slicing its own chunk
    back out of an all-gather, re-tiling a reduce-scatter) so the loop
    carries a fixed-shape value.
    """
    axis = mesh.axis_names[0]
    n = mesh.devices.size

    if op == "all_reduce":

        def one(c):
            return jax.lax.psum(c, axis)

    elif op == "all_gather":

        def one(c):
            gathered = jax.lax.all_gather(c, axis, tiled=True)  # (n*e,)
            idx = jax.lax.axis_index(axis)
            return jax.lax.dynamic_slice_in_dim(
                gathered, idx * c.shape[0], c.shape[0]
            )

    elif op == "reduce_scatter":

        def one(c):
            scattered = jax.lax.psum_scatter(c, axis, tiled=True)  # (e/n,)
            return jnp.tile(scattered, n)

    elif op == "ppermute":

        def one(c):
            perm = [(i, (i + 1) % n) for i in range(n)]
            return jax.lax.ppermute(c, axis, perm)

    else:
        raise ValueError(f"unknown collective {op!r}")

    def step(c):
        y = one(c)
        # psum output is typed axis-invariant; convert back to varying so
        # the fori_loop carry type is stable.  Other collectives already
        # produce varying outputs (pcast would reject a no-op cast).
        if op == "all_reduce":
            if hasattr(jax.lax, "pcast"):
                y = jax.lax.pcast(y, (axis,), to="varying")
            elif hasattr(jax.lax, "pvary"):
                y = jax.lax.pvary(y, (axis,))
        return y

    def local_loop(c, reps):
        return jax.lax.fori_loop(0, reps, lambda i, c: step(c), c)

    mapped = shard_map(
        local_loop,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(axis),
    )
    # reps is a traced argument (dynamic fori_loop bound), so warmup and
    # timed runs share ONE compiled executable — a separate warmup
    # executable would leave the timed one cold.
    return jax.jit(mapped)


def run_sweep(
    mesh: Optional[Mesh] = None,
    min_bytes: int = 2**20,
    max_bytes: int = 2**29,
    step_factor: int = 2,
    iters: int = 100,
    warmup: int = 5,
    op: str = "all_reduce",
    dtype=jnp.bfloat16,
    on_result: Optional[Callable[[CollectiveResult], None]] = None,
    per_iter: bool = False,
) -> List[CollectiveResult]:
    """Message-size sweep.  Default timing runs the whole chained loop
    on-device (nccl-tests semantics: no per-iteration dispatch in the
    measurement).  ``per_iter=True`` instead times each round
    individually — dispatch overhead included, which is WHY it is not
    the default — emitting one ``bench.iter`` span per round (histogram
    ``bench.<op>``) so results carry p50/p99, not just means."""
    if step_factor < 2:
        raise ValueError(f"step factor must be >= 2, got {step_factor}")
    if mesh is None:
        devs = jax.devices()
        mesh = Mesh(np.array(devs), ("x",))
    n = mesh.devices.size
    itemsize = jnp.dtype(dtype).itemsize
    results = []

    fn = _make_collective(op, mesh)
    size = min_bytes
    while size <= max_bytes:
        # nccl-tests accounting: `size` S is the per-rank payload — the
        # buffer each rank holds for all-reduce / reduce-scatter / sendrecv,
        # and the total gathered output for all-gather.  shard_map splits
        # the global array n ways, so the global element count is sized to
        # make each device's local block S bytes (S/n for all-gather,
        # whose chained step re-gathers to S).
        local_elems = max(1, size // itemsize)
        if op == "all_gather":
            local_elems = max(1, size // itemsize // n)
        global_shape = (n * local_elems,)
        x = jax.device_put(
            jnp.ones(global_shape, dtype),
            NamedSharding(mesh, P(mesh.axis_names[0])),
        )
        jax.block_until_ready(fn(x, max(warmup, 1)))  # compile + warmup
        payload_bytes = local_elems * itemsize
        if op == "all_gather":
            payload_bytes *= n
        p50_us = p99_us = None
        if per_iter:
            from container_engine_accelerators_tpu.obs import trace

            samples = []
            for i in range(iters):
                with trace.span("bench.iter", histogram=f"bench.{op}",
                                op=op, size_bytes=payload_bytes,
                                iteration=i) as s:
                    jax.block_until_ready(fn(x, 1))
                samples.append(s.duration_s)
            dt = sum(samples) / iters
            ordered = sorted(samples)
            p50_us = ordered[len(ordered) // 2] * 1e6
            p99_us = ordered[min(len(ordered) - 1,
                                 int(len(ordered) * 0.99))] * 1e6
        else:
            t0 = time.perf_counter()
            jax.block_until_ready(fn(x, iters))
            dt = (time.perf_counter() - t0) / iters

        alg_bw = payload_bytes / dt / 1e9
        result = CollectiveResult(
            op=op,
            size_bytes=payload_bytes,
            time_us=dt * 1e6,
            alg_bw_gbps=alg_bw,
            bus_bw_gbps=alg_bw * _bus_factor(op, n),
            p50_us=p50_us,
            p99_us=p99_us,
        )
        results.append(result)
        if on_result is not None:
            # Per-size hook (DCN accounting rides here) so a daemon
            # restart mid-sweep is exercised mid-sweep, not after it.
            on_result(result)
        size *= step_factor
    return results


def main(argv=None):
    p = argparse.ArgumentParser(description="XLA collectives bandwidth sweep")
    p.add_argument("-b", "--min-bytes", default="1M")
    p.add_argument("-e", "--max-bytes", default="512M")
    p.add_argument("-f", "--step-factor", type=int, default=2)
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument(
        "--op",
        default="all_reduce",
        choices=["all_reduce", "all_gather", "reduce_scatter", "ppermute"],
    )
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument(
        "--percentiles", action="store_true",
        help="time every round individually (one bench.iter span each) "
             "and report p50/p99 next to the mean; per-round dispatch "
             "overhead is included, so means run slightly higher than "
             "the default chained-loop timing",
    )
    p.add_argument("--line-rate-gbps", type=float, default=None,
                   help="ICI/DCN line rate; enables the >=threshold pass bar")
    p.add_argument("--pass-threshold", type=float, default=0.9)
    p.add_argument("--json", action="store_true", help="one JSON line per size")
    p.add_argument(
        "--verdict-json", default=None, metavar="FILE",
        help="write the full sweep + PASS/FAIL verdict as one JSON document "
             "(the artifact a cluster rig uploads; nccl-tests analog of the "
             "mpirun log the reference's test runner collects)",
    )
    args = p.parse_args(argv)

    from container_engine_accelerators_tpu.parallel import dcn

    dcn.initialize()
    acct = DcnBenchAccounting.from_env(f"bench-{args.op}")

    try:
        results = run_sweep(
            min_bytes=_parse_size(args.min_bytes),
            max_bytes=_parse_size(args.max_bytes),
            step_factor=args.step_factor,
            iters=args.iters,
            warmup=args.warmup,
            op=args.op,
            dtype=jnp.dtype(args.dtype),
            on_result=acct.record,
            per_iter=args.percentiles,
        )
    finally:
        acct.close()

    n = len(jax.devices())
    print(f"# {args.op} over {n} devices ({jax.devices()[0].platform})")
    tail_hdr = f" {'p50(us)':>10} {'p99(us)':>10}" if args.percentiles else ""
    print(f"# {'bytes':>12} {'time(us)':>12} {'algbw(GB/s)':>12} "
          f"{'busbw(GB/s)':>12}{tail_hdr}")
    best = 0.0
    for r in results:
        best = max(best, r.bus_bw_gbps)
        if args.json:
            print(json.dumps(dataclasses.asdict(r)))
        else:
            tail = (f" {r.p50_us:>10.1f} {r.p99_us:>10.1f}"
                    if r.p50_us is not None else "")
            print(f"  {r.size_bytes:>12} {r.time_us:>12.1f} "
                  f"{r.alg_bw_gbps:>12.2f} {r.bus_bw_gbps:>12.2f}{tail}")
    ok = True
    frac = None
    if args.line_rate_gbps:
        frac = best / args.line_rate_gbps
        ok = frac >= args.pass_threshold
        print(f"# peak busbw {best:.1f} GB/s = {frac:.1%} of line rate "
              f"{args.line_rate_gbps} GB/s -> {'PASS' if ok else 'FAIL'}")
    if args.verdict_json:
        verdict = {
            "op": args.op,
            "devices": n,
            "platform": jax.devices()[0].platform,
            "device_kind": getattr(jax.devices()[0], "device_kind", ""),
            "dtype": args.dtype,
            "iters": args.iters,
            "warmup": args.warmup,
            "results": [dataclasses.asdict(r) for r in results],
            "peak_busbw_gbps": best,
            "line_rate_gbps": args.line_rate_gbps,
            "pass_threshold": args.pass_threshold,
            "line_rate_fraction": frac,
            "pass": ok if args.line_rate_gbps else None,
        }
        with open(args.verdict_json, "w") as f:
            json.dump(verdict, f, indent=1)
            f.write("\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
