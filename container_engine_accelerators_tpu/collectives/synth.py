"""Collective schedule synthesis over the fleet's comm graph.

Lowers ``all_reduce`` / ``all_gather`` / ``reduce_scatter`` into
explicit per-leg transfer steps for three algorithm families and picks
the cheapest under the graph's cost model (TACCL's shape, PAPERS.md —
the topology sketch chooses the algorithm, not a hardcoded ring):

- **ring** — the bandwidth-optimal classic: rack-major node order,
  ``n-1`` reduce-scatter steps and/or ``n-1`` all-gather steps, each
  moving one ``S/n`` chunk per node to its ring successor;
- **tree** — the flat two-phase star: everyone sends to the root, the
  root answers (latency-optimal for small payloads; the cost model's
  endpoint serialization charges the root its fan-in honestly);
- **hierarchical** — the two-level DCN shape for ``all_reduce``:
  intra-rack ring reduce-scatter, a cross-rack exchange per shard
  owner, intra-rack ring all-gather — the only family whose
  cross-rack traffic is ``S/k`` per node instead of riding every
  step, which is why it wins the moment the cross-rack tier degrades.

A :class:`Schedule` is plain data: an ordered list of *step groups*,
each a list of :class:`TransferStep` legs that may run concurrently
(every leg's payload is read from pre-step state, so groups have
barrier semantics and no intra-group data hazards).  ``simulate``
executes a schedule over in-memory buffers — the unit-testable oracle
the runner's wire execution is verified against.

:class:`Synthesizer` owns re-synthesis: it caches the schedule keyed
by the graph signature it was planned against, and a signature change
(fault armed, link healed) triggers a fresh synthesis, counted by
``collective.resynth`` and marked in the trace — the "fault → new
schedule, heal → recover" loop the scenario gates assert on.
"""

import dataclasses
import logging
import math
from typing import Dict, List, Optional, Tuple

try:  # vectorized reduction; the pure-python loop below is the spec
    import numpy as _np
except ImportError:  # pragma: no cover - numpy ships with the stack
    _np = None

from container_engine_accelerators_tpu.collectives.topo import CommGraph
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import trace

log = logging.getLogger(__name__)

COLLECTIVES = ("all_reduce", "all_gather", "reduce_scatter")
# Preference order breaks exact cost ties deterministically.  The
# hand-written families participate in auto-selection; ``searched``
# (collectives/search.py's sketch-guided synthesis) is pin-only — it
# spends real synthesis CPU enumerating candidates, so a config must
# ask for it (``algorithm: searched``) rather than every auto pass
# paying the search.
AUTO_ALGORITHMS = ("ring", "tree", "hierarchical")
ALGORITHMS = AUTO_ALGORITHMS + ("searched",)


def bus_factor(op: str, n: int) -> float:
    """nccl-tests bus-bandwidth factor (collectives/bench.py keeps the
    same accounting for the XLA sweep — one convention, two rigs)."""
    if op == "all_reduce":
        return 2 * (n - 1) / n
    if op in ("all_gather", "reduce_scatter"):
        return (n - 1) / n
    return 1.0  # point-to-point shift


class SynthesisError(ValueError):
    """The requested (collective, algorithm, fleet shape) combination
    cannot be lowered — e.g. hierarchical over one rack."""


@dataclasses.dataclass(frozen=True)
class TransferStep:
    """One leg: move ``nbytes`` at ``offset`` of the collective buffer
    from ``src`` to ``dst``; the receiver reduces (elementwise
    combine) or places (overwrite) the region."""

    src: str
    dst: str
    offset: int
    nbytes: int
    reduce: bool
    phase: str


@dataclasses.dataclass
class Schedule:
    collective: str
    algorithm: str
    nbytes: int
    order: List[str]
    steps: List[List[TransferStep]]
    est_cost_s: float
    signature: tuple

    @property
    def transfers(self) -> int:
        return sum(len(g) for g in self.steps)

    def to_dict(self) -> dict:
        """JSON-clean summary for reports/CLI tables (the full step
        list stays in memory; reports carry the shape, not the data)."""
        return {
            "collective": self.collective,
            "algorithm": self.algorithm,
            "bytes": self.nbytes,
            "steps": len(self.steps),
            "transfers": self.transfers,
            "est_cost_ms": (round(self.est_cost_s * 1e3, 3)
                            if math.isfinite(self.est_cost_s) else None),
            "phases": sorted({t.phase for g in self.steps for t in g}),
        }


def partition(nbytes: int, parts: int) -> List[Tuple[int, int]]:
    """Even ``parts``-way split of ``[0, nbytes)`` as (offset, length);
    the remainder spreads one byte at a time over the leading chunks,
    so lengths differ by at most one (and may be zero for tiny
    payloads — zero-length legs are skipped at lowering time)."""
    base, rem = divmod(nbytes, parts)
    out = []
    off = 0
    for i in range(parts):
        ln = base + (1 if i < rem else 0)
        out.append((off, ln))
        off += ln
    return out


def _ring_phase(order: List[str], chunks: List[Tuple[int, int]],
                reduce: bool, phase: str,
                offset_base: int = 0) -> List[List[TransferStep]]:
    """The ``n-1`` steps of a ring reduce-scatter (``reduce=True``:
    step ``s`` moves chunk ``(i - s - 1) mod n`` so node ``i`` ends
    owning the fully reduced chunk ``i``) or ring all-gather
    (``reduce=False``: step ``s`` moves chunk ``(i - s) mod n``,
    starting from each node owning chunk ``i``)."""
    n = len(order)
    groups = []
    for s in range(n - 1):
        group = []
        for i in range(n):
            c = (i - s - 1) % n if reduce else (i - s) % n
            off, ln = chunks[c]
            if ln == 0:
                continue
            group.append(TransferStep(
                src=order[i], dst=order[(i + 1) % n],
                offset=offset_base + off, nbytes=ln,
                reduce=reduce, phase=phase))
        if group:
            groups.append(group)
    return groups


def _ring(order: List[str], collective: str,
          nbytes: int) -> List[List[TransferStep]]:
    chunks = partition(nbytes, len(order))
    if collective == "all_reduce":
        return (_ring_phase(order, chunks, True, "rs")
                + _ring_phase(order, chunks, False, "ag"))
    if collective == "reduce_scatter":
        return _ring_phase(order, chunks, True, "rs")
    return _ring_phase(order, chunks, False, "ag")


def _tree(order: List[str], collective: str,
          nbytes: int) -> List[List[TransferStep]]:
    root, rest = order[0], order[1:]
    chunks = partition(nbytes, len(order))
    up_reduce = collective in ("all_reduce", "reduce_scatter")
    up = [TransferStep(src=n, dst=root,
                       offset=0 if up_reduce else chunks[i + 1][0],
                       nbytes=nbytes if up_reduce else chunks[i + 1][1],
                       reduce=up_reduce,
                       phase="reduce" if up_reduce else "gather")
          for i, n in enumerate(rest)
          if (nbytes if up_reduce else chunks[i + 1][1]) > 0]
    if collective == "reduce_scatter":
        down = [TransferStep(src=root, dst=n, offset=chunks[i + 1][0],
                             nbytes=chunks[i + 1][1], reduce=False,
                             phase="scatter")
                for i, n in enumerate(rest) if chunks[i + 1][1] > 0]
    else:
        down = [TransferStep(src=root, dst=n, offset=0, nbytes=nbytes,
                             reduce=False, phase="bcast")
                for n in rest]
    return [g for g in (up, down) if g]


def _hierarchical(graph: CommGraph, collective: str,
                  nbytes: int) -> List[List[TransferStep]]:
    """Two-level lowerings.  Requires >= 2 equal-size racks (the
    counterpart pairing is positional) — callers treat
    :class:`SynthesisError` as "not a candidate".

    - **all_reduce**: intra-rack ring reduce-scatter over the
      rack-size chunking, one cross-rack star exchange per shard
      owner, intra-rack ring all-gather;
    - **all_gather**: one cross-rack counterpart exchange (each node
      ships its own global chunk to its position-mates in every other
      rack), then an intra-rack ring all-gather of the *coarse*
      chunks (position ``j``'s pieces across all racks) — cross-rack
      bytes per node are ``(R-1)·S/n`` instead of the flat ring's
      every-boundary crossings;
    - **reduce_scatter**: the mirror — intra-rack ring
      reduce-scatter over the coarse chunks, then one cross-rack
      counterpart exchange shipping each rack's partial of the
      destination's own chunk (``reduce=True``)."""
    racks = list(graph.racks().values())
    if len(racks) < 2:
        raise SynthesisError("hierarchical needs >= 2 racks")
    k = len(racks[0])
    if any(len(r) != k for r in racks):
        raise SynthesisError(
            "hierarchical needs equal-size racks, got "
            f"{[len(r) for r in racks]}")
    if collective == "all_gather":
        return _hier_all_gather(racks, nbytes)
    if collective == "reduce_scatter":
        return _hier_reduce_scatter(racks, nbytes)
    chunks = partition(nbytes, k)
    steps: List[List[TransferStep]] = []
    # Intra-rack reduce-scatter: every rack steps in lockstep, so the
    # per-s groups merge across racks into one concurrent group.
    for s in range(k - 1):
        group = []
        for members in racks:
            for i in range(k):
                c = (i - s - 1) % k
                off, ln = chunks[c]
                if ln == 0:
                    continue
                group.append(TransferStep(
                    src=members[i], dst=members[(i + 1) % k],
                    offset=off, nbytes=ln, reduce=True, phase="rs"))
        if group:
            steps.append(group)
    # Cross-rack exchange: shard i's owners (one per rack) star-reduce
    # into rack 0's owner, which answers with the full sum — 2 groups
    # total, each carrying S/k per participating node.
    up, down = [], []
    for i in range(k):
        off, ln = chunks[i]
        if ln == 0:
            continue
        anchor = racks[0][i]
        for members in racks[1:]:
            up.append(TransferStep(src=members[i], dst=anchor,
                                   offset=off, nbytes=ln, reduce=True,
                                   phase="xr"))
            down.append(TransferStep(src=anchor, dst=members[i],
                                     offset=off, nbytes=ln,
                                     reduce=False, phase="xr"))
    for g in (up, down):
        if g:
            steps.append(g)
    # Intra-rack all-gather, lockstep across racks again.
    for s in range(k - 1):
        group = []
        for members in racks:
            for i in range(k):
                c = (i - s) % k
                off, ln = chunks[c]
                if ln == 0:
                    continue
                group.append(TransferStep(
                    src=members[i], dst=members[(i + 1) % k],
                    offset=off, nbytes=ln, reduce=False, phase="ag"))
        if group:
            steps.append(group)
    return steps


def _hier_all_gather(racks: List[List[str]],
                     nbytes: int) -> List[List[TransferStep]]:
    """Two-level all_gather over the n-way global chunking (rack-major
    order, so rack ``r`` position ``j`` owns global chunk ``r·k+j``):
    one ``xr`` counterpart-exchange group, then ``k-1`` lockstep
    intra-rack ring steps gathering the coarse chunks (each coarse
    chunk is ``R`` non-contiguous pieces, one leg per piece)."""
    R, k = len(racks), len(racks[0])
    chunks = partition(nbytes, R * k)
    steps: List[List[TransferStep]] = []
    xr = []
    for r in range(R):
        for j in range(k):
            off, ln = chunks[r * k + j]
            if ln == 0:
                continue
            for r2 in range(R):
                if r2 == r:
                    continue
                xr.append(TransferStep(
                    src=racks[r][j], dst=racks[r2][j],
                    offset=off, nbytes=ln, reduce=False, phase="xr"))
    if xr:
        steps.append(xr)
    for s in range(k - 1):
        group = []
        for r in range(R):
            for i in range(k):
                c = (i - s) % k
                for r2 in range(R):
                    off, ln = chunks[r2 * k + c]
                    if ln == 0:
                        continue
                    group.append(TransferStep(
                        src=racks[r][i], dst=racks[r][(i + 1) % k],
                        offset=off, nbytes=ln, reduce=False,
                        phase="ag"))
        if group:
            steps.append(group)
    return steps


def _hier_reduce_scatter(racks: List[List[str]],
                         nbytes: int) -> List[List[TransferStep]]:
    """Two-level reduce_scatter, the all_gather mirror: ``k-1``
    lockstep intra-rack ring reduce-scatter steps over the coarse
    chunks (after which position ``j`` owns its rack's partial of
    every rack's ``j``-th global chunk), then one ``xr`` counterpart
    group where each node ships the partial of its position-mate's
    own chunk with ``reduce=True`` — every node ends owning its fully
    reduced global chunk ``r·k+j``."""
    R, k = len(racks), len(racks[0])
    chunks = partition(nbytes, R * k)
    steps: List[List[TransferStep]] = []
    for s in range(k - 1):
        group = []
        for r in range(R):
            for i in range(k):
                c = (i - s - 1) % k
                for r2 in range(R):
                    off, ln = chunks[r2 * k + c]
                    if ln == 0:
                        continue
                    group.append(TransferStep(
                        src=racks[r][i], dst=racks[r][(i + 1) % k],
                        offset=off, nbytes=ln, reduce=True,
                        phase="rs"))
        if group:
            steps.append(group)
    xr = []
    for r in range(R):
        for j in range(k):
            for r2 in range(R):
                if r2 == r:
                    continue
                off, ln = chunks[r2 * k + j]
                if ln == 0:
                    continue
                xr.append(TransferStep(
                    src=racks[r][j], dst=racks[r2][j],
                    offset=off, nbytes=ln, reduce=True, phase="xr"))
    if xr:
        steps.append(xr)
    return steps


def estimate_cost_s(graph: CommGraph,
                    steps: List[List[TransferStep]]) -> float:
    """Cost of a lowered schedule under the graph: per group, every
    endpoint serializes its own legs (a tree root's fan-in is charged
    as a sum, not hidden behind a max), the group costs its busiest
    endpoint, and groups are barriers so the total is the sum."""
    total = 0.0
    for group in steps:
        by_end: Dict[str, float] = {}
        for t in group:
            c = graph.leg_cost_s(t.src, t.dst, t.nbytes)
            by_end[t.src] = by_end.get(t.src, 0.0) + c
            by_end[t.dst] = by_end.get(t.dst, 0.0) + c
        total += max(by_end.values(), default=0.0)
    return total


def _lower(graph: CommGraph, algorithm: str, collective: str,
           nbytes: int) -> List[List[TransferStep]]:
    order = graph.order()
    if len(order) < 2:
        raise SynthesisError("a collective needs >= 2 nodes")
    if algorithm == "ring":
        return _ring(order, collective, nbytes)
    if algorithm == "tree":
        return _tree(order, collective, nbytes)
    if algorithm == "hierarchical":
        return _hierarchical(graph, collective, nbytes)
    if algorithm == "searched":
        # Lazy import: search.py scores candidates with THIS module's
        # cost model and verifies them against THIS module's oracle.
        from container_engine_accelerators_tpu.collectives import search
        return search.search_steps(graph, collective, nbytes)
    raise SynthesisError(f"unknown algorithm {algorithm!r}")


def synthesize(graph: CommGraph, collective: str, nbytes: int,
               algorithm: Optional[str] = None) -> Schedule:
    """Lower ``collective`` over ``graph``; with ``algorithm=None``
    every auto family that can lower this shape is costed and the
    cheapest wins (ties break by the AUTO_ALGORITHMS preference
    order; ``searched`` is pin-only).  A fleet mid-partition prices
    every candidate at infinity — the cheapest is still returned
    (legs will fail, the caller retries, and the heal's signature
    change re-synthesizes)."""
    if collective not in COLLECTIVES:
        raise SynthesisError(f"unknown collective {collective!r}")
    if nbytes <= 0:
        raise SynthesisError("collective payload must be > 0 bytes")
    candidates = [algorithm] if algorithm else list(AUTO_ALGORITHMS)
    best: Optional[Schedule] = None
    for rank, algo in enumerate(candidates):
        try:
            steps = _lower(graph, algo, collective, nbytes)
        except SynthesisError:
            if algorithm:
                raise
            continue
        cost = estimate_cost_s(graph, steps)
        sched = Schedule(collective=collective, algorithm=algo,
                         nbytes=nbytes, order=graph.order(),
                         steps=steps, est_cost_s=cost,
                         signature=graph.signature())
        if best is None or (cost, rank) < (best.est_cost_s,
                                           candidates.index(
                                               best.algorithm)):
            best = sched
    if best is None:
        raise SynthesisError(
            f"no algorithm lowers {collective} over this fleet")
    return best


class Synthesizer:
    """Schedule cache + re-synthesis trigger for one collective shape.

    ``schedule_for(graph)`` returns the cached schedule while the
    graph signature it was planned against holds; a signature change
    (fault or heal) synthesizes fresh, bumps ``collective.resynth``
    and drops a ``collective.resynth`` trace marker carrying the
    old/new algorithm — the evidence the scenario gate reads."""

    def __init__(self, collective: str, nbytes: int,
                 algorithm: Optional[str] = None):
        self.collective = collective
        self.nbytes = int(nbytes)
        self.algorithm = algorithm
        self.resynth_count = 0
        self._schedule: Optional[Schedule] = None

    def current(self) -> Optional[Schedule]:
        """The schedule the last planning pass produced (None before
        the first ``schedule_for``)."""
        return self._schedule

    def schedule_for(self, graph: CommGraph) -> Schedule:
        sig = graph.signature()
        if self._schedule is not None \
                and sig == self._schedule.signature:
            return self._schedule
        prev = self._schedule
        self._schedule = synthesize(graph, self.collective,
                                    self.nbytes, self.algorithm)
        if prev is not None:
            self.resynth_count += 1
            counters.inc("collective.resynth")
            trace.event("collective.resynth",
                        collective=self.collective,
                        prev_algorithm=prev.algorithm,
                        algorithm=self._schedule.algorithm,
                        degraded_edges=len(sig))
            log.warning(
                "collective schedule re-synthesized: %s -> %s "
                "(%d degraded/partitioned edge(s))",
                prev.algorithm, self._schedule.algorithm, len(sig))
        return self._schedule


# -- in-memory execution oracle ----------------------------------------------


def combine(dst: bytearray, offset: int, payload: bytes) -> None:
    """Elementwise byte-add mod 256 — the rig's reduction operator:
    cheap, commutative, associative, and a dropped or duplicated leg
    changes the result (the verification actually verifies).  uint8
    addition wraps mod 256 natively, so the vectorized path is
    bit-identical to the loop — it exists because oracle verification
    of searched schedules runs at real payload sizes, and the routed
    plane reduces inside daemon landing threads."""
    n = len(payload)
    if _np is not None and n >= 64:
        view = _np.frombuffer(dst, dtype=_np.uint8, count=n,
                              offset=offset)
        view += _np.frombuffer(payload, dtype=_np.uint8, count=n)
        return
    for i, b in enumerate(payload):
        j = offset + i
        dst[j] = (dst[j] + b) & 0xFF


def make_inputs(collective: str, order: List[str], nbytes: int,
                seed: int = 0) -> Dict[str, bytes]:
    """Deterministic per-node input buffers.  all_reduce and
    reduce_scatter start from full distinct buffers; all_gather starts
    from each node's own shard at its chunk offset (zeros elsewhere —
    the gather must move the shard, not rely on it being there)."""
    inputs = {}
    chunks = partition(nbytes, len(order))
    for i, name in enumerate(order):
        pattern = bytes(((seed * 131 + i * 31 + j * 7) % 251)
                        for j in range(nbytes))
        if collective == "all_gather":
            buf = bytearray(nbytes)
            off, ln = chunks[i]
            buf[off:off + ln] = pattern[off:off + ln]
            inputs[name] = bytes(buf)
        else:
            inputs[name] = pattern
    return inputs


def expected_outputs(collective: str, order: List[str],
                     inputs: Dict[str, bytes],
                     nbytes: int) -> Dict[str, Tuple[int, int, bytes]]:
    """Per node: the (offset, length, bytes) region that must match
    after the collective — full reduced buffer for all_reduce, the
    concatenation for all_gather, each node's own reduced chunk for
    reduce_scatter (the rest of its buffer is scratch by contract)."""
    chunks = partition(nbytes, len(order))
    if collective == "all_gather":
        full = bytearray(nbytes)
        for i, name in enumerate(order):
            off, ln = chunks[i]
            full[off:off + ln] = inputs[name][off:off + ln]
        return {n: (0, nbytes, bytes(full)) for n in order}
    total = bytearray(nbytes)
    for name in order:
        combine(total, 0, inputs[name])
    if collective == "all_reduce":
        return {n: (0, nbytes, bytes(total)) for n in order}
    return {
        name: (chunks[i][0], chunks[i][1],
               bytes(total[chunks[i][0]:chunks[i][0] + chunks[i][1]]))
        for i, name in enumerate(order)
    }


def simulate(schedule: Schedule,
             inputs: Dict[str, bytes]) -> Dict[str, bytearray]:
    """Apply a schedule to in-memory buffers with the runner's exact
    barrier semantics: each group's payloads snapshot pre-step state,
    then every leg lands.  The pure-python twin of the wire execution
    — what schedule-correctness tests (and debugging) run against."""
    bufs = {n: bytearray(b) for n, b in inputs.items()}
    for group in schedule.steps:
        staged = [(t, bytes(bufs[t.src][t.offset:t.offset + t.nbytes]))
                  for t in group]
        for t, payload in staged:
            if t.reduce:
                combine(bufs[t.dst], t.offset, payload)
            else:
                bufs[t.dst][t.offset:t.offset + t.nbytes] = payload
    return bufs
