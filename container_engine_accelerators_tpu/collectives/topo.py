"""Annotated comm graph: what the collective synthesizer plans against.

TACCL's core observation (PAPERS.md) is that the right collective
algorithm is a function of the topology *sketch* — which links exist,
how fast each tier is, and what shape the hierarchy has.  The fleet
rig already holds every input: :class:`FleetTopology` classifies each
pair by the production scheduler distance (``ici`` / ``intra-rack`` /
``cross-rack``), the :class:`LinkTable` knows which links are
partitioned, latency-injected, or shedding frames, and the windowed
``goodput.link.*`` series carry live measured rates.  This module
folds the three into one :class:`CommGraph` snapshot:

- every directed pair gets a :class:`CommEdge` with its tier, fault
  state, and (when the rig has moved bytes) measured goodput;
- :meth:`CommGraph.leg_cost_s` is the alpha-beta cost model the
  synthesizer's algorithm choice minimizes — injected latency lands in
  the alpha term, loss injection discounts the beta term, a partition
  costs infinity;
- :meth:`CommGraph.signature` is the re-synthesis trigger: it hashes
  only the *planning-relevant* state (up/degraded per edge), so a
  fault or a heal changes it and steady-state noise does not;
- :meth:`CommGraph.scheduler_link_penalty` renders the same evidence
  for the placement side: a distance-penalty callable
  ``calculate_pods_assignment`` adds on top of the production
  topology distance, so the packer steers pods away from nodes behind
  partitioned or lossy links (and degrades to the best available
  placement when no healthy one exists — a penalty, never a veto).

The graph is a snapshot by design: build one per planning pass.  It
never mutates live link state and imports nothing heavier than the
fleet topology model (no jax — the engine must load on a coordinator
that never touches an accelerator).
"""

import dataclasses
import math
import time
from typing import Callable, Dict, List, Optional, Tuple

from container_engine_accelerators_tpu.fleet.topology import (
    TIER_CROSS_RACK,
    TIER_ICI,
    TIER_INTRA_RACK,
    FleetTopology,
)
from container_engine_accelerators_tpu.scheduler import topology as sched_topo

# Tier base parameters for the cost model — RELATIVE envelopes, not
# hardware claims: ICI is effectively free next to any DCN tier,
# intra-rack DCN is a few times faster than the cross-rack spine.
# Measured goodput overrides the beta term once the rig has evidence.
TIER_BW_BPS = {
    TIER_ICI: 100e9,
    TIER_INTRA_RACK: 25e9,
    TIER_CROSS_RACK: 5e9,
}
TIER_ALPHA_S = {
    TIER_ICI: 1e-6,
    TIER_INTRA_RACK: 20e-6,
    TIER_CROSS_RACK: 100e-6,
}

# A link with a pending loss budget re-sends a share of everything it
# carries; discount its effective bandwidth rather than guessing a
# retransmit schedule.
DROP_BW_DISCOUNT = 4.0
# A link the goodput evidence flags as slow (see below) gets the same
# treatment: still usable, priced to be avoided.
SLOW_BW_DISCOUNT = 4.0

# Goodput evidence is RELATIVE, never absolute: a windowed
# ``goodput.link.*`` rate measures what a link carried, not what it
# could carry, so an idle or lightly-used link must never read as
# slow.  An edge is flagged ``slow`` only when it was demonstrably
# active (rate above the trust floor) AND delivered under
# SLOW_RATE_RATIO of the best rate any same-tier edge achieved in the
# same window — the shape a lossy link makes next to its healthy
# peers under symmetric collective traffic.
MIN_TRUSTED_RATE_BPS = 1024.0
SLOW_RATE_RATIO = 0.25

# Placement penalties the scheduler-side annotation source hands out,
# sized against scheduler.topology's distance envelope: a normal
# cross-rack hop costs ~DCN_MIN + DCN_FAR (~1e6), so DEGRADED must
# dominate any healthy alternative and PARTITIONED must dominate
# DEGRADED — while both stay finite, so an all-bad fleet still yields
# the least-bad assignment instead of none.
DEGRADED_LINK_PENALTY = 10 * sched_topo.DCN_FAR
PARTITIONED_LINK_PENALTY = 1000 * sched_topo.DCN_FAR


@dataclasses.dataclass
class CommEdge:
    """One directed link, annotated with everything planning needs."""

    src: str
    dst: str
    tier: str
    up: bool = True
    latency_s: float = 0.0
    drop_pending: int = 0
    #: windowed goodput evidence, observability + the `slow` verdict's
    #: input — never a capacity claim (utilization is not capacity)
    goodput_bps: float = 0.0
    #: flagged by the relative same-tier rate comparison at build time
    slow: bool = False

    @property
    def degraded(self) -> bool:
        """Injected evidence: lossy or latency-injected but still
        passing frames.  Feeds the planning signature (deterministic —
        faults and heals move it, measurement noise cannot)."""
        return self.up and (self.latency_s > 0.0 or self.drop_pending > 0)

    @property
    def suspect(self) -> bool:
        """Any avoid-if-you-can verdict, measured slowness included —
        what the scheduler's placement penalty and the node-health
        rollup read (a lossy real link shows up HERE even when no one
        told the coordinator's link table about it)."""
        return self.up and (self.degraded or self.slow)

    def cost_s(self, nbytes: int) -> float:
        """Alpha-beta transfer-time estimate for ``nbytes`` over this
        edge.  Partitioned edges cost infinity (no schedule through a
        null route can complete); injected latency is honest alpha;
        loss injection and measured slowness discount the tier's
        bandwidth envelope."""
        if not self.up:
            return math.inf
        alpha = TIER_ALPHA_S[self.tier] + self.latency_s
        bw = TIER_BW_BPS[self.tier]
        if self.drop_pending > 0:
            bw /= DROP_BW_DISCOUNT
        if self.slow:
            bw /= SLOW_BW_DISCOUNT
        return alpha + nbytes / bw


class CommGraph:
    """A planning snapshot of the fleet's communication structure."""

    def __init__(self, topology: FleetTopology,
                 edges: Dict[Tuple[str, str], CommEdge]):
        self.topology = topology
        self._edges = edges

    @classmethod
    def build(cls, topology: FleetTopology, links=None,
              rates: Optional[Callable[[str, str], float]] = None,
              ) -> "CommGraph":
        """Snapshot the fleet: tiers from the production distance
        function, fault state from the link table (when given), and
        live per-link goodput from the windowed series (or an injected
        ``rates(src, dst)`` source for tests).  Absent evidence reads
        as healthy at tier defaults — the same "no entry means no
        fault" contract the link table itself keeps."""
        if rates is None:
            from container_engine_accelerators_tpu.obs import timeseries

            def rates(src: str, dst: str) -> float:
                return timeseries.rate(f"goodput.link.{src}->{dst}")

        state = links.snapshot_state() if links is not None else {}
        names = topology.names()
        edges: Dict[Tuple[str, str], CommEdge] = {}
        for a in names:
            for b in names:
                if a == b:
                    continue
                st = state.get((a, b), {})
                edges[(a, b)] = CommEdge(
                    src=a, dst=b, tier=topology.tier(a, b),
                    up=bool(st.get("up", True)),
                    latency_s=float(st.get("latency_s", 0.0)),
                    drop_pending=int(st.get("drop_next", 0)),
                    goodput_bps=float(rates(a, b) or 0.0),
                )
        # The relative slowness pass: within each tier, an ACTIVE edge
        # delivering well under the tier's best observed rate is
        # flagged `slow` — goodput as evidence of trouble, never as a
        # capacity estimate (an idle edge's decayed window is not
        # evidence of anything).
        peak_by_tier: Dict[str, float] = {}
        for e in edges.values():
            if e.goodput_bps >= MIN_TRUSTED_RATE_BPS:
                peak_by_tier[e.tier] = max(
                    peak_by_tier.get(e.tier, 0.0), e.goodput_bps)
        for e in edges.values():
            peak = peak_by_tier.get(e.tier, 0.0)
            if (e.up and peak > 0.0
                    and e.goodput_bps >= MIN_TRUSTED_RATE_BPS
                    and e.goodput_bps < SLOW_RATE_RATIO * peak):
                e.slow = True
        return cls(topology, edges)

    # -- queries -------------------------------------------------------------

    def nodes(self) -> List[str]:
        return self.topology.names()

    def edge(self, src: str, dst: str) -> CommEdge:
        return self._edges[(src, dst)]

    def up(self, src: str, dst: str) -> bool:
        return self._edges[(src, dst)].up

    def leg_cost_s(self, src: str, dst: str, nbytes: int) -> float:
        return self._edges[(src, dst)].cost_s(nbytes)

    def racks(self) -> Dict[str, List[str]]:
        """Rack -> member node names, both in deterministic order —
        the hierarchy the two-level schedule is synthesized over."""
        out: Dict[str, List[str]] = {}
        for name in sorted(self.topology.specs):
            out.setdefault(self.topology.specs[name].rack, []).append(name)
        return dict(sorted(out.items()))

    def order(self) -> List[str]:
        """Ring order: rack-major, so a ring crosses each rack
        boundary the minimum number of times the cycle allows."""
        return [n for members in self.racks().values() for n in members]

    def signature(self) -> tuple:
        """Hash of the planning-relevant state.  A schedule synthesized
        against one signature stays valid until the signature changes —
        a partition, a heal, injected latency appearing or clearing, a
        loss budget arming or spending out.  Measured goodput is
        deliberately NOT in the signature (it wobbles every round);
        it still shapes costs whenever a re-synthesis does happen."""
        return tuple(
            (src, dst, e.up, round(e.latency_s, 4), e.drop_pending > 0)
            for (src, dst), e in sorted(self._edges.items())
            if not e.up or e.degraded
        )

    # -- the placement-side annotation source --------------------------------

    def node_health(self) -> Dict[str, dict]:
        """Per-node link-health rollup: how many of the node's directed
        links are partitioned or degraded — the human-readable half of
        the annotation source (reports, CLI tables)."""
        out: Dict[str, dict] = {
            n: {"partitioned_links": 0, "degraded_links": 0}
            for n in self.nodes()
        }
        for (src, dst), e in self._edges.items():
            for end in (src, dst):
                if not e.up:
                    out[end]["partitioned_links"] += 1
                elif e.suspect:
                    out[end]["degraded_links"] += 1
        return out

    def scheduler_link_penalty(self) -> Callable[[dict, dict], float]:
        """A distance-penalty callable for the assignment search
        (``scheduler.daemon.calculate_pods_assignment(link_penalty=)``).

        Maps candidate nodes back to fleet nodes by the HOST label the
        simulator stamps (fleet/topology.NodeSpec.labels) and charges
        :data:`PARTITIONED_LINK_PENALTY` when either direction between
        the pair is down, :data:`DEGRADED_LINK_PENALTY` when either is
        lossy/latency-injected, 0 otherwise.  Hosts the fleet does not
        know cost nothing — the annotation source only ever *adds*
        evidence, it never vetoes a placement outright, so a job that
        fits nowhere healthy still lands on the least-bad nodes.

        This closure reads THIS graph — a frozen snapshot.  A
        long-lived SchedulerDaemon should wire
        :class:`LinkHealthPenalty` instead, which re-snapshots the
        link table on a bounded cadence so faults armed between
        scheduling passes steer the next placement."""
        known = set(self.topology.names())

        def penalty(node_a: dict, node_b: dict) -> float:
            a = (node_a.get("node_labels") or {}).get(
                sched_topo.HOST_LABEL)
            b = (node_b.get("node_labels") or {}).get(
                sched_topo.HOST_LABEL)
            if a not in known or b not in known or a == b:
                return 0.0
            fwd, rev = self._edges[(a, b)], self._edges[(b, a)]
            if not (fwd.up and rev.up):
                return PARTITIONED_LINK_PENALTY
            if fwd.suspect or rev.suspect:
                return DEGRADED_LINK_PENALTY
            return 0.0

        return penalty


class LinkHealthPenalty:
    """The LIVE link-health annotation source for a long-lived
    scheduler: a penalty callable (drop-in for
    ``calculate_pods_assignment(link_penalty=)`` /
    ``SchedulerDaemon(link_penalty=)``) that re-snapshots the fleet's
    link table on a bounded cadence instead of freezing one CommGraph
    forever.

    The assignment search evaluates the penalty in its inner loop —
    thousands of calls per pass — so rebuilding per call would be
    absurd and rebuilding never (a bare
    ``CommGraph.build(...).scheduler_link_penalty()`` closure) means a
    fault armed after construction never steers anything.  The middle
    road: each call checks a monotonic clock and rebuilds the snapshot
    at most once per ``refresh_s`` (default 1 s, the scheduler
    daemon's own pass interval), so within a pass the penalty is
    coherent and between passes it is fresh.  ``refresh_s=0`` rebuilds
    on every call — the deterministic setting tests use.
    """

    def __init__(self, topology: FleetTopology, links,
                 rates: Optional[Callable[[str, str], float]] = None,
                 refresh_s: float = 1.0):
        self.topology = topology
        self.links = links
        self.rates = rates
        self.refresh_s = float(refresh_s)
        self._built_at = -math.inf
        self._penalty: Optional[Callable[[dict, dict], float]] = None

    def refresh(self) -> None:
        """Force a rebuild on the next call (e.g. right after arming a
        fault, when waiting out the cadence would blur a test)."""
        self._built_at = -math.inf

    def __call__(self, node_a: dict, node_b: dict) -> float:
        now = time.monotonic()
        if self._penalty is None \
                or now - self._built_at >= self.refresh_s:
            self._penalty = CommGraph.build(
                self.topology, links=self.links,
                rates=self.rates).scheduler_link_penalty()
            self._built_at = now
        return self._penalty(node_a, node_b)
