"""Execute synthesized collective schedules over the fleet rig.

The runner is the wire half of the engine: it takes the schedule
synth.py planned (ring / tree / hierarchical, chosen from the comm
graph) and drives every :class:`TransferStep` through the SAME data
plane the rest of the stack uses — pooled production
``ResilientDcnXferClient``s per node, serial staging legs or the
chunked/striped pipelined plane, every cross-node byte through the
link table (in-process fleets) or each worker daemon's real TCP stack
(process mode).  Link chaos therefore hits a collective exactly where
it would hit a training job's exchange.

Semantics: step groups are barriers.  Every leg's payload snapshots
pre-group state (so concurrent legs in one group can never observe
each other's landings — the same contract synth.simulate verifies),
legs run concurrently on a bounded pool, and the group's reductions
apply on the coordinator after every leg returns.  A leg retries
under a bounded budget; a leg that spends it fails the whole run for
this round (the controller's round loop is the outer retry, and a
graph-signature change from the fault re-synthesizes the schedule —
``collective.resynth``).

Accounting follows collectives/bench.py's nccl-tests conventions:
``algbw = S / t`` with S the per-rank payload and t the whole
schedule's wall time, ``busbw = algbw * bus_factor(op, n)`` — so a
number measured here compares against the XLA sweep's.  The run
emits ``collective.*`` counters/gauges and a span tree
(``collective.run`` > ``collective.phase`` > ``collective.leg`` with
src/dst/phase attrs) so the critical-path report names the hop that
dominated, not just the slower total.

CLI (the `make collectives` acceptance leg)::

    python -m container_engine_accelerators_tpu.collectives.runner \
        --compare --nodes 4 --racks 2 --xrack-latency-ms 25 \
        --bytes 262144 --margin 1.3

boots an in-process 2-rack fleet, degrades the cross-rack tier, runs
ring and hierarchical pinned, and exits non-zero unless hierarchical
beats the flat ring's bus bandwidth by the margin.
"""

import argparse
import contextlib
import itertools
import json
import logging
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from container_engine_accelerators_tpu.collectives import synth
from container_engine_accelerators_tpu.collectives.topo import CommGraph
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import timeseries, trace
from container_engine_accelerators_tpu.parallel import dcn, dcn_pipeline
from container_engine_accelerators_tpu.parallel.dcn_client import (
    DcnXferError,
    ResilientDcnXferClient,
)
from container_engine_accelerators_tpu.utils.retry import RetryPolicy

log = logging.getLogger(__name__)


class CollectiveConfig:
    """Engine knobs.  Scenario specs pass them as the ``collective:``
    mapping (:meth:`from_scenario` — unknown keys are dropped with a
    log line, the TPU_FAULT_SPEC rule)."""

    #: which collective and how many payload bytes per rank (S)
    op: str = "all_reduce"
    bytes: int = 262144
    #: pin one algorithm, or None = the cost model chooses per graph
    algorithm: Optional[str] = None
    #: verify every node's result region against the in-memory oracle
    verify: bool = True
    #: per-leg retry budget (the controller round loop retries above)
    leg_attempts: int = 3
    leg_backoff_ms: float = 30.0
    leg_deadline_s: float = 8.0
    #: land/read timeout for one DCN phase inside a leg
    land_timeout_s: float = 2.0
    #: concurrent legs per step group (and the client-pool high water)
    max_workers: int = 8
    #: per-node client retry deadline
    client_deadline_s: float = 4.0
    #: daemon-routed execution: every leg is ONE daemon->daemon
    #: forward hop; the coordinator posts programs and collects
    #: verdicts, payload bytes never cross its clients
    routed: bool = False

    _FIELDS = ("op", "bytes", "algorithm", "verify", "leg_attempts",
               "leg_backoff_ms", "leg_deadline_s", "land_timeout_s",
               "max_workers", "client_deadline_s", "routed")

    def __init__(self, **kw):
        for field in self._FIELDS:
            setattr(self, field, kw.pop(field, getattr(type(self),
                                                       field)))
        if kw:
            raise TypeError(f"unknown CollectiveConfig fields: "
                            f"{sorted(kw)}")

    @classmethod
    def from_scenario(cls, raw: Optional[dict]) -> "CollectiveConfig":
        if raw is None:
            return cls()
        known = {}
        for key, value in dict(raw).items():
            if key in cls._FIELDS:
                known[key] = value
            else:
                log.error("ignoring unknown collective knob %r", key)
        return cls(**known)


class CollectiveEngine:
    """Synthesize-and-execute loop over one fleet's nodes.

    ``nodes`` is the controller's node map (EmulatedNode or ProcNode —
    both expose ``root``/``client``/``daemon.data_port``/``down``);
    ``links`` is the coordinator's LinkTable (fault evidence for the
    graph; process-mode fleets mirror their worker-shim faults into
    it).  ``pipe_cfg`` non-None routes legs over the pipelined plane.
    """

    def __init__(self, nodes: dict, topology, links=None,
                 cfg: Optional[CollectiveConfig] = None,
                 pipe_cfg=None):
        self.nodes = nodes
        self.topology = topology
        self.links = links
        self.cfg = cfg or CollectiveConfig()
        self.pipe_cfg = pipe_cfg
        self.synth = synth.Synthesizer(self.cfg.op, self.cfg.bytes,
                                       self.cfg.algorithm)
        self._retry = RetryPolicy(
            max_attempts=int(self.cfg.leg_attempts),
            initial_backoff_s=float(self.cfg.leg_backoff_ms) / 1e3,
            max_backoff_s=0.2,
            deadline_s=float(self.cfg.leg_deadline_s),
        )
        workers = int(self.cfg.max_workers)
        if self.cfg.routed:
            # Routed legs are verdict round-trips, not payload moves;
            # a fixed pool would put the coordinator back on the
            # critical path the forwarding plane exists to leave
            # (group wall time = latency x ceil(legs/workers) instead
            # of one latency).  Scale the pool with the fleet.
            workers = max(workers, 4 * max(len(nodes), 1))
        self._pool = ThreadPoolExecutor(
            max_workers=workers,
            thread_name_prefix="collective")
        self._client_pool: Dict[str, List] = {}
        self._clients_lock = threading.Lock()
        self._fid = itertools.count()
        # Routed-mode accounting is mutated from pool threads.
        self._acct_lock = threading.Lock()

    # -- pooled clients (the serving frontend's discipline) ------------------

    def _checkout(self, node):
        """Take a client for ``node`` out of the pool (or dial a new
        one).  The caller owns it until :meth:`_checkin` — while held
        it can never be handed to another leg, and nothing closes it
        behind the caller's back.  The routed round leans on this: a
        daemon-side flow lives exactly as long as the CONNECTION that
        registered it, so the round checks out one owner client per
        node and holds it across every leg failure."""
        with self._clients_lock:
            pool = self._client_pool.setdefault(node.name, [])
            if pool:
                return pool.pop()
        return ResilientDcnXferClient(
            os.path.join(node.root, "tpu-dcn"),
            retry=RetryPolicy(
                max_attempts=4, initial_backoff_s=0.02,
                max_backoff_s=0.2,
                deadline_s=float(self.cfg.client_deadline_s)),
        )

    def _checkin(self, node, c, clean=True) -> None:
        if clean:
            with self._clients_lock:
                self._client_pool.setdefault(node.name, []).append(c)
            return
        try:
            c.close()
        except OSError:
            pass

    @contextlib.contextmanager
    def _client(self, node):
        c = self._checkout(node)
        clean = False
        try:
            yield c
            clean = True
        finally:
            self._checkin(node, c, clean=clean)

    def close(self) -> None:
        self._pool.shutdown(wait=True)
        with self._clients_lock:
            clients = [c for pool in self._client_pool.values()
                       for c in pool]
            self._client_pool.clear()
        for c in clients:
            try:
                c.close()
            except OSError:
                pass

    # -- graph + schedule ----------------------------------------------------

    def graph(self) -> CommGraph:
        return CommGraph.build(self.topology, links=self.links)

    # -- one leg -------------------------------------------------------------

    def _leg(self, rnd: int, gi: int, t: synth.TransferStep,
             payload: bytes, ctx: Optional[dict]) -> bytes:
        with contextlib.ExitStack() as stack:
            if ctx:
                # Legs run on pool threads; join the round's trace so
                # the critical-path report sees one tree per run.
                stack.enter_context(trace.attach(ctx["trace"],
                                                 ctx["span"]))
            with trace.span("collective.leg",
                            histogram="collective.leg",
                            src=t.src, dst=t.dst, phase=t.phase,
                            bytes=t.nbytes, reduce=t.reduce) as span:
                src, dst = self.nodes[t.src], self.nodes[t.dst]
                if getattr(src, "down", False) \
                        or getattr(dst, "down", False):
                    counters.inc("collective.failures")
                    raise DcnXferError(
                        f"leg {t.src}->{t.dst}: node down")
                flow = (f"coll.r{rnd}.g{gi}.{t.src}.{t.dst}."
                        f"{next(self._fid)}")
                with self._client(src) as sc, self._client(dst) as dc:
                    # Registration sits INSIDE the try: if the second
                    # register raises (its worker just died), the
                    # finally still releases whatever the first one
                    # registered — faulted rounds must not accumulate
                    # leaked assembly buffers on surviving daemons.
                    try:
                        dc.register_flow(flow, peer=t.src,
                                         bytes=t.nbytes)
                        sc.register_flow(flow, peer=t.dst,
                                         bytes=t.nbytes)
                        if self.pipe_cfg is None:
                            # Serial leg: whole-payload staging up
                            # front, ONCE (the controller's _leg
                            # discipline) — retries below re-send
                            # only, and a daemon restart that lost
                            # the staging is healed by the resilient
                            # client's transparent restage.  The
                            # pipelined leg stages chunk-by-chunk
                            # inside each attempt instead.
                            sc.put(flow, payload)
                            dcn.wait_flow_rx(
                                sc, flow, t.nbytes,
                                timeout_s=float(
                                    self.cfg.land_timeout_s))
                        last: Optional[BaseException] = None
                        attempts = 0
                        for _attempt in self._retry.attempts():
                            attempts += 1
                            try:
                                got = self._transfer(sc, dc, dst, flow,
                                                     payload, t)
                                if got != payload:
                                    raise DcnXferError(
                                        f"payload mismatch on {flow}")
                                counters.inc("collective.transfers")
                                span.annotate(attempts=attempts)
                                return got
                            except (DcnXferError, OSError,
                                    TimeoutError) as e:
                                last = e
                                counters.inc("collective.leg.retried")
                        span.annotate(attempts=attempts)
                        raise DcnXferError(
                            f"leg {t.src}->{t.dst} spent its retry "
                            f"budget: {last}")
                    except (DcnXferError, OSError, TimeoutError):
                        # One failure count per failed leg, whatever
                        # phase broke — registration, staging, or a
                        # spent retry budget.
                        counters.inc("collective.failures")
                        raise
                    finally:
                        for c in (sc, dc):
                            try:
                                c.release_flow(flow)
                            except (DcnXferError, OSError):
                                pass

    def _transfer(self, sc, dc, dst_node, flow: str, payload: bytes,
                  t: synth.TransferStep) -> bytes:
        """One attempt of a leg's data movement.  The serial path
        assumes ``_leg`` staged the payload once up front: an attempt
        re-sends only, and a daemon restart that lost the staging is
        healed by the resilient client (``dcn.send.restaged``)."""
        nbytes = len(payload)
        land_s = float(self.cfg.land_timeout_s)
        port = dst_node.daemon.data_port
        if self.pipe_cfg is not None:
            dcn_pipeline.send_pipelined(sc, flow, payload, "127.0.0.1",
                                        port, self.pipe_cfg,
                                        timeout_s=land_s)
            return dcn_pipeline.read_pipelined(dc, flow, nbytes,
                                               self.pipe_cfg,
                                               timeout_s=land_s)
        sc.send(flow, "127.0.0.1", port, nbytes)
        dcn.wait_flow_rx(dc, flow, nbytes, timeout_s=land_s)
        return dc.read(flow, nbytes)

    # -- one collective ------------------------------------------------------

    def run_round(self, rnd: int) -> dict:
        """Synthesize (or reuse) the schedule for the current graph and
        run it once.  Returns the round-log entry: algorithm, timing,
        nccl-convention bandwidths, failure and re-synthesis counts —
        ``ok`` keeps the controller's convergence contract."""
        cfg = self.cfg
        graph = self.graph()
        before = self.synth.resynth_count
        schedule = self.synth.schedule_for(graph)
        resynth = self.synth.resynth_count - before
        order = schedule.order
        n = len(order)
        inputs = synth.make_inputs(cfg.op, order, cfg.bytes, seed=rnd)
        bufs = {name: bytearray(b) for name, b in inputs.items()}
        counters.inc("collective.runs")
        entry = {
            "workload": "collective",
            "collective": cfg.op,
            "algorithm": schedule.algorithm,
            "bytes": cfg.bytes,
            "steps": len(schedule.steps),
            "transfers": schedule.transfers,
            "resynth": resynth,
            "est_cost_ms": schedule.to_dict()["est_cost_ms"],
        }
        per_node_ok: Dict[str, int] = {name: 0 for name in order}
        per_node_failed: Dict[str, int] = {name: 0 for name in order}
        if cfg.routed:
            if self._hazard_free(schedule):
                return self._routed_round(rnd, schedule, inputs, entry,
                                          per_node_ok, per_node_failed)
            # Safety net, not a normal path: every family and searched
            # lowering is hazard-free by construction, but a hazarded
            # schedule must run with pre-group snapshots — which only
            # the coordinator path provides.
            counters.inc("collective.routed.fallback")
            log.warning("schedule %s is not hazard-free; routed mode "
                        "falling back to coordinator execution",
                        schedule.algorithm)
        error: Optional[str] = None
        t0 = time.monotonic()
        with trace.span("collective.run", histogram="collective.run",
                        collective=cfg.op,
                        algorithm=schedule.algorithm, bytes=cfg.bytes,
                        nodes=n, round=rnd) as span:
            gi = 0
            for phase, groups in itertools.groupby(
                    schedule.steps,
                    key=lambda g: g[0].phase if g else ""):
                with trace.span("collective.phase", phase=phase):
                    for group in groups:
                        errs = self._run_group(rnd, gi, group, bufs,
                                               per_node_ok,
                                               per_node_failed)
                        gi += 1
                        if errs:
                            error = str(errs[0][1])
                            break
                if error:
                    # Later groups consume this one's reductions; a
                    # broken barrier makes them meaningless.  The
                    # round fails, the controller loops, and the
                    # fault's signature change re-plans.
                    break
            span.annotate(ok=error is None, error=error)
        elapsed = max(time.monotonic() - t0, 1e-9)
        ok = error is None
        if ok and cfg.verify:
            expected = synth.expected_outputs(cfg.op, order, inputs,
                                              cfg.bytes)
            for name, (off, ln, want) in expected.items():
                if bytes(bufs[name][off:off + ln]) != want:
                    counters.inc("collective.verify.failed")
                    ok = False
                    error = f"verification failed on {name}"
                    break
        algbw = cfg.bytes / elapsed
        busbw = algbw * synth.bus_factor(cfg.op, n)
        if ok:
            # Gauges carry the LAST completed collective — a failed
            # round keeps the previous evidence instead of publishing
            # a bandwidth no data actually achieved.
            timeseries.gauge("collective.busbw_bps", busbw)
            timeseries.gauge("collective.algbw_bps", algbw)
        entry.update(
            ok=ok,
            error=error,
            time_ms=round(elapsed * 1e3, 3),
            algbw_bps=round(algbw, 1) if ok else 0.0,
            busbw_bps=round(busbw, 1) if ok else 0.0,
            per_node_ok=per_node_ok,
            per_node_failed=per_node_failed,
        )
        return entry

    # -- routed execution (daemon-routed forwarding plane) -------------------

    @staticmethod
    def _hazard_free(schedule) -> bool:
        """True when every barrier group can run WITHOUT pre-group
        snapshots: no leg reads a region another leg in the same group
        writes on the same node, and same-region concurrent writes
        only overlap when both reduce (byte-add commutes, and the
        destination daemon serializes combines under its flow lock).
        Ring steps shift read and write chunks apart, tree phases
        split sources from destinations, and the searched emitter
        inherits the family structure — so real schedules pass; the
        check is the routed mode's safety net, not a planner."""
        for group in schedule.steps:
            for a in group:
                for b in group:
                    if a is b:
                        continue
                    if not (a.offset < b.offset + b.nbytes
                            and b.offset < a.offset + a.nbytes):
                        continue
                    if b.dst == a.src:
                        return False       # a reads what b writes
                    if b.dst == a.dst and not (a.reduce and b.reduce):
                        return False       # racing plain writes
        return True

    def _routed_round(self, rnd: int, schedule, inputs: dict,
                      entry: dict, per_node_ok: Dict[str, int],
                      per_node_failed: Dict[str, int]) -> dict:
        """Daemon-routed execution: ONE shared flow per round on every
        daemon, inputs staged once up front (setup, unmeasured), then
        each schedule leg becomes a single ``forward`` op — the source
        daemon ships its staged region straight to the destination
        daemon over the persistent peer stream, and the coordinator
        only posts programs and collects verdicts.

        Correctness contract: the coordinator assigns every leg's
        frame seq (a destination's dedup window is shared by ALL
        source daemons, so only the schedule's author can hand out
        non-colliding numbers), a replayed leg reuses the seq it
        burned (landed-or-dup is exactly-once either way), and a group
        barrier is each touched destination's CUMULATIVE flow rx —
        baseline put plus every forwarded byte through this group.

        Accounting contract: forwarded legs land in
        ``dcn.lane.forward.*`` / ``xferd.forward.*`` on the daemons
        and move ZERO payload bytes through coordinator clients —
        ``routed.coordinator_payload_bytes`` stays 0 unless a
        forward-less daemon downgrades a leg (read + put_range through
        the coordinator, counted, same seq)."""
        cfg = self.cfg
        order = schedule.order
        n = len(order)
        S = cfg.bytes
        land_s = float(cfg.land_timeout_s)
        flow = f"collr.r{rnd}.{next(self._fid)}"
        routed = {
            "forward_legs": 0,
            "forward_bytes": 0,
            "forward_retries": 0,
            "downgraded_legs": 0,
            "coordinator_payload_bytes": 0,
            "setup_bytes": 0,
            "verify_bytes": 0,
        }
        ports = {name: self.nodes[name].daemon.data_port
                 for name in order}
        # Nodes discovered forward-less THIS round (fresh each round:
        # a restarted daemon may have regained the capability).
        fwd_less: set = set()
        seq_next = {name: 0 for name in order}
        expect_rx = {name: S for name in order}
        registered: List[str] = []
        owners: Dict[str, ResilientDcnXferClient] = {}
        error: Optional[str] = None
        elapsed = 1e-9
        try:
            for name in order:
                node = self.nodes[name]
                if getattr(node, "down", False):
                    raise DcnXferError(f"node {name} down")
                # One OWNER client per node, held for the whole
                # round: a daemon releases a flow when the connection
                # that registered it dies, and a failing leg closes
                # its pooled client on the way out — so the round's
                # shared flow must be anchored to a connection no leg
                # can ever be handed.
                c = owners[name] = self._checkout(node)
                c.register_flow(flow, peer="routed", bytes=S)
                registered.append(name)
                c.put(flow, inputs[name])
                dcn.wait_flow_rx(c, flow, S, timeout_s=land_s)
                routed["setup_bytes"] += S
            t0 = time.monotonic()
            with trace.span("collective.run",
                            histogram="collective.run",
                            collective=cfg.op,
                            algorithm=schedule.algorithm,
                            bytes=cfg.bytes, nodes=n, round=rnd,
                            routed=True) as span:
                gi = 0
                for phase, groups in itertools.groupby(
                        schedule.steps,
                        key=lambda g: g[0].phase if g else ""):
                    with trace.span("collective.phase", phase=phase,
                                    routed=True):
                        for group in groups:
                            self._routed_group(
                                flow, group, ports, seq_next,
                                expect_rx, fwd_less, routed,
                                per_node_ok, per_node_failed)
                            gi += 1
                span.annotate(ok=True)
            elapsed = max(time.monotonic() - t0, 1e-9)
        except (DcnXferError, OSError, TimeoutError) as e:
            counters.inc("collective.failures")
            error = str(e)
        ok = error is None
        if ok and cfg.verify:
            expected = synth.expected_outputs(cfg.op, order, inputs,
                                              cfg.bytes)
            try:
                for name, (off, ln, want) in expected.items():
                    got = owners[name].read(flow, ln, offset=off)
                    routed["verify_bytes"] += len(got)
                    if got != want:
                        counters.inc("collective.verify.failed")
                        ok = False
                        error = f"verification failed on {name}"
                        break
            except (DcnXferError, OSError, TimeoutError) as e:
                counters.inc("collective.verify.failed")
                ok = False
                error = f"verification read failed: {e}"
        for name in registered:
            try:
                owners[name].release_flow(flow)
            except (DcnXferError, OSError):
                pass
        for name, c in owners.items():
            # A clean round returns its owners to the pool; a faulted
            # one closes them (a dead daemon's conn must not be
            # re-dealt to the next round's setup).
            self._checkin(self.nodes[name], c, clean=error is None)
        algbw = cfg.bytes / elapsed
        busbw = algbw * synth.bus_factor(cfg.op, n)
        if ok:
            timeseries.gauge("collective.busbw_bps", busbw)
            timeseries.gauge("collective.algbw_bps", algbw)
            timeseries.gauge("collective.routed.busbw_bps", busbw)
        entry.update(
            ok=ok,
            error=error,
            time_ms=round(elapsed * 1e3, 3),
            algbw_bps=round(algbw, 1) if ok else 0.0,
            busbw_bps=round(busbw, 1) if ok else 0.0,
            per_node_ok=per_node_ok,
            per_node_failed=per_node_failed,
            routed=routed,
        )
        return entry

    def _routed_group(self, flow: str, group: List[synth.TransferStep],
                      ports: Dict[str, int], seq_next: Dict[str, int],
                      expect_rx: Dict[str, int], fwd_less: set,
                      routed: dict, per_node_ok: Dict[str, int],
                      per_node_failed: Dict[str, int]) -> None:
        """One barrier group, routed: post every leg as a forward
        program, join verdicts, then wait for each destination's
        cumulative rx to cover the group's landings.  A barrier
        timeout gets ONE engine-level re-post of that destination's
        legs under the seqs they burned (dedup keeps replays
        exactly-once) before it fails the round."""
        counters.inc("collective.steps")
        ctx = trace.context()
        legs: List[Tuple[synth.TransferStep, int]] = []
        for t in group:
            seq_next[t.dst] += 1
            legs.append((t, seq_next[t.dst]))
            expect_rx[t.dst] += t.nbytes
        futures = [(t, self._pool.submit(self._forward_leg, t, flow,
                                         ports[t.dst], seq, fwd_less,
                                         routed, ctx))
                   for t, seq in legs]
        errors: List[Tuple[synth.TransferStep, BaseException]] = []
        for t, fut in futures:
            try:
                fut.result()
                per_node_ok[t.src] += 1
            except (DcnXferError, OSError, TimeoutError) as e:
                errors.append((t, e))
                per_node_failed[t.src] += 1
        if errors:
            t, e = errors[0]
            raise DcnXferError(
                f"routed leg {t.src}->{t.dst} failed: {e}")
        land_s = float(self.cfg.land_timeout_s)
        for name in sorted({t.dst for t, _ in legs}):
            with self._client(self.nodes[name]) as c:
                try:
                    dcn.wait_flow_rx(c, flow, expect_rx[name],
                                     timeout_s=land_s)
                    continue
                except TimeoutError:
                    counters.inc("collective.forward.reposted")
            for t, seq in legs:
                if t.dst == name:
                    self._forward_leg(t, flow, ports[name], seq,
                                      fwd_less, routed, ctx)
            with self._client(self.nodes[name]) as c:
                dcn.wait_flow_rx(c, flow, expect_rx[name],
                                 timeout_s=land_s)

    def _forward_leg(self, t: synth.TransferStep, flow: str,
                     dst_port: int, seq: int, fwd_less: set,
                     routed: dict, ctx: Optional[dict]) -> None:
        """One routed leg: a single control-plane call to the SOURCE
        daemon (``forward``) that moves the payload daemon->daemon.
        A source that answers "unknown op" is downgraded mid-schedule
        to a coordinator-routed leg — same seq, same landing
        semantics, but the payload crosses the coordinator and the
        accounting says so."""
        with contextlib.ExitStack() as stack:
            if ctx:
                stack.enter_context(trace.attach(ctx["trace"],
                                                 ctx["span"]))
            with trace.span("collective.leg",
                            histogram="collective.leg",
                            src=t.src, dst=t.dst, phase=t.phase,
                            bytes=t.nbytes, reduce=t.reduce,
                            routed=True) as span:
                src = self.nodes[t.src]
                dst = self.nodes[t.dst]
                if getattr(src, "down", False) \
                        or getattr(dst, "down", False):
                    counters.inc("collective.failures")
                    raise DcnXferError(
                        f"leg {t.src}->{t.dst}: node down")
                last: Optional[BaseException] = None
                attempts = 0
                for _attempt in self._retry.attempts():
                    attempts += 1
                    try:
                        with self._client(src) as sc:
                            if t.src in fwd_less:
                                self._downgraded_leg(sc, flow, t,
                                                     dst_port, seq,
                                                     routed)
                            else:
                                try:
                                    resp = sc.forward(
                                        flow, "127.0.0.1", dst_port,
                                        t.nbytes, offset=t.offset,
                                        seq=seq, total=self.cfg.bytes,
                                        reduce=t.reduce,
                                        stage_wait_ms=int(
                                            self.cfg.land_timeout_s
                                            * 1e3))
                                except DcnXferError as e:
                                    if "unknown op" not in str(e):
                                        raise
                                    # Capability-less daemon: every
                                    # later leg from this source goes
                                    # coordinator-routed without
                                    # re-asking.
                                    fwd_less.add(t.src)
                                    counters.inc(
                                        "collective.forward."
                                        "downgraded")
                                    self._downgraded_leg(
                                        sc, flow, t, dst_port, seq,
                                        routed)
                                else:
                                    with self._acct_lock:
                                        routed["forward_legs"] += 1
                                        routed["forward_bytes"] += int(
                                            resp.get("bytes",
                                                     t.nbytes))
                                        routed["forward_retries"] += \
                                            max(int(resp.get(
                                                "attempts", 1)) - 1, 0)
                        counters.inc("collective.transfers")
                        counters.inc("collective.forward.legs")
                        span.annotate(attempts=attempts)
                        return
                    except (DcnXferError, OSError, TimeoutError) as e:
                        last = e
                        counters.inc("collective.leg.retried")
                        counters.inc("collective.forward.retried")
                span.annotate(attempts=attempts)
                counters.inc("collective.failures")
                raise DcnXferError(
                    f"routed leg {t.src}->{t.dst} spent its retry "
                    f"budget: {last}")

    def _downgraded_leg(self, sc, flow: str, t: synth.TransferStep,
                        dst_port: int, seq: int, routed: dict) -> None:
        """Coordinator-routed fallback for ONE leg: read the source
        region through the client, write it to the destination
        daemon's data port as the SAME forward frame (same seq, same
        reduce semantics, indistinguishable landing) — the payload
        crosses the coordinator twice, and the lane accounting records
        exactly that."""
        data = sc.read(flow, t.nbytes, offset=t.offset)
        if len(data) != t.nbytes:
            raise DcnXferError(
                f"downgraded leg {t.src}->{t.dst}: short read "
                f"({len(data)}/{t.nbytes})")
        sc.put_range(flow, data, t.offset, seq, "127.0.0.1", dst_port,
                     reduce=t.reduce, total=self.cfg.bytes)
        with self._acct_lock:
            routed["downgraded_legs"] += 1
            # In once (read), out once (put_range).
            routed["coordinator_payload_bytes"] += 2 * t.nbytes

    def _run_group(self, rnd: int, gi: int,
                   group: List[synth.TransferStep], bufs: dict,
                   per_node_ok: Dict[str, int],
                   per_node_failed: Dict[str, int],
                   ) -> List[Tuple[synth.TransferStep, BaseException]]:
        """One barrier group: snapshot payloads, run every leg on the
        pool, apply reductions coordinator-side after the join (so
        overlapping reduce targets — a tree root's fan-in — never
        race)."""
        counters.inc("collective.steps")
        ctx = trace.context()
        staged = [(t, bytes(bufs[t.src][t.offset:t.offset + t.nbytes]))
                  for t in group]
        futures = [(t, payload,
                    self._pool.submit(self._leg, rnd, gi, t, payload,
                                      ctx))
                   for t, payload in staged]
        landed: List[Tuple[synth.TransferStep, bytes]] = []
        errors: List[Tuple[synth.TransferStep, BaseException]] = []
        for t, payload, fut in futures:
            try:
                landed.append((t, fut.result()))
                per_node_ok[t.src] += 1
            except (DcnXferError, OSError, TimeoutError) as e:
                errors.append((t, e))
                per_node_failed[t.src] += 1
        for t, got in landed:
            if t.reduce:
                synth.combine(bufs[t.dst], t.offset, got)
            else:
                bufs[t.dst][t.offset:t.offset + t.nbytes] = got
        return errors


# -- CLI: the acceptance comparisons -----------------------------------------


class CompareError(Exception):
    """A comparison leg failed outright (not a margin miss)."""


def _boot_fleet(name: str, nodes: int, racks: int):
    from container_engine_accelerators_tpu.fleet.controller import (
        FleetController,
    )

    ctl = FleetController({
        "name": name,
        "nodes": int(nodes),
        "racks": int(racks),
        "chips": 2,
        "topology": "1x2x1",
        "rounds": 0,
        "metrics": False,
    })
    ctl.boot()
    return ctl


def _best_round(ctl, args, algorithm: str,
                routed: bool = False) -> Optional[dict]:
    """``--rounds`` rounds of one pinned algorithm on a booted fleet;
    keeps the best-busbw entry.  A family the rig cannot lower
    (hierarchical on unequal racks) is *not a candidate* — returns
    None; a round that FAILS raises :class:`CompareError`."""
    engine = CollectiveEngine(
        ctl.nodes, ctl.topology, links=ctl.links,
        cfg=CollectiveConfig(op=args.op, bytes=args.bytes,
                             algorithm=algorithm, routed=routed))
    try:
        best = None
        for rnd in range(int(args.rounds)):
            try:
                entry = engine.run_round(rnd)
            except synth.SynthesisError as e:
                print(f"# {algorithm}: not a candidate ({e})",
                      file=sys.stderr)
                return None
            if not entry["ok"]:
                raise CompareError(
                    f"{algorithm} round {rnd} failed: "
                    f"{entry['error']}")
            if best is None \
                    or entry["busbw_bps"] > best["busbw_bps"]:
                best = entry
        return best
    finally:
        engine.close()


# The pinned asymmetric rig (5 nodes round-robined into 2 UNEQUAL
# racks: r0={n0,n2,n4}, r1={n1,n3}) with one degraded spine: both
# cross-rack edges the topology-blind families are forced through —
# the rack-major ring's wrap edges, which are also the star tree's
# root legs.  Ring and tree take ``order`` only, so they cannot route
# around these; the searched engine plans on the measured graph and
# can.
DEFAULT_SPINE_FAULTS = (
    "node:n4<->node:n1:latency:25",
    "node:n3<->node:n0:latency:25",
)

#: families the searched schedule must beat (best of)
FAMILIES = ("ring", "tree", "hierarchical")


def _compare_searched(args) -> int:
    """The searched-schedule acceptance gate: on the pinned asymmetric
    rig (unequal racks + degraded spine pairs), ``searched`` must beat
    the best hand-written family's bus bandwidth by ``--margin``; with
    ``--routed`` the searched run must ALSO prove its forwarded legs
    moved zero payload bytes through coordinator clients."""
    ctl = _boot_fleet("collective-searched", args.nodes, args.racks)
    spine = list(args.spine_fault or DEFAULT_SPINE_FAULTS)
    try:
        for spec in spine:
            ctl.links.apply(spec)
        families = {}
        for algo in FAMILIES:
            families[algo] = _best_round(ctl, args, algo)
        searched = _best_round(ctl, args, "searched",
                               routed=bool(args.routed))
    except CompareError as e:
        print(str(e), file=sys.stderr)
        return 2
    finally:
        ctl.close()
    candidates = {a: e for a, e in families.items() if e is not None}
    if not candidates or searched is None:
        print("no comparable family result", file=sys.stderr)
        return 2
    best_family = max(candidates, key=lambda a:
                      candidates[a]["busbw_bps"])
    family_bw = candidates[best_family]["busbw_bps"]
    searched_bw = searched["busbw_bps"]
    margin = searched_bw / max(family_bw, 1e-9)
    ok = margin >= float(args.margin)
    routed_acct = searched.get("routed") or {}
    if args.routed:
        # The lane-accounting proof: forwarded legs land on the
        # daemons (dcn.lane.forward.*), never on coordinator clients.
        if routed_acct.get("coordinator_payload_bytes", -1) != 0:
            print(f"# routed proof FAILED: "
                  f"{routed_acct.get('coordinator_payload_bytes')} "
                  f"payload bytes crossed coordinator clients",
                  file=sys.stderr)
            ok = False
        if not routed_acct.get("forward_bytes"):
            print("# routed proof FAILED: no forwarded bytes",
                  file=sys.stderr)
            ok = False
    report = {
        "mode": "searched",
        "nodes": int(args.nodes), "racks": int(args.racks),
        "op": args.op, "bytes": int(args.bytes),
        "routed": bool(args.routed),
        "spine_faults": spine,
        "families": families,
        "best_family": best_family,
        "searched": searched,
        "margin_x": round(margin, 3),
        "margin": float(args.margin),
        "pass": ok,
    }
    trend_rc = _compare_ledger(report, args)
    print(json.dumps(report))
    print(f"# searched {searched_bw:.0f} B/s vs best family "
          f"({best_family}) {family_bw:.0f} B/s = {margin:.2f}x "
          f"(need >= {args.margin:g}x) -> "
          f"{'PASS' if ok else 'FAIL'}", file=sys.stderr)
    if not ok:
        return 1
    return trend_rc


def _compare_ledger(report: dict, args) -> int:
    """Searched-vs-family evidence into the history ledger, judged
    against PRIOR runs of this config key first (a regressed run
    cannot poison its own baseline — fleet_sim's discipline).
    Returns 1 on a regression under ``--trend-gate``, else 0; ledger
    trouble costs the trend layer, never the comparison verdict."""
    if not (args.ledger or args.trend_gate):
        return 0
    from container_engine_accelerators_tpu.obs import history

    ledger = history.RunLedger()
    if not ledger.enabled:
        return 0
    cfg_key = history.config_key(
        "collective_compare", report["op"], f"b{report['bytes']}",
        f"n{report['nodes']}", f"r{report['racks']}",
        "routed" if report["routed"] else "coord")
    metrics = {
        "searched_busbw_bps": report["searched"]["busbw_bps"],
        "best_family_busbw_bps":
            report["families"][report["best_family"]]["busbw_bps"],
        "margin_x": report["margin_x"],
    }
    if report["routed"]:
        metrics["routed_busbw_bps"] = report["searched"]["busbw_bps"]
    try:
        prior = ledger.records(kind="collective_compare",
                               cfg_key=cfg_key)
    except history.LedgerError as e:
        print(f"history ledger unreadable ({e}); trend gate skipped",
              file=sys.stderr)
        return 0
    verdicts = [history.trend_verdict(prior, m, v)
                for m, v in sorted(metrics.items())]
    ledger.record("collective_compare", cfg_key, metrics,
                  run_id=history.new_run_id())
    regressed = [v for v in verdicts if v["status"] == "regressed"]
    for v in verdicts:
        if v["status"] != "no_baseline":
            print("trend: " + history.format_verdict(v),
                  file=sys.stderr)
    report["trend"] = {"config_key": cfg_key, "verdicts": verdicts,
                       "ok": not regressed}
    return 1 if (args.trend_gate and regressed) else 0


def _scale_check(args) -> int:
    """The 2→4 rack scaling gate: routed searched busbw must GROW
    with fleet size on equal-rack rigs with a uniform cross-rack
    latency tier (per-rank bytes fixed, so more ranks = more data in
    flight — busbw is exactly the metric that must rise)."""
    points = []
    for racks in (2, 4):
        nodes = racks * int(args.rack_size)
        ctl = _boot_fleet(f"collective-scale-{racks}", nodes, racks)
        try:
            if args.xrack_latency_ms > 0:
                for a in range(racks):
                    for b in range(a + 1, racks):
                        ctl.links.apply(
                            f"rack:r{a}<->rack:r{b}:latency:"
                            f"{args.xrack_latency_ms:g}")
            best = _best_round(ctl, args, "searched", routed=True)
        except CompareError as e:
            print(str(e), file=sys.stderr)
            return 2
        finally:
            ctl.close()
        if best is None:
            print(f"searched failed on {racks} racks",
                  file=sys.stderr)
            return 2
        points.append({"racks": racks, "nodes": nodes,
                       "busbw_bps": best["busbw_bps"],
                       "time_ms": best["time_ms"],
                       "routed": best.get("routed")})
    grew = points[1]["busbw_bps"] > points[0]["busbw_bps"]
    print(json.dumps({"mode": "scale", "op": args.op,
                      "bytes": int(args.bytes),
                      "rack_size": int(args.rack_size),
                      "xrack_latency_ms": float(args.xrack_latency_ms),
                      "points": points, "pass": grew}))
    print(f"# routed searched busbw {points[0]['busbw_bps']:.0f} B/s "
          f"@2 racks -> {points[1]['busbw_bps']:.0f} B/s @4 racks -> "
          f"{'PASS' if grew else 'FAIL'}", file=sys.stderr)
    return 0 if grew else 1


def _compare(args) -> int:
    """Boot an in-process 2-rack fleet, degrade the cross-rack tier,
    run ring and hierarchical pinned over the SAME rig, and gate
    hierarchical's bus bandwidth at ``margin`` x the flat ring's."""
    ctl = _boot_fleet("collective-compare", args.nodes, args.racks)
    results = {}
    try:
        if args.xrack_latency_ms > 0:
            ctl.links.apply(
                f"rack:r0<->rack:r1:latency:{args.xrack_latency_ms:g}")
        for algo in ("ring", "hierarchical"):
            try:
                results[algo] = _best_round(ctl, args, algo)
            except CompareError as e:
                print(str(e), file=sys.stderr)
                return 2
            if results[algo] is None:
                print(f"{algo}: not a candidate on this rig",
                      file=sys.stderr)
                return 2
    finally:
        ctl.close()
    ring_bw = results["ring"]["busbw_bps"]
    hier_bw = results["hierarchical"]["busbw_bps"]
    ratio = hier_bw / max(ring_bw, 1e-9)
    ok = ratio >= float(args.margin)
    print(json.dumps({
        "nodes": int(args.nodes), "racks": int(args.racks),
        "op": args.op, "bytes": int(args.bytes),
        "xrack_latency_ms": float(args.xrack_latency_ms),
        "ring": results["ring"], "hierarchical": results["hierarchical"],
        "ratio": round(ratio, 3), "margin": float(args.margin),
        "pass": ok,
    }))
    print(f"# hierarchical {hier_bw:.0f} B/s vs ring {ring_bw:.0f} B/s "
          f"= {ratio:.2f}x (need >= {args.margin:g}x) -> "
          f"{'PASS' if ok else 'FAIL'}", file=sys.stderr)
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="topology-aware collective engine CLI")
    p.add_argument("--compare", action="store_true",
                   help="run the ring-vs-hierarchical acceptance "
                        "comparison on an in-process fleet")
    p.add_argument("--searched", action="store_true",
                   help="with --compare: searched vs the best "
                        "hand-written family on the pinned asymmetric "
                        "rig (unequal racks + degraded spine pairs)")
    p.add_argument("--routed", action="store_true",
                   help="run the searched schedule in daemon-routed "
                        "mode and gate the zero-coordinator-payload "
                        "lane-accounting proof")
    p.add_argument("--scale-check", action="store_true",
                   help="routed searched busbw must grow on a 2->4 "
                        "rack scaling check")
    p.add_argument("--spine-fault", action="append", default=None,
                   metavar="SPEC",
                   help="link-fault spec(s) for the degraded spine "
                        "(repeatable; default: the pinned 5-node "
                        "rig's ring wrap / tree root edges)")
    p.add_argument("--ledger", action="store_true",
                   help="record compare evidence to the history "
                        "ledger (kind collective_compare)")
    p.add_argument("--trend-gate", action="store_true",
                   help="exit non-zero when a recorded metric "
                        "regresses vs this config key's baseline")
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--racks", type=int, default=2)
    p.add_argument("--rack-size", type=int, default=2,
                   help="nodes per rack for --scale-check rigs")
    p.add_argument("--bytes", type=int, default=262144)
    p.add_argument("--op", default="all_reduce",
                   choices=list(synth.COLLECTIVES))
    p.add_argument("--rounds", type=int, default=3,
                   help="rounds per algorithm; best busbw is compared")
    p.add_argument("--xrack-latency-ms", type=float, default=25.0,
                   help="injected cross-rack one-way latency (the "
                        "slow-spine rig the comparison runs on)")
    p.add_argument("--margin", type=float, default=1.3,
                   help="the challenger must beat the incumbent by "
                        "this factor (ring-vs-hierarchical default "
                        "1.3; the searched gate passes 1.15)")
    args = p.parse_args(argv)
    if args.scale_check:
        return _scale_check(args)
    if not args.compare:
        p.error("nothing to do: pass --compare or --scale-check")
    if args.searched:
        return _compare_searched(args)
    return _compare(args)


if __name__ == "__main__":
    sys.exit(main())
