"""Execute synthesized collective schedules over the fleet rig.

The runner is the wire half of the engine: it takes the schedule
synth.py planned (ring / tree / hierarchical, chosen from the comm
graph) and drives every :class:`TransferStep` through the SAME data
plane the rest of the stack uses — pooled production
``ResilientDcnXferClient``s per node, serial staging legs or the
chunked/striped pipelined plane, every cross-node byte through the
link table (in-process fleets) or each worker daemon's real TCP stack
(process mode).  Link chaos therefore hits a collective exactly where
it would hit a training job's exchange.

Semantics: step groups are barriers.  Every leg's payload snapshots
pre-group state (so concurrent legs in one group can never observe
each other's landings — the same contract synth.simulate verifies),
legs run concurrently on a bounded pool, and the group's reductions
apply on the coordinator after every leg returns.  A leg retries
under a bounded budget; a leg that spends it fails the whole run for
this round (the controller's round loop is the outer retry, and a
graph-signature change from the fault re-synthesizes the schedule —
``collective.resynth``).

Accounting follows collectives/bench.py's nccl-tests conventions:
``algbw = S / t`` with S the per-rank payload and t the whole
schedule's wall time, ``busbw = algbw * bus_factor(op, n)`` — so a
number measured here compares against the XLA sweep's.  The run
emits ``collective.*`` counters/gauges and a span tree
(``collective.run`` > ``collective.phase`` > ``collective.leg`` with
src/dst/phase attrs) so the critical-path report names the hop that
dominated, not just the slower total.

CLI (the `make collectives` acceptance leg)::

    python -m container_engine_accelerators_tpu.collectives.runner \
        --compare --nodes 4 --racks 2 --xrack-latency-ms 25 \
        --bytes 262144 --margin 1.3

boots an in-process 2-rack fleet, degrades the cross-rack tier, runs
ring and hierarchical pinned, and exits non-zero unless hierarchical
beats the flat ring's bus bandwidth by the margin.
"""

import argparse
import contextlib
import itertools
import json
import logging
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from container_engine_accelerators_tpu.collectives import synth
from container_engine_accelerators_tpu.collectives.topo import CommGraph
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import timeseries, trace
from container_engine_accelerators_tpu.parallel import dcn, dcn_pipeline
from container_engine_accelerators_tpu.parallel.dcn_client import (
    DcnXferError,
    ResilientDcnXferClient,
)
from container_engine_accelerators_tpu.utils.retry import RetryPolicy

log = logging.getLogger(__name__)


class CollectiveConfig:
    """Engine knobs.  Scenario specs pass them as the ``collective:``
    mapping (:meth:`from_scenario` — unknown keys are dropped with a
    log line, the TPU_FAULT_SPEC rule)."""

    #: which collective and how many payload bytes per rank (S)
    op: str = "all_reduce"
    bytes: int = 262144
    #: pin one algorithm, or None = the cost model chooses per graph
    algorithm: Optional[str] = None
    #: verify every node's result region against the in-memory oracle
    verify: bool = True
    #: per-leg retry budget (the controller round loop retries above)
    leg_attempts: int = 3
    leg_backoff_ms: float = 30.0
    leg_deadline_s: float = 8.0
    #: land/read timeout for one DCN phase inside a leg
    land_timeout_s: float = 2.0
    #: concurrent legs per step group (and the client-pool high water)
    max_workers: int = 8
    #: per-node client retry deadline
    client_deadline_s: float = 4.0

    _FIELDS = ("op", "bytes", "algorithm", "verify", "leg_attempts",
               "leg_backoff_ms", "leg_deadline_s", "land_timeout_s",
               "max_workers", "client_deadline_s")

    def __init__(self, **kw):
        for field in self._FIELDS:
            setattr(self, field, kw.pop(field, getattr(type(self),
                                                       field)))
        if kw:
            raise TypeError(f"unknown CollectiveConfig fields: "
                            f"{sorted(kw)}")

    @classmethod
    def from_scenario(cls, raw: Optional[dict]) -> "CollectiveConfig":
        if raw is None:
            return cls()
        known = {}
        for key, value in dict(raw).items():
            if key in cls._FIELDS:
                known[key] = value
            else:
                log.error("ignoring unknown collective knob %r", key)
        return cls(**known)


class CollectiveEngine:
    """Synthesize-and-execute loop over one fleet's nodes.

    ``nodes`` is the controller's node map (EmulatedNode or ProcNode —
    both expose ``root``/``client``/``daemon.data_port``/``down``);
    ``links`` is the coordinator's LinkTable (fault evidence for the
    graph; process-mode fleets mirror their worker-shim faults into
    it).  ``pipe_cfg`` non-None routes legs over the pipelined plane.
    """

    def __init__(self, nodes: dict, topology, links=None,
                 cfg: Optional[CollectiveConfig] = None,
                 pipe_cfg=None):
        self.nodes = nodes
        self.topology = topology
        self.links = links
        self.cfg = cfg or CollectiveConfig()
        self.pipe_cfg = pipe_cfg
        self.synth = synth.Synthesizer(self.cfg.op, self.cfg.bytes,
                                       self.cfg.algorithm)
        self._retry = RetryPolicy(
            max_attempts=int(self.cfg.leg_attempts),
            initial_backoff_s=float(self.cfg.leg_backoff_ms) / 1e3,
            max_backoff_s=0.2,
            deadline_s=float(self.cfg.leg_deadline_s),
        )
        self._pool = ThreadPoolExecutor(
            max_workers=int(self.cfg.max_workers),
            thread_name_prefix="collective")
        self._client_pool: Dict[str, List] = {}
        self._clients_lock = threading.Lock()
        self._fid = itertools.count()

    # -- pooled clients (the serving frontend's discipline) ------------------

    @contextlib.contextmanager
    def _client(self, node):
        c = None
        with self._clients_lock:
            pool = self._client_pool.setdefault(node.name, [])
            if pool:
                c = pool.pop()
        if c is None:
            c = ResilientDcnXferClient(
                os.path.join(node.root, "tpu-dcn"),
                retry=RetryPolicy(
                    max_attempts=4, initial_backoff_s=0.02,
                    max_backoff_s=0.2,
                    deadline_s=float(self.cfg.client_deadline_s)),
            )
        clean = False
        try:
            yield c
            clean = True
        finally:
            if clean:
                with self._clients_lock:
                    self._client_pool.setdefault(node.name,
                                                 []).append(c)
            else:
                try:
                    c.close()
                except OSError:
                    pass

    def close(self) -> None:
        self._pool.shutdown(wait=True)
        with self._clients_lock:
            clients = [c for pool in self._client_pool.values()
                       for c in pool]
            self._client_pool.clear()
        for c in clients:
            try:
                c.close()
            except OSError:
                pass

    # -- graph + schedule ----------------------------------------------------

    def graph(self) -> CommGraph:
        return CommGraph.build(self.topology, links=self.links)

    # -- one leg -------------------------------------------------------------

    def _leg(self, rnd: int, gi: int, t: synth.TransferStep,
             payload: bytes, ctx: Optional[dict]) -> bytes:
        with contextlib.ExitStack() as stack:
            if ctx:
                # Legs run on pool threads; join the round's trace so
                # the critical-path report sees one tree per run.
                stack.enter_context(trace.attach(ctx["trace"],
                                                 ctx["span"]))
            with trace.span("collective.leg",
                            histogram="collective.leg",
                            src=t.src, dst=t.dst, phase=t.phase,
                            bytes=t.nbytes, reduce=t.reduce) as span:
                src, dst = self.nodes[t.src], self.nodes[t.dst]
                if getattr(src, "down", False) \
                        or getattr(dst, "down", False):
                    counters.inc("collective.failures")
                    raise DcnXferError(
                        f"leg {t.src}->{t.dst}: node down")
                flow = (f"coll.r{rnd}.g{gi}.{t.src}.{t.dst}."
                        f"{next(self._fid)}")
                with self._client(src) as sc, self._client(dst) as dc:
                    # Registration sits INSIDE the try: if the second
                    # register raises (its worker just died), the
                    # finally still releases whatever the first one
                    # registered — faulted rounds must not accumulate
                    # leaked assembly buffers on surviving daemons.
                    try:
                        dc.register_flow(flow, peer=t.src,
                                         bytes=t.nbytes)
                        sc.register_flow(flow, peer=t.dst,
                                         bytes=t.nbytes)
                        if self.pipe_cfg is None:
                            # Serial leg: whole-payload staging up
                            # front, ONCE (the controller's _leg
                            # discipline) — retries below re-send
                            # only, and a daemon restart that lost
                            # the staging is healed by the resilient
                            # client's transparent restage.  The
                            # pipelined leg stages chunk-by-chunk
                            # inside each attempt instead.
                            sc.put(flow, payload)
                            dcn.wait_flow_rx(
                                sc, flow, t.nbytes,
                                timeout_s=float(
                                    self.cfg.land_timeout_s))
                        last: Optional[BaseException] = None
                        attempts = 0
                        for _attempt in self._retry.attempts():
                            attempts += 1
                            try:
                                got = self._transfer(sc, dc, dst, flow,
                                                     payload, t)
                                if got != payload:
                                    raise DcnXferError(
                                        f"payload mismatch on {flow}")
                                counters.inc("collective.transfers")
                                span.annotate(attempts=attempts)
                                return got
                            except (DcnXferError, OSError,
                                    TimeoutError) as e:
                                last = e
                                counters.inc("collective.leg.retried")
                        span.annotate(attempts=attempts)
                        raise DcnXferError(
                            f"leg {t.src}->{t.dst} spent its retry "
                            f"budget: {last}")
                    except (DcnXferError, OSError, TimeoutError):
                        # One failure count per failed leg, whatever
                        # phase broke — registration, staging, or a
                        # spent retry budget.
                        counters.inc("collective.failures")
                        raise
                    finally:
                        for c in (sc, dc):
                            try:
                                c.release_flow(flow)
                            except (DcnXferError, OSError):
                                pass

    def _transfer(self, sc, dc, dst_node, flow: str, payload: bytes,
                  t: synth.TransferStep) -> bytes:
        """One attempt of a leg's data movement.  The serial path
        assumes ``_leg`` staged the payload once up front: an attempt
        re-sends only, and a daemon restart that lost the staging is
        healed by the resilient client (``dcn.send.restaged``)."""
        nbytes = len(payload)
        land_s = float(self.cfg.land_timeout_s)
        port = dst_node.daemon.data_port
        if self.pipe_cfg is not None:
            dcn_pipeline.send_pipelined(sc, flow, payload, "127.0.0.1",
                                        port, self.pipe_cfg,
                                        timeout_s=land_s)
            return dcn_pipeline.read_pipelined(dc, flow, nbytes,
                                               self.pipe_cfg,
                                               timeout_s=land_s)
        sc.send(flow, "127.0.0.1", port, nbytes)
        dcn.wait_flow_rx(dc, flow, nbytes, timeout_s=land_s)
        return dc.read(flow, nbytes)

    # -- one collective ------------------------------------------------------

    def run_round(self, rnd: int) -> dict:
        """Synthesize (or reuse) the schedule for the current graph and
        run it once.  Returns the round-log entry: algorithm, timing,
        nccl-convention bandwidths, failure and re-synthesis counts —
        ``ok`` keeps the controller's convergence contract."""
        cfg = self.cfg
        graph = self.graph()
        before = self.synth.resynth_count
        schedule = self.synth.schedule_for(graph)
        resynth = self.synth.resynth_count - before
        order = schedule.order
        n = len(order)
        inputs = synth.make_inputs(cfg.op, order, cfg.bytes, seed=rnd)
        bufs = {name: bytearray(b) for name, b in inputs.items()}
        counters.inc("collective.runs")
        entry = {
            "workload": "collective",
            "collective": cfg.op,
            "algorithm": schedule.algorithm,
            "bytes": cfg.bytes,
            "steps": len(schedule.steps),
            "transfers": schedule.transfers,
            "resynth": resynth,
            "est_cost_ms": schedule.to_dict()["est_cost_ms"],
        }
        per_node_ok: Dict[str, int] = {name: 0 for name in order}
        per_node_failed: Dict[str, int] = {name: 0 for name in order}
        error: Optional[str] = None
        t0 = time.monotonic()
        with trace.span("collective.run", histogram="collective.run",
                        collective=cfg.op,
                        algorithm=schedule.algorithm, bytes=cfg.bytes,
                        nodes=n, round=rnd) as span:
            gi = 0
            for phase, groups in itertools.groupby(
                    schedule.steps,
                    key=lambda g: g[0].phase if g else ""):
                with trace.span("collective.phase", phase=phase):
                    for group in groups:
                        errs = self._run_group(rnd, gi, group, bufs,
                                               per_node_ok,
                                               per_node_failed)
                        gi += 1
                        if errs:
                            error = str(errs[0][1])
                            break
                if error:
                    # Later groups consume this one's reductions; a
                    # broken barrier makes them meaningless.  The
                    # round fails, the controller loops, and the
                    # fault's signature change re-plans.
                    break
            span.annotate(ok=error is None, error=error)
        elapsed = max(time.monotonic() - t0, 1e-9)
        ok = error is None
        if ok and cfg.verify:
            expected = synth.expected_outputs(cfg.op, order, inputs,
                                              cfg.bytes)
            for name, (off, ln, want) in expected.items():
                if bytes(bufs[name][off:off + ln]) != want:
                    counters.inc("collective.verify.failed")
                    ok = False
                    error = f"verification failed on {name}"
                    break
        algbw = cfg.bytes / elapsed
        busbw = algbw * synth.bus_factor(cfg.op, n)
        if ok:
            # Gauges carry the LAST completed collective — a failed
            # round keeps the previous evidence instead of publishing
            # a bandwidth no data actually achieved.
            timeseries.gauge("collective.busbw_bps", busbw)
            timeseries.gauge("collective.algbw_bps", algbw)
        entry.update(
            ok=ok,
            error=error,
            time_ms=round(elapsed * 1e3, 3),
            algbw_bps=round(algbw, 1) if ok else 0.0,
            busbw_bps=round(busbw, 1) if ok else 0.0,
            per_node_ok=per_node_ok,
            per_node_failed=per_node_failed,
        )
        return entry

    def _run_group(self, rnd: int, gi: int,
                   group: List[synth.TransferStep], bufs: dict,
                   per_node_ok: Dict[str, int],
                   per_node_failed: Dict[str, int],
                   ) -> List[Tuple[synth.TransferStep, BaseException]]:
        """One barrier group: snapshot payloads, run every leg on the
        pool, apply reductions coordinator-side after the join (so
        overlapping reduce targets — a tree root's fan-in — never
        race)."""
        counters.inc("collective.steps")
        ctx = trace.context()
        staged = [(t, bytes(bufs[t.src][t.offset:t.offset + t.nbytes]))
                  for t in group]
        futures = [(t, payload,
                    self._pool.submit(self._leg, rnd, gi, t, payload,
                                      ctx))
                   for t, payload in staged]
        landed: List[Tuple[synth.TransferStep, bytes]] = []
        errors: List[Tuple[synth.TransferStep, BaseException]] = []
        for t, payload, fut in futures:
            try:
                landed.append((t, fut.result()))
                per_node_ok[t.src] += 1
            except (DcnXferError, OSError, TimeoutError) as e:
                errors.append((t, e))
                per_node_failed[t.src] += 1
        for t, got in landed:
            if t.reduce:
                synth.combine(bufs[t.dst], t.offset, got)
            else:
                bufs[t.dst][t.offset:t.offset + t.nbytes] = got
        return errors


# -- CLI: the ring-vs-hierarchical acceptance comparison ---------------------


def _compare(args) -> int:
    """Boot an in-process 2-rack fleet, degrade the cross-rack tier,
    run ring and hierarchical pinned over the SAME rig, and gate
    hierarchical's bus bandwidth at ``margin`` x the flat ring's."""
    from container_engine_accelerators_tpu.fleet.controller import (
        FleetController,
    )

    ctl = FleetController({
        "name": "collective-compare",
        "nodes": int(args.nodes),
        "racks": int(args.racks),
        "chips": 2,
        "topology": "1x2x1",
        "rounds": 0,
        "metrics": False,
    })
    results = {}
    try:
        ctl.boot()
        if args.xrack_latency_ms > 0:
            ctl.links.apply(
                f"rack:r0<->rack:r1:latency:{args.xrack_latency_ms:g}")
        for algo in ("ring", "hierarchical"):
            engine = CollectiveEngine(
                ctl.nodes, ctl.topology, links=ctl.links,
                cfg=CollectiveConfig(op=args.op, bytes=args.bytes,
                                     algorithm=algo))
            try:
                best = None
                for rnd in range(int(args.rounds)):
                    entry = engine.run_round(rnd)
                    if not entry["ok"]:
                        print(f"{algo} round {rnd} failed: "
                              f"{entry['error']}", file=sys.stderr)
                        return 2
                    if best is None \
                            or entry["busbw_bps"] > best["busbw_bps"]:
                        best = entry
                results[algo] = best
            finally:
                engine.close()
    finally:
        ctl.close()
    ring_bw = results["ring"]["busbw_bps"]
    hier_bw = results["hierarchical"]["busbw_bps"]
    ratio = hier_bw / max(ring_bw, 1e-9)
    ok = ratio >= float(args.margin)
    print(json.dumps({
        "nodes": int(args.nodes), "racks": int(args.racks),
        "op": args.op, "bytes": int(args.bytes),
        "xrack_latency_ms": float(args.xrack_latency_ms),
        "ring": results["ring"], "hierarchical": results["hierarchical"],
        "ratio": round(ratio, 3), "margin": float(args.margin),
        "pass": ok,
    }))
    print(f"# hierarchical {hier_bw:.0f} B/s vs ring {ring_bw:.0f} B/s "
          f"= {ratio:.2f}x (need >= {args.margin:g}x) -> "
          f"{'PASS' if ok else 'FAIL'}", file=sys.stderr)
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="topology-aware collective engine CLI")
    p.add_argument("--compare", action="store_true",
                   help="run the ring-vs-hierarchical acceptance "
                        "comparison on an in-process fleet")
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--racks", type=int, default=2)
    p.add_argument("--bytes", type=int, default=262144)
    p.add_argument("--op", default="all_reduce",
                   choices=list(synth.COLLECTIVES))
    p.add_argument("--rounds", type=int, default=3,
                   help="rounds per algorithm; best busbw is compared")
    p.add_argument("--xrack-latency-ms", type=float, default=25.0,
                   help="injected cross-rack one-way latency (the "
                        "slow-spine rig the comparison runs on)")
    p.add_argument("--margin", type=float, default=1.3,
                   help="hierarchical must beat ring by this factor")
    args = p.parse_args(argv)
    if not args.compare:
        p.error("nothing to do: pass --compare")
    return _compare(args)


if __name__ == "__main__":
    sys.exit(main())
