"""Public re-exports for the collectives package."""
from container_engine_accelerators_tpu.collectives.bench import (
    CollectiveResult,
    run_sweep,
)

__all__ = ["CollectiveResult", "run_sweep"]
