"""Public re-exports for the collectives package.

The engine modules (topo / synth / runner) are dependency-light and
import eagerly; the XLA bench re-exports resolve lazily so importing
the engine on a coordinator never drags jax in (bench.py imports jax
at module top — that is its job, not the planner's).
"""

__all__ = ["CollectiveResult", "run_sweep"]


def __getattr__(name):
    if name in __all__:
        from container_engine_accelerators_tpu.collectives import bench

        return getattr(bench, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
