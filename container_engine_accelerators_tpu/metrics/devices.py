"""Container → TPU device assignment via the kubelet PodResources API.

Port of the reference's devices.go (pkg/gpu/nvidia/metrics/devices.go:33-100):
dial the kubelet's pod-resources unix socket, List all pods, and collect the
``google.com/tpu`` device IDs each container was allocated.  Virtual
(shared) device IDs are skipped, like the reference skips vgpu IDs
(devices.go:86-92) — per-container accounting is meaningless when the chip
is shared.
"""

import dataclasses
import logging
from typing import Dict, List

import grpc

from container_engine_accelerators_tpu.metrics import podresources_v1_pb2 as pb
from container_engine_accelerators_tpu.sharing import is_virtual_device_id

log = logging.getLogger(__name__)

POD_RESOURCES_SOCKET = "/var/lib/kubelet/pod-resources/kubelet.sock"
TPU_RESOURCE_NAME = "google.com/tpu"


@dataclasses.dataclass(frozen=True)
class ContainerID:
    namespace: str
    pod: str
    container: str


class PodResourcesClient:
    """Thin client over the PodResourcesLister service."""

    def __init__(self, socket_path: str = POD_RESOURCES_SOCKET):
        self.socket_path = socket_path

    def list_pods(self) -> pb.ListPodResourcesResponse:
        with grpc.insecure_channel(f"unix:{self.socket_path}") as channel:
            lister = channel.unary_unary(
                "/v1.PodResourcesLister/List",
                request_serializer=pb.ListPodResourcesRequest.SerializeToString,
                response_deserializer=pb.ListPodResourcesResponse.FromString,
            )
            return lister(pb.ListPodResourcesRequest(), timeout=10)

    def get_devices_for_all_containers(
        self, resource_name: str = TPU_RESOURCE_NAME
    ) -> Dict[ContainerID, List[str]]:
        """Map each container to its allocated physical TPU device IDs
        (ref: devices.go:51-100)."""
        out: Dict[ContainerID, List[str]] = {}
        resp = self.list_pods()
        for pod in resp.pod_resources:
            for container in pod.containers:
                device_ids: List[str] = []
                for dev in container.devices:
                    if dev.resource_name != resource_name:
                        continue
                    for device_id in dev.device_ids:
                        if is_virtual_device_id(device_id):
                            log.debug(
                                "skipping virtual device %s for metrics",
                                device_id,
                            )
                            continue
                        device_ids.append(device_id)
                if device_ids:
                    out[
                        ContainerID(
                            namespace=pod.namespace,
                            pod=pod.name,
                            container=container.name,
                        )
                    ] = device_ids
        return out
