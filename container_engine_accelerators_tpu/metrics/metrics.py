"""Prometheus metrics server for TPU nodes.

Port of the reference's metrics server (pkg/gpu/nvidia/metrics/metrics.go):
the same load-bearing gauge set — the serving demo's HPA scales on
``duty_cycle`` (demo/serving/tensorflow-serving.yaml:63-79) — with TPU
sources: TensorCore duty cycle and HBM occupancy come from tpulib counters
instead of NVML sampling (metrics.go:59-115, util.go:37-94).

Per-container gauges join device assignments through the kubelet
PodResources API; per-node gauges cover every chip.  The registry is fully
reset periodically so pods that vanish stop being reported
(metrics.go:241-253).

Exported gauges (container): duty_cycle, memory_total, memory_used, request
           (node):           duty_cycle_tpu_node, memory_total_tpu_node,
                             memory_used_tpu_node
           (agent):          agent_events{event=...} — the
                             self-healing counters from metrics/counters.py
                             (retries, reconnects, health transitions)
"""

import logging
import threading
import time
from typing import Optional, Tuple

from prometheus_client import CollectorRegistry, Gauge, start_http_server

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.metrics.devices import (
    POD_RESOURCES_SOCKET,
    PodResourcesClient,
    TPU_RESOURCE_NAME,
)
from container_engine_accelerators_tpu.tpulib.types import HbmInfo, TpuLib

log = logging.getLogger(__name__)

MAKE = "google"
RESET_INTERVAL_S = 60.0  # metricsResetInterval analog

_CONTAINER_LABELS = [
    "namespace",
    "pod",
    "container",
    "make",
    "accelerator_id",
    "model",
]
_NODE_LABELS = ["make", "accelerator_id", "model"]


class TpuMetricsCollector:
    """Sampling seam (the reference's metricsCollector interface,
    metrics.go:29-35): tests substitute a mock."""

    def __init__(self, lib: TpuLib):
        self.lib = lib

    def collect_tpu_device(self, device_name: str) -> Tuple[int, HbmInfo]:
        return (
            self.lib.duty_cycle(device_name),
            self.lib.hbm_info(device_name),
        )

    def devices(self):
        return [c.name for c in self.lib.chips()]

    def model(self, device_name: str) -> str:
        try:
            return self.lib.model(device_name)
        except Exception:
            return "tpu"


class MetricServer:
    def __init__(
        self,
        lib: Optional[TpuLib] = None,
        manager=None,
        port: int = 2112,
        collection_interval_s: float = 30.0,
        pod_resources_socket: str = POD_RESOURCES_SOCKET,
        collector: Optional[TpuMetricsCollector] = None,
        registry: Optional[CollectorRegistry] = None,
    ):
        self.collector = collector or TpuMetricsCollector(lib)
        self.manager = manager
        self.port = port
        self.collection_interval_s = collection_interval_s
        self.pod_resources = PodResourcesClient(pod_resources_socket)
        self.registry = registry or CollectorRegistry()
        self._stop = threading.Event()
        self._last_reset = time.monotonic()

        g = lambda name, doc, labels: Gauge(  # noqa: E731
            name, doc, labels, registry=self.registry
        )
        self.duty_cycle = g(
            "duty_cycle",
            "Percent of time over the past sample period during which the "
            "accelerator was actively processing",
            _CONTAINER_LABELS,
        )
        self.memory_total = g(
            "memory_total", "Total accelerator memory (bytes)", _CONTAINER_LABELS
        )
        self.memory_used = g(
            "memory_used", "Allocated accelerator memory (bytes)", _CONTAINER_LABELS
        )
        self.request = g(
            "request",
            "Number of accelerator devices requested by the container",
            ["namespace", "pod", "container", "resource_name"],
        )
        self.duty_cycle_node = g(
            "duty_cycle_tpu_node",
            "Node-level TPU duty cycle",
            _NODE_LABELS,
        )
        self.memory_total_node = g(
            "memory_total_tpu_node", "Node-level total HBM (bytes)", _NODE_LABELS
        )
        self.memory_used_node = g(
            "memory_used_tpu_node", "Node-level used HBM (bytes)", _NODE_LABELS
        )
        self.agent_events = g(
            "agent_events",
            "Cumulative self-healing/robustness events on this node agent "
            "(retries, reconnects, flow replays, health transitions, "
            "injected faults) keyed by metrics/counters.py name",
            ["event"],
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        start_http_server(self.port, registry=self.registry)
        t = threading.Thread(
            target=self._collect_loop, name="tpu-metrics", daemon=True
        )
        t.start()

    def stop(self) -> None:
        self._stop.set()

    def _collect_loop(self) -> None:
        while not self._stop.wait(self.collection_interval_s):
            try:
                self.collect_once()
            except Exception as e:
                log.error("metrics collection failed: %s", e)

    # -- collection ----------------------------------------------------------

    def _reset(self) -> None:
        for gauge in (
            self.duty_cycle,
            self.memory_total,
            self.memory_used,
            self.request,
            self.duty_cycle_node,
            self.memory_total_node,
            self.memory_used_node,
            self.agent_events,
        ):
            gauge.clear()

    def _chips_for(self, device_id: str):
        """A physical device ID is a chip (accelN) or a sub-slice (sliceM);
        expand to member chips for per-chip sampling."""
        if device_id.startswith("slice") and self.manager is not None:
            sm = self.manager.subslice_manager
            if sm is not None and device_id in sm._members:
                return [c.name for c in sm._members[device_id]]
            return []
        return [device_id]

    def collect_once(self) -> None:
        now = time.monotonic()
        if now - self._last_reset >= RESET_INTERVAL_S:
            self._reset()
            self._last_reset = now

        try:
            container_devices = self.pod_resources.get_devices_for_all_containers()
        except Exception as e:
            log.warning("pod-resources query failed: %s", e)
            container_devices = {}

        for cid, device_ids in container_devices.items():
            self.request.labels(
                namespace=cid.namespace,
                pod=cid.pod,
                container=cid.container,
                resource_name=TPU_RESOURCE_NAME,
            ).set(len(device_ids))
            for device_id in device_ids:
                for chip in self._chips_for(device_id):
                    try:
                        duty, hbm = self.collector.collect_tpu_device(chip)
                    except Exception as e:
                        log.warning("sampling %s failed: %s", chip, e)
                        continue
                    labels = dict(
                        namespace=cid.namespace,
                        pod=cid.pod,
                        container=cid.container,
                        make=MAKE,
                        accelerator_id=chip,
                        model=self.collector.model(chip),
                    )
                    self.duty_cycle.labels(**labels).set(duty)
                    self.memory_total.labels(**labels).set(hbm.total_bytes)
                    self.memory_used.labels(**labels).set(hbm.used_bytes)

        # Robustness counters are cumulative process state, re-published
        # wholesale each pass (so the periodic registry reset cannot lose
        # them the way it drops vanished pods' series).
        for name, value in counters.snapshot().items():
            self.agent_events.labels(event=name).set(value)

        for chip in self.collector.devices():
            try:
                duty, hbm = self.collector.collect_tpu_device(chip)
            except Exception as e:
                log.warning("sampling %s failed: %s", chip, e)
                continue
            labels = dict(
                make=MAKE, accelerator_id=chip, model=self.collector.model(chip)
            )
            self.duty_cycle_node.labels(**labels).set(duty)
            self.memory_total_node.labels(**labels).set(hbm.total_bytes)
            self.memory_used_node.labels(**labels).set(hbm.used_bytes)
