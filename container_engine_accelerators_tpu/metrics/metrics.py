"""Prometheus metrics server for TPU nodes.

Port of the reference's metrics server (pkg/gpu/nvidia/metrics/metrics.go):
the same load-bearing gauge set — the serving demo's HPA scales on
``duty_cycle`` (demo/serving/tensorflow-serving.yaml:63-79) — with TPU
sources: TensorCore duty cycle and HBM occupancy come from tpulib counters
instead of NVML sampling (metrics.go:59-115, util.go:37-94).

Per-container gauges join device assignments through the kubelet
PodResources API; per-node gauges cover every chip.  The registry is fully
reset periodically so pods that vanish stop being reported
(metrics.go:241-253).

Exported gauges (container): duty_cycle, memory_total, memory_used, request
           (node):           duty_cycle_tpu_node, memory_total_tpu_node,
                             memory_used_tpu_node
           (agent):          agent_events{event=...} — the
                             self-healing counters from metrics/counters.py
                             (retries, reconnects, health transitions);
                             agent_latency{op=...,bucket=...} — the
                             log2 latency histograms from obs/histo.py
                             as cumulative ``le``-style buckets in
                             microseconds (bucket="+Inf" = total count);
                             agent_rate{event=...} — per-second windowed
                             rates (obs/timeseries.py) for every counter
                             and byte series;
                             agent_goodput{scope=...,name=...} — landed
                             bytes/s per flow/link/node;
                             agent_gauge{name=...} — explicit gauges
                             (in-flight chunks, stripe utilization,
                             retransmit ratio, SLO verdicts);
                             agent_exemplar{op=...,bucket=...,trace=...}
                             — each latency bucket's worst sample's
                             trace id, value = its duration in µs
                             (metric → trace in one hop via
                             ``cmd/agent_trace.py --exemplar <op>``)

``start`` retries a port conflict under a bounded backoff budget (a
node agent racing its own previous incarnation's socket TIME_WAIT, or a
stray scraper squatting the port, must not kill the DaemonSet pod), and
``rebind`` moves a live server to a fresh port without restarting
collection.

Besides ``/metrics``, the server answers ``GET /spans?since=<cursor>``
with the node agent's recent span ring (obs/trace.py) as bounded JSON:
``{"cursor": N, "dropped": K, "spans": [...]}``.  Callers page by
passing the returned ``cursor`` back as ``since``; ``dropped`` counts
spans the ring evicted before they were read (the reader fell behind).
This is how the process-mode fleet aggregator collects every worker's
spans for the report's ``critical_path`` section without touching the
worker's disk — metrics and traces ride one scrape surface.

``GET /profile?since=<cursor>`` serves the continuous profiler's
folded-stack aggregate (obs/profiler.py) under the same cursor/bounded
JSON discipline: cumulative totals plus the stacks that changed after
the cursor — the third surface on the same listener, and how the
fleet aggregator merges per-worker CPU attribution into
``report.profile``.
"""

import json as _json
import logging
import threading
import time
import urllib.parse
from typing import Optional, Tuple

from prometheus_client import CollectorRegistry, Gauge, start_http_server

try:  # the /spans-capable server needs prometheus's WSGI surface
    from wsgiref.simple_server import make_server as _make_server

    from prometheus_client.exposition import (
        ThreadingWSGIServer as _ThreadingWSGIServer,
        _SilentHandler,
        make_wsgi_app as _make_wsgi_app,
    )
    _WSGI_OK = True
except ImportError:  # pragma: no cover — old prometheus_client
    _WSGI_OK = False

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.metrics.devices import (
    POD_RESOURCES_SOCKET,
    PodResourcesClient,
    TPU_RESOURCE_NAME,
)
from container_engine_accelerators_tpu.obs import (
    histo,
    profiler,
    timeseries,
    trace,
)
from container_engine_accelerators_tpu.tpulib.types import HbmInfo, TpuLib
from container_engine_accelerators_tpu.utils.retry import RetryPolicy

log = logging.getLogger(__name__)

MAKE = "google"
RESET_INTERVAL_S = 60.0  # metricsResetInterval analog

# Rides out a previous incarnation's listener lingering through its
# grace period (or TIME_WAIT on a SO_REUSEADDR-less kernel) without
# masking a genuinely squatted port forever.
BIND_RETRY = RetryPolicy(
    max_attempts=6, initial_backoff_s=0.2, max_backoff_s=2.0, deadline_s=15.0
)

# /spans response bounds: the default page and the hard per-GET cap —
# a scraper that never passes `limit` still gets a bounded body.
SPANS_DEFAULT_LIMIT = 512
SPANS_MAX_LIMIT = 2048

# /profile response bounds (same discipline): top-N folded stacks per
# GET, hard-capped — the registry itself is already LRU-bounded.
PROFILE_DEFAULT_LIMIT = profiler.SCRAPE_DEFAULT_LIMIT
PROFILE_MAX_LIMIT = profiler.SCRAPE_MAX_LIMIT

_CONTAINER_LABELS = [
    "namespace",
    "pod",
    "container",
    "make",
    "accelerator_id",
    "model",
]
_NODE_LABELS = ["make", "accelerator_id", "model"]


class TpuMetricsCollector:
    """Sampling seam (the reference's metricsCollector interface,
    metrics.go:29-35): tests substitute a mock."""

    def __init__(self, lib: TpuLib):
        self.lib = lib

    def collect_tpu_device(self, device_name: str) -> Tuple[int, HbmInfo]:
        return (
            self.lib.duty_cycle(device_name),
            self.lib.hbm_info(device_name),
        )

    def devices(self):
        return [c.name for c in self.lib.chips()]

    def model(self, device_name: str) -> str:
        try:
            return self.lib.model(device_name)
        except Exception:
            return "tpu"


class MetricServer:
    def __init__(
        self,
        lib: Optional[TpuLib] = None,
        manager=None,
        port: int = 2112,
        collection_interval_s: float = 30.0,
        pod_resources_socket: str = POD_RESOURCES_SOCKET,
        collector: Optional[TpuMetricsCollector] = None,
        registry: Optional[CollectorRegistry] = None,
    ):
        self.collector = collector or TpuMetricsCollector(lib)
        self.manager = manager
        self.port = port
        self.collection_interval_s = collection_interval_s
        self.pod_resources = PodResourcesClient(pod_resources_socket)
        self.registry = registry or CollectorRegistry()
        self._stop = threading.Event()
        self._last_reset = time.monotonic()

        g = lambda name, doc, labels: Gauge(  # noqa: E731
            name, doc, labels, registry=self.registry
        )
        self.duty_cycle = g(
            "duty_cycle",
            "Percent of time over the past sample period during which the "
            "accelerator was actively processing",
            _CONTAINER_LABELS,
        )
        self.memory_total = g(
            "memory_total", "Total accelerator memory (bytes)", _CONTAINER_LABELS
        )
        self.memory_used = g(
            "memory_used", "Allocated accelerator memory (bytes)", _CONTAINER_LABELS
        )
        self.request = g(
            "request",
            "Number of accelerator devices requested by the container",
            ["namespace", "pod", "container", "resource_name"],
        )
        self.duty_cycle_node = g(
            "duty_cycle_tpu_node",
            "Node-level TPU duty cycle",
            _NODE_LABELS,
        )
        self.memory_total_node = g(
            "memory_total_tpu_node", "Node-level total HBM (bytes)", _NODE_LABELS
        )
        self.memory_used_node = g(
            "memory_used_tpu_node", "Node-level used HBM (bytes)", _NODE_LABELS
        )
        self.agent_events = g(
            "agent_events",
            "Cumulative self-healing/robustness events on this node agent "
            "(retries, reconnects, flow replays, health transitions, "
            "injected faults) keyed by metrics/counters.py name",
            ["event"],
        )
        self.agent_latency = g(
            "agent_latency",
            "Log2-bucket latency histograms for node-agent operations "
            "(obs/histo.py): bucket is a cumulative le upper bound in "
            "microseconds; bucket=\"+Inf\" is the total observation count",
            ["op", "bucket"],
        )
        self.agent_rate = g(
            "agent_rate",
            "Per-second windowed rate (obs/timeseries.py ring buckets, "
            "window TPU_RATE_WINDOW_S) of every counter and byte "
            "series on this node agent — decays to zero when the "
            "activity stops",
            ["event"],
        )
        self.agent_goodput = g(
            "agent_goodput",
            "Landed-payload bytes per second over the trailing window, "
            "per flow / link / node (dedup-dropped replays and "
            "link-eaten frames never count)",
            ["scope", "name"],
        )
        self.agent_gauge = g(
            "agent_gauge",
            "Explicit instantaneous gauges (obs/timeseries.py): "
            "in-flight chunks, active stripes, retransmit ratio, SLO "
            "verdict gauges (slo.<key>.ok / slo.<key>.value)",
            ["name"],
        )
        self.agent_exemplar = g(
            "agent_exemplar",
            "Trace exemplars: for each agent_latency bucket, the trace "
            "id of its worst sample (value = that sample's duration in "
            "microseconds); resolve with cmd/agent_trace.py --exemplar",
            ["op", "bucket", "trace"],
        )
        self._httpd = None
        self._http_thread = None

    # -- lifecycle -----------------------------------------------------------

    def _wsgi_app(self):
        """The server's one WSGI app: ``/spans`` (bounded JSON from the
        span ring, cursor-paged) and ``/profile`` (the continuous
        profiler's folded stacks, same cursor discipline) beside the
        prometheus exposition at every other path — one listener, one
        port, every surface."""
        metrics_app = _make_wsgi_app(self.registry)

        def app(environ, start_response):
            path = environ.get("PATH_INFO", "")
            if path not in ("/spans", "/profile"):
                return metrics_app(environ, start_response)
            qs = urllib.parse.parse_qs(environ.get("QUERY_STRING", ""))

            def qint(key, default):
                try:
                    return int(qs.get(key, [default])[0])
                except (TypeError, ValueError):
                    return default  # malformed query degrades, 500s not

            since = qint("since", 0)
            if path == "/spans":
                limit = min(max(1, qint("limit", SPANS_DEFAULT_LIMIT)),
                            SPANS_MAX_LIMIT)
                spans, cursor, dropped = trace.tail_since(since, limit)
                payload = {
                    "cursor": cursor,
                    "dropped": dropped,
                    "spans": spans,
                }
            else:
                limit = min(max(1, qint("limit",
                                        PROFILE_DEFAULT_LIMIT)),
                            PROFILE_MAX_LIMIT)
                payload = profiler.scrape(since=since, limit=limit)
            body = _json.dumps(payload).encode()
            start_response("200 OK", [
                ("Content-Type", "application/json"),
                ("Content-Length", str(len(body))),
            ])
            return [body]

        return app

    def _bind(self, retry: RetryPolicy) -> None:
        """Bind the HTTP listener under a retry budget; OSError past the
        budget propagates (a squatted port is a real outage — but it
        costs the caller the budget, not a one-strike crash)."""

        def attempt():
            if not _WSGI_OK:  # pragma: no cover — old prometheus_client
                # Degraded: metrics only, no /spans (span scrapes then
                # read as stale; the fleet report says so per node).
                log.error("prometheus_client lacks the WSGI surface; "
                          "/spans endpoint unavailable")
                return start_http_server(self.port,
                                         registry=self.registry)

            class _Server(_ThreadingWSGIServer):
                """Per-bind subclass (prometheus does the same) so
                address_family tweaks never leak between servers."""

            httpd = _make_server("0.0.0.0", self.port, self._wsgi_app(),
                                 _Server, handler_class=_SilentHandler)
            t = threading.Thread(target=httpd.serve_forever,
                                 name="tpu-metrics-http", daemon=True)
            t.start()
            return httpd, t

        bound = retry.call(
            attempt,
            retry_on=(OSError,),
            on_retry=lambda a, e: counters.inc("metrics.bind.retried"),
        )
        if isinstance(bound, tuple):  # prometheus_client >= 0.17
            self._httpd, self._http_thread = bound
            # port=0 means "any free port": reflect the real one so
            # callers (and tests) can find the listener.
            self.port = self._httpd.server_port

    def start(self, retry: Optional[RetryPolicy] = None) -> None:
        self._bind(retry or BIND_RETRY)
        t = threading.Thread(
            target=self._collect_loop, name="tpu-metrics", daemon=True
        )
        t.start()

    def rebind(self, port: Optional[int] = None,
               retry: Optional[RetryPolicy] = None) -> int:
        """Move the listener to ``port`` (0 = any free port) without
        restarting collection; returns the bound port.  The recovery
        path for a port lost after boot — scraping resumes on the new
        port, gauges and counters carry over untouched."""
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if port is not None:
            self.port = port
        self._bind(retry or BIND_RETRY)
        counters.inc("metrics.rebind")
        log.warning("metrics server re-bound to port %d", self.port)
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def _collect_loop(self) -> None:
        while not self._stop.wait(self.collection_interval_s):
            try:
                self.collect_once()
            except Exception as e:
                log.error("metrics collection failed: %s", e)

    # -- collection ----------------------------------------------------------

    def _reset(self) -> None:
        for gauge in (
            self.duty_cycle,
            self.memory_total,
            self.memory_used,
            self.request,
            self.duty_cycle_node,
            self.memory_total_node,
            self.memory_used_node,
            self.agent_events,
            self.agent_latency,
            self.agent_rate,
            self.agent_goodput,
            self.agent_gauge,
            self.agent_exemplar,
        ):
            gauge.clear()
        # The registry has no scrape-wide lock, so a GET landing between
        # the clears above and the next collection pass would serve the
        # agent families empty (scrapers read vanished counters as 0).
        # Republish the cumulative state immediately; only the per-pod
        # device series stay absent until their next sample.
        self._republish_cumulative()

    def _republish_cumulative(self) -> None:
        """Re-export every family backed by cumulative process state
        (counters, histograms, rates, gauges) — called after a registry
        reset and on every collection pass."""
        # Robustness counters are cumulative process state, re-published
        # wholesale each pass (so the periodic registry reset cannot lose
        # them the way it drops vanished pods' series).
        for name, value in counters.snapshot().items():
            self.agent_events.labels(event=name).set(value)

        # Latency histograms ride the same contract: cumulative process
        # state, re-published wholesale.  Buckets are exported
        # Prometheus-style (cumulative over ascending le bounds) so
        # histogram_quantile-like math works on the scrape.
        for op, h in histo.snapshot().items():
            cumulative = 0
            for le, count in sorted(h["buckets"].items(),
                                    key=lambda kv: int(kv[0])):
                cumulative += count
                self.agent_latency.labels(op=op, bucket=le).set(cumulative)
            self.agent_latency.labels(op=op, bucket="+Inf").set(h["count"])
            # Exemplars: one row per bucket that saw a traced sample —
            # the trace id travels as a label (Prometheus values are
            # numeric), the value is the worst sample's duration.
            for le, ex in h.get("exemplars", {}).items():
                self.agent_exemplar.labels(
                    op=op, bucket=le, trace=ex["trace"]
                ).set(ex["dur_us"])

        # Windowed rates: republished wholesale like the counters —
        # idle series export an explicit 0.0 (a stopped flow must
        # scrape as zero, not silently vanish between resets).
        # goodput.* series split into their own labeled family.
        for name, per_s in timeseries.rates().items():
            scoped = timeseries.split_goodput(name)
            if scoped is not None:
                self.agent_goodput.labels(
                    scope=scoped[0], name=scoped[1]
                ).set(per_s)
            else:
                self.agent_rate.labels(event=name).set(per_s)
        for name, value in timeseries.gauges().items():
            self.agent_gauge.labels(name=name).set(value)

    def _chips_for(self, device_id: str):
        """A physical device ID is a chip (accelN) or a sub-slice (sliceM);
        expand to member chips for per-chip sampling."""
        if device_id.startswith("slice") and self.manager is not None:
            sm = self.manager.subslice_manager
            if sm is not None and device_id in sm._members:
                return [c.name for c in sm._members[device_id]]
            return []
        return [device_id]

    def collect_once(self) -> None:
        now = time.monotonic()
        if now - self._last_reset >= RESET_INTERVAL_S:
            self._reset()
            self._last_reset = now

        try:
            container_devices = self.pod_resources.get_devices_for_all_containers()
        except Exception as e:
            log.warning("pod-resources query failed: %s", e)
            container_devices = {}

        for cid, device_ids in container_devices.items():
            self.request.labels(
                namespace=cid.namespace,
                pod=cid.pod,
                container=cid.container,
                resource_name=TPU_RESOURCE_NAME,
            ).set(len(device_ids))
            for device_id in device_ids:
                for chip in self._chips_for(device_id):
                    try:
                        duty, hbm = self.collector.collect_tpu_device(chip)
                    except Exception as e:
                        log.warning("sampling %s failed: %s", chip, e)
                        continue
                    labels = dict(
                        namespace=cid.namespace,
                        pod=cid.pod,
                        container=cid.container,
                        make=MAKE,
                        accelerator_id=chip,
                        model=self.collector.model(chip),
                    )
                    self.duty_cycle.labels(**labels).set(duty)
                    self.memory_total.labels(**labels).set(hbm.total_bytes)
                    self.memory_used.labels(**labels).set(hbm.used_bytes)

        self._republish_cumulative()

        for chip in self.collector.devices():
            try:
                duty, hbm = self.collector.collect_tpu_device(chip)
            except Exception as e:
                log.warning("sampling %s failed: %s", chip, e)
                continue
            labels = dict(
                make=MAKE, accelerator_id=chip, model=self.collector.model(chip)
            )
            self.duty_cycle_node.labels(**labels).set(duty)
            self.memory_total_node.labels(**labels).set(hbm.total_bytes)
            self.memory_used_node.labels(**labels).set(hbm.used_bytes)
