"""Public re-exports for the metrics package.

``MetricServer`` resolves lazily (PEP 562): the robustness counters in
``metrics.counters`` are stdlib-only and imported at module scope by
utils/ and parallel/, so importing this package must not drag in
prometheus_client/grpc — those load only when the exporter itself is
requested (cmd/tpu_device_plugin.py defers that behind
``--enable-container-tpu-metrics``).
"""
from container_engine_accelerators_tpu.metrics import counters

__all__ = ["MetricServer", "counters"]


def __getattr__(name):
    if name == "MetricServer":
        from container_engine_accelerators_tpu.metrics.metrics import (
            MetricServer,
        )

        return MetricServer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
