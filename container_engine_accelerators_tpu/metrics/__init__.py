"""Public re-exports for the metrics package."""
from container_engine_accelerators_tpu.metrics.metrics import MetricServer

__all__ = ["MetricServer"]
