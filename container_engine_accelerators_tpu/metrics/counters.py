"""Process-wide robustness counters: retries, reconnects, health flips.

The self-healing layer (utils/retry.py, parallel/dcn_client.py,
health/health_checker.py, deviceplugin/manager.py) needs its recovery
behavior to be *observable*, not just tested — an agent that silently
reconnects forty times a minute is a failing node that still looks
green.  Components increment flat named counters here; the MetricServer
exports the snapshot as the ``agent_events{event=...}`` gauge
family next to the duty-cycle/HBM gauges (metrics/metrics.py), so the
same Prometheus scrape that feeds the HPA also shows recovery churn.

Kept dependency-free (stdlib only) so utils/ and parallel/ can import
it without dragging in prometheus_client or grpc.

Every increment also feeds the windowed time-series layer
(obs/timeseries.py), so each counter has a per-second rate over the
trailing window for free — exported as ``agent_rate{event=...}`` next
to the cumulative ``agent_events``.  The cumulative value answers
"how many since boot"; the rate answers "is it happening NOW".

Counter name convention: dotted ``<component>.<event>`` —
``dcn.reconnect.success``, ``health.recovered``, ``retry.exhausted``,
``fault.fired.<site>``.
"""

import threading
from typing import Dict

from container_engine_accelerators_tpu.obs import timeseries

_lock = threading.Lock()
_counters: Dict[str, int] = {}


def inc(name: str, n: int = 1) -> int:
    """Add ``n`` to counter ``name`` (created at 0); returns the new value."""
    timeseries.record(name, n)
    with _lock:
        value = _counters.get(name, 0) + n
        _counters[name] = value
        return value


def get(name: str) -> int:
    with _lock:
        return _counters.get(name, 0)


def snapshot() -> Dict[str, int]:
    """Point-in-time copy of every counter (what the exporter publishes)."""
    with _lock:
        return dict(_counters)


def reset() -> None:
    """Zero everything — test isolation only; production counters are
    cumulative for the life of the agent process."""
    with _lock:
        _counters.clear()
