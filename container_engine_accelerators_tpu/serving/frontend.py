"""ServingFrontend: admission control, batching, hedged retries.

The fleet rig proves bytes survive chaos; this module is the layer
that makes *requests* survive it — the front end production puts
between millions of users and a fleet of accelerator nodes.  It is
robust by construction, not by retry-harder:

- **admission control**: a bounded queue.  A full queue REJECTS
  (``RequestShed``, ``serving.shed``) instead of buffering without
  bound — reject-over-collapse: the requests already admitted keep
  their latency budget, and the queue-depth gauge
  (``serving.queue.depth``) tells the operator load is being turned
  away *before* p99 melts.

- **batching**: a cutter thread groups admitted requests into batches
  of at most ``max_batch``, waiting at most ``max_wait_ms`` for the
  batch to fill — the continuous-batching trade (throughput from
  batching, bounded added latency from the cutter) applied to the
  dispatch path.

- **hedged retries**: each batch dispatches to one node; if no
  response lands by the hedge deadline (``hedge_after_ms``, or
  adaptively the ``hedge_percentile`` of observed attempt latency —
  the tail-at-scale recipe), a backup attempt launches on a SECOND
  node (``serving.hedge.fired``).  First response wins; the loser's
  in-flight work is cancelled cooperatively at its next phase
  boundary, and per-request-id dedup guarantees exactly one delivery
  even when both attempts land (``serving.hedge.won`` /
  ``serving.hedge.wasted``, duplicate results counted as
  ``serving.dedup.dropped``).

- **breakers + failover**: every attempt consults the per-node
  :class:`~container_engine_accelerators_tpu.serving.breaker.
  NodeBreaker`; a node that keeps failing is ejected and probed back
  in.  Within one attempt sequence, failures fail over to the next
  allowed node under a bounded ``attempts`` budget.

The default execution path is a **cross-node shard read** on the DCN
data plane: the batch payload is staged on a shard-home node and
streamed to the serving node through its daemon — every hop rides a
pooled production ``ResilientDcnXferClient``, so daemon kills, rack
partitions, link loss, and slow links exercise this stack end to end.
Tests may inject a ``transfer=`` callable to model slow/failing
backends deterministically.

Every admitted request terminates in exactly one of: a result, an
error, or (at close) a shutdown error — never silently lost, never
delivered twice.  That invariant is what the chaos scenarios gate.
"""

import contextlib
import itertools
import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Callable, Dict, List, Optional

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import histo, timeseries, trace
from container_engine_accelerators_tpu.parallel import dcn
from container_engine_accelerators_tpu.parallel.dcn_client import (
    DcnXferError,
    ResilientDcnXferClient,
)
from container_engine_accelerators_tpu.serving.breaker import NodeBreaker
from container_engine_accelerators_tpu.utils.retry import RetryPolicy

E2E_OP = "serving.e2e"
ATTEMPT_OP = "serving.attempt"


class RequestShed(RuntimeError):
    """Admission rejected the request: the bounded queue is full (or
    the frontend is closing).  The caller backs off or fails fast —
    the frontend never buffers without bound."""


class AttemptCancelled(Exception):
    """This attempt lost the hedge race (or the frontend is closing);
    its in-flight work stops at the next phase boundary."""


class ServingConfig:
    """Frontend knobs.  Scenario specs pass them as the ``serving:``
    mapping (:meth:`from_scenario` — unknown keys are dropped with a
    log line, the TPU_FAULT_SPEC rule)."""

    #: bounded admission queue depth; a full queue sheds
    admission_capacity: int = 64
    #: batch cutter: size and wait ceilings
    max_batch: int = 8
    max_wait_ms: float = 5.0
    #: hedge deadline: fixed ms, or None = adaptive from the observed
    #: ``serving.attempt`` latency percentile (floored)
    hedge_after_ms: Optional[float] = None
    hedge_percentile: float = 0.95
    hedge_floor_ms: float = 50.0
    #: per-batch end-to-end budget; past it every undelivered request
    #: gets a timeout error (terminates — nothing is ever lost)
    request_timeout_s: float = 10.0
    #: per-attempt-sequence failover budget (distinct nodes tried)
    attempts: int = 3
    hedge_attempts: int = 2
    #: breaker: consecutive failures to eject, cooldown before a probe
    breaker_failures: int = 3
    breaker_cooldown_s: float = 1.0
    #: per-node client retry deadline (snappier than the fleet default
    #: — a serving attempt must fail over, not ride a 15 s reconnect)
    client_deadline_s: float = 3.0
    #: concurrent batch dispatches (and 2x this many attempt workers)
    max_inflight_batches: int = 4
    #: land/read timeout for one DCN phase inside an attempt
    land_timeout_s: float = 2.0

    _FIELDS = ("admission_capacity", "max_batch", "max_wait_ms",
               "hedge_after_ms", "hedge_percentile", "hedge_floor_ms",
               "request_timeout_s", "attempts", "hedge_attempts",
               "breaker_failures", "breaker_cooldown_s",
               "client_deadline_s", "max_inflight_batches",
               "land_timeout_s")

    def __init__(self, **kw):
        for field in self._FIELDS:
            setattr(self, field, kw.pop(field, getattr(type(self),
                                                       field)))
        if kw:
            raise TypeError(f"unknown ServingConfig fields: "
                            f"{sorted(kw)}")

    @classmethod
    def from_scenario(cls, raw: Optional[dict]) -> "ServingConfig":
        import logging

        log = logging.getLogger(__name__)
        if raw is None:
            return cls()
        known = {}
        for key, value in dict(raw).items():
            if key in cls._FIELDS:
                known[key] = value
            elif key not in ("requests_per_round", "round_deadline_s"):
                # The two round-pacing keys belong to the controller;
                # anything else is a typo — degrade, don't crash.
                log.error("ignoring unknown serving knob %r", key)
        return cls(**known)


class Request:
    """One admitted request.  Exactly-once delivery by construction:
    the first ``_deliver`` wins, every later one reports False (the
    dedup the hedge race depends on)."""

    __slots__ = ("rid", "payload", "t_submit", "result", "error",
                 "winner", "_done", "_lock")

    def __init__(self, rid: int, payload: bytes, t_submit: float):
        self.rid = rid
        self.payload = payload
        self.t_submit = t_submit
        self.result: Optional[bytes] = None
        self.error: Optional[str] = None
        self.winner: Optional[str] = None
        self._done = threading.Event()
        self._lock = threading.Lock()

    def _deliver(self, result: Optional[bytes], error: Optional[str],
                 role: str) -> bool:
        with self._lock:
            if self._done.is_set():
                return False
            self.result = result
            self.error = error
            self.winner = role
            self._done.set()
        return True

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        """Block until the request terminated (result OR error);
        returns whether it did within the timeout."""
        return self._done.wait(timeout_s)


class _Batch:
    """One cut batch: the dispatch unit.  Holds the concatenated
    payload, per-request slicing, and the hedge race state (winner,
    per-role cancel tokens).  ``ctx`` is the batch's trace context
    (set by the dispatcher's ``serving.batch`` span): every attempt —
    primary AND hedge — attaches to it, so one request's whole
    admit→cut→attempt→hedge story reads as ONE trace."""

    def __init__(self, bid: int, requests: List[Request]):
        self.bid = bid
        self.requests = requests
        self.payload = b"".join(r.payload for r in requests)
        self.t_cut = time.monotonic()
        self.ctx: Optional[dict] = None
        self.hedged = False
        self.winner: Optional[str] = None
        self.errors: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._tokens: Dict[str, threading.Event] = {}

    def cancel_token(self, role: str) -> threading.Event:
        with self._lock:
            return self._tokens.setdefault(role, threading.Event())

    def done(self) -> bool:
        return all(r.done() for r in self.requests)

    def deliver(self, result: bytes, role: str) -> bool:
        """First-response-wins: the first role to deliver claims the
        batch, cancels the losers' tokens, and hands each request its
        slice.  A later delivery returns False (its results are the
        duplicates the request-id dedup exists to drop)."""
        with self._lock:
            if self.winner is not None:
                return False
            self.winner = role
            losers = [tok for r, tok in self._tokens.items()
                      if r != role]
        for tok in losers:
            tok.set()
        now = time.monotonic()
        cur = trace.current()
        tid = cur.trace_id if cur is not None else None
        off = 0
        delivered = 0
        for req in self.requests:
            chunk = result[off:off + len(req.payload)]
            off += len(req.payload)
            if req._deliver(chunk, None, role):
                delivered += 1
                histo.observe(E2E_OP, now - req.t_submit,
                              trace_id=tid)
        if delivered:
            counters.inc("serving.ok", delivered)
        return True

    def record_failure(self, role: str, error: str) -> None:
        with self._lock:
            self.errors[role] = error

    def terminate(self, error: str) -> None:
        """Every attempt is spent (or the budget is): hand every
        still-undelivered request a terminal error — a request may
        fail, it may never be LOST."""
        failed = 0
        for req in self.requests:
            if req._deliver(None, error, "error"):
                failed += 1
        if failed:
            counters.inc("serving.errors", failed)


class ServingFrontend:
    """The fleet-facing request frontend (module docstring has the
    architecture).  ``nodes`` is the fleet's name → node mapping —
    anything EmulatedNode/ProcNode-shaped (``.name``/``.root``/
    ``.down``/``.daemon.data_port``) serves."""

    def __init__(self, nodes: Dict[str, object],
                 config: Optional[ServingConfig] = None,
                 transfer: Optional[Callable] = None):
        self.nodes = nodes
        self.cfg = config or ServingConfig()
        self.breaker = NodeBreaker(
            failures=self.cfg.breaker_failures,
            cooldown_s=self.cfg.breaker_cooldown_s)
        self._transfer = transfer or self._dcn_transfer
        self._admit: "queue.Queue" = queue.Queue(
            maxsize=max(1, int(self.cfg.admission_capacity)))
        self._stop = threading.Event()
        self._rid = itertools.count(1)
        self._bid = itertools.count(1)
        self._fid = itertools.count(1)  # unique flow names per attempt
        self._rr = itertools.count()
        self.node_stats = {name: {"ok": 0, "failed": 0}
                           for name in nodes}
        self._stats_lock = threading.Lock()
        self._client_pool: Dict[str, List] = {}
        self._clients_lock = threading.Lock()
        self._batcher: Optional[threading.Thread] = None
        self._batch_pool: Optional[ThreadPoolExecutor] = None
        self._attempt_pool: Optional[ThreadPoolExecutor] = None
        # Dispatch slots: the cutter takes one BEFORE draining the
        # admission queue and _dispatch gives it back when the batch
        # resolves.  Without this the cutter would drain the bounded
        # queue straight into the executor's unbounded work queue —
        # admission control in name only: submit() would never see
        # Full, nothing would shed, and requests would buffer without
        # bound exactly where the depth gauge can't see them.
        self._slots = threading.BoundedSemaphore(
            max(1, int(self.cfg.max_inflight_batches)))
        # Baseline for the adaptive hedge deadline's percentile
        # (_attempt_percentile_s): this frontend's observations only.
        self._attempt0: Dict[str, int] = dict(
            histo.snapshot().get(ATTEMPT_OP, {}).get("buckets", {}))

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServingFrontend":
        if self._batcher is not None:
            return self
        workers = max(1, int(self.cfg.max_inflight_batches))
        self._batch_pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="serving-batch")
        # Attempts get their own pool: a dispatch thread waiting on
        # its attempt futures must never be the thing those futures
        # are queued behind (the classic same-pool deadlock).
        self._attempt_pool = ThreadPoolExecutor(
            max_workers=2 * workers,
            thread_name_prefix="serving-attempt")
        self._batcher = threading.Thread(
            target=self._batch_loop, name="serving-batcher", daemon=True)
        self._batcher.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._batcher is not None:
            self._batcher.join(timeout=5.0)
        if self._batch_pool is not None:
            self._batch_pool.shutdown(wait=True)
        if self._attempt_pool is not None:
            self._attempt_pool.shutdown(wait=True)
        # Nothing admitted may be lost, even at shutdown: whatever is
        # still queued terminates with a shutdown error.
        self._drain_admit()
        timeseries.gauge("serving.queue.depth", 0.0)
        with self._clients_lock:
            clients = [c for pool in self._client_pool.values()
                       for c in pool]
            self._client_pool.clear()
        for c in clients:
            try:
                c.close()
            except OSError:
                pass

    # -- admission -----------------------------------------------------------

    def _drain_admit(self) -> None:
        """Terminate everything still in the admission queue with a
        shutdown error — errored, never lost."""
        while True:
            try:
                req = self._admit.get_nowait()
            except queue.Empty:
                break
            if req._deliver(None, "frontend closed", "shutdown"):
                counters.inc("serving.errors")

    def submit(self, payload: bytes) -> Request:
        """Admit one request, or shed it.  Sheds raise
        :class:`RequestShed` — the caller hears "not now" immediately
        instead of queueing into a latency cliff."""
        if self._stop.is_set():
            counters.inc("serving.shed")
            raise RequestShed("frontend is closing")
        req = Request(next(self._rid), payload, time.monotonic())
        try:
            self._admit.put_nowait(req)
        except queue.Full:
            counters.inc("serving.shed")
            timeseries.gauge("serving.queue.depth",
                             float(self._admit.qsize()))
            raise RequestShed(
                f"admission queue full "
                f"({self.cfg.admission_capacity})") from None
        counters.inc("serving.requests")
        timeseries.gauge("serving.queue.depth",
                         float(self._admit.qsize()))
        if self._stop.is_set():
            # submit raced close(): the stop check above passed before
            # close() set the flag, and close()'s drain may already
            # have run — a request put after it would sit in a queue
            # nobody reads, silently lost.  Re-drain here (the batcher
            # is stopped, _deliver is first-wins) so it terminates.
            self._drain_admit()
        return req

    # -- batching ------------------------------------------------------------

    def _batch_loop(self) -> None:
        max_wait_s = max(0.0, float(self.cfg.max_wait_ms)) / 1e3
        while not self._stop.is_set():
            # A dispatch slot first, a batch second: with every slot
            # in flight the cutter stalls HERE, admitted requests
            # accumulate in the bounded queue, and the overflow sheds
            # at submit() — backpressure reaches the caller instead of
            # the executor's unbounded queue.
            if not self._slots.acquire(timeout=0.05):
                continue
            try:
                first = self._admit.get(timeout=0.05)
            except queue.Empty:
                self._slots.release()
                continue
            members = [first]
            cut_at = time.monotonic() + max_wait_s
            while len(members) < self.cfg.max_batch:
                remaining = cut_at - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    members.append(self._admit.get(timeout=remaining))
                except queue.Empty:
                    break
            timeseries.gauge("serving.queue.depth",
                             float(self._admit.qsize()))
            counters.inc("serving.batches")
            batch = _Batch(next(self._bid), members)
            try:
                self._batch_pool.submit(self._dispatch, batch)
            except RuntimeError:
                # Pool already shut down (a close racing the cutter's
                # last batch): the slot comes back and every member
                # terminates — errored, never lost.
                self._slots.release()
                batch.terminate("frontend closed")

    # -- dispatch: hedge race ------------------------------------------------

    def _hedge_deadline_s(self) -> float:
        if self.cfg.hedge_after_ms is not None:
            return max(float(self.cfg.hedge_after_ms), 1.0) / 1e3
        floor = max(self.cfg.hedge_floor_ms, 1.0) / 1e3
        # THIS frontend's attempt latencies only: the histogram
        # registry is process-global and cumulative, and a stale slow
        # tail from an earlier run would pin the adaptive deadline at
        # its cap — hedging silently disabled.
        p_us = histo.delta_percentile_us(
            ATTEMPT_OP, self._attempt0, self.cfg.hedge_percentile)
        if p_us is None:
            return floor
        return min(max(p_us / 1e6, floor),
                   self.cfg.request_timeout_s / 2)

    def _record_wait_spans(self, batch: _Batch, bspan) -> None:
        """The admit→cut phases, recorded as completed child spans of
        the batch: per-request ``serving.queue.wait`` (submit → cut,
        measured across threads — no ``with`` block can bracket it)
        and one ``serving.batch.wait`` (cut → dispatch start).  This
        is what lets the critical-path engine answer "was it the
        queue, the cutter, or the attempt?" per request shape."""
        now_mono = time.monotonic()
        now_wall = time.time()
        cut_wall = now_wall - (now_mono - batch.t_cut)
        for req in batch.requests:
            trace.record_span(
                "serving.queue.wait",
                duration_s=batch.t_cut - req.t_submit,
                end_ts=cut_wall,
                trace_id=bspan.trace_id, parent_id=bspan.span_id,
                rid=req.rid)
        trace.record_span(
            "serving.batch.wait",
            duration_s=now_mono - batch.t_cut,
            end_ts=now_wall,
            trace_id=bspan.trace_id, parent_id=bspan.span_id,
            batch=batch.bid)

    def _dispatch(self, batch: _Batch) -> None:
        timeseries.gauge_add("serving.inflight", 1)
        deadline = time.monotonic() + self.cfg.request_timeout_s
        try:
            with trace.span("serving.batch", batch=batch.bid,
                            requests=len(batch.requests),
                            bytes=len(batch.payload)) as bspan:
                batch.ctx = trace.context()
                self._record_wait_spans(batch, bspan)
                primary = self._attempt_pool.submit(
                    self._attempt_seq, batch, "primary", deadline)
                futures = [primary]
                hedge_s = self._hedge_deadline_s()
                try:
                    primary.result(
                        timeout=min(hedge_s,
                                    max(0.0,
                                        deadline - time.monotonic())))
                except _FutureTimeout:
                    if not batch.done():
                        batch.hedged = True
                        counters.inc("serving.hedge.fired")
                        futures.append(self._attempt_pool.submit(
                            self._attempt_seq, batch, "hedge",
                            deadline))
                # Wait the race out: done the moment anything
                # delivers, or every attempt sequence has given up, or
                # the budget is up.
                while (not batch.done()
                       and any(not f.done() for f in futures)
                       and time.monotonic() < deadline):
                    time.sleep(0.002)
                if batch.hedged:
                    if batch.winner == "hedge":
                        counters.inc("serving.hedge.won")
                    elif batch.winner == "primary":
                        counters.inc("serving.hedge.wasted")
                if not batch.done():
                    why = "; ".join(f"{r}: {e}" for r, e
                                    in sorted(batch.errors.items())) \
                        or "request timeout"
                    batch.terminate(f"all attempts failed ({why})")
                bspan.annotate(hedged=batch.hedged,
                               winner=batch.winner)
        except Exception as e:
            # An exception type _attempt_seq doesn't anticipate
            # re-raises out of primary.result() and would skip the
            # terminate fallback above — every request in the batch
            # silently lost, the one outcome the frontend may never
            # produce.  Errored, never lost, whatever the exception.
            batch.terminate(f"internal dispatch error: {e!r}")
        finally:
            timeseries.gauge_add("serving.inflight", -1)
            self._slots.release()

    def _attempt_seq(self, batch: _Batch, role: str,
                     deadline: float) -> bool:
        """One role's bounded failover sequence: try up to
        ``attempts`` (breaker-allowed, preferably distinct) nodes
        until one delivers.  Returns whether this role won.  Attempts
        run on pool threads, so they JOIN the batch's trace
        explicitly (``batch.ctx``): hedge winner and loser share the
        request's trace id, and a cancelled loser's span still closes
        (status ``error``) into the ring — the race leaves no open
        spans behind."""
        ctx = batch.ctx or {}
        with trace.attach(ctx.get("trace"), ctx.get("span")):
            return self._attempt_seq_traced(batch, role, deadline)

    def _attempt_seq_traced(self, batch: _Batch, role: str,
                            deadline: float) -> bool:
        cancel = batch.cancel_token(role)
        budget = (self.cfg.attempts if role == "primary"
                  else self.cfg.hedge_attempts)
        tried: set = set()
        last: Optional[BaseException] = None
        for _ in range(max(1, int(budget))):
            if cancel.is_set() or batch.done() or self._stop.is_set():
                return False
            if time.monotonic() >= deadline:
                break
            node = self._pick_node(exclude=tried)
            if node is None:
                node = self._pick_node(exclude=set())
            if node is None:
                last = DcnXferError("no serving node available "
                                    "(all down or breaker-open)")
                time.sleep(0.05)
                continue
            tried.add(node.name)
            try:
                with trace.span(ATTEMPT_OP, histogram=ATTEMPT_OP,
                                batch=batch.bid, role=role,
                                node=node.name,
                                bytes=len(batch.payload)):
                    result = self._transfer(batch, node, cancel)
                self.breaker.record_success(node.name)
                with self._stats_lock:
                    self.node_stats[node.name]["ok"] += 1
                if not batch.deliver(result, role):
                    # Both attempts landed: the loser's results are
                    # dropped HERE, by the request-id dedup.
                    counters.inc("serving.dedup.dropped")
                return batch.winner == role
            except AttemptCancelled:
                self.breaker.release_probe(node.name)
                return False
            except (DcnXferError, OSError, TimeoutError) as e:
                last = e
                self.breaker.record_failure(node.name)
                with self._stats_lock:
                    self.node_stats[node.name]["failed"] += 1
            except Exception as e:
                # An exception type we didn't anticipate is still a
                # verdict on this attempt: record the failure so a
                # half-open probe slot is never leaked (a leaked slot
                # wedges the node out of dispatch forever — allow()
                # re-grants only on probing=False) and failover
                # continues under the same bounded budget.
                last = e
                self.breaker.record_failure(node.name)
                with self._stats_lock:
                    self.node_stats[node.name]["failed"] += 1
        batch.record_failure(role, str(last) if last else "no attempt")
        return False

    # -- node selection ------------------------------------------------------

    def _pick_node(self, exclude: set):
        """Round-robin over live, breaker-allowed nodes."""
        names = list(self.nodes)
        if not names:
            return None
        start = next(self._rr)
        for k in range(len(names)):
            name = names[(start + k) % len(names)]
            node = self.nodes[name]
            if name in exclude:
                continue
            if getattr(node, "down", False) \
                    or getattr(node, "permanently_down", False):
                continue
            if not self.breaker.allow(name):
                continue
            return node
        return None

    def _shard_home(self, serving_node):
        """The node the serving node reads its shard from: the next
        live node after it in fleet order, so every request crosses a
        node→node link (the DCN fault surface).  A one-node fleet
        reads from itself."""
        names = list(self.nodes)
        idx = names.index(serving_node.name)
        for k in range(1, len(names)):
            cand = self.nodes[names[(idx + k) % len(names)]]
            if not getattr(cand, "down", False) \
                    and not getattr(cand, "permanently_down", False):
                return cand
        return serving_node

    # -- the default execution path: cross-node shard read -------------------

    @contextlib.contextmanager
    def _client(self, node):
        """A pooled per-node ResilientDcnXferClient.  Concurrent
        attempts never share a control socket; a client that saw an
        error is closed instead of re-pooled (its flow table may be
        mid-replay)."""
        c = None
        with self._clients_lock:
            pool = self._client_pool.setdefault(node.name, [])
            if pool:
                c = pool.pop()
        if c is None:
            c = ResilientDcnXferClient(
                os.path.join(node.root, "tpu-dcn"),
                retry=RetryPolicy(
                    max_attempts=4, initial_backoff_s=0.02,
                    max_backoff_s=0.2,
                    deadline_s=self.cfg.client_deadline_s),
            )
        clean = False
        try:
            yield c
            clean = True
        finally:
            if clean:
                with self._clients_lock:
                    self._client_pool.setdefault(node.name,
                                                 []).append(c)
            else:
                try:
                    c.close()
                except OSError:
                    pass

    @staticmethod
    def _check(cancel: threading.Event) -> None:
        if cancel.is_set():
            raise AttemptCancelled()

    def _dcn_transfer(self, batch: _Batch, node,
                      cancel: threading.Event) -> bytes:
        """Execute one batch as a cross-node shard read: stage the
        payload on the shard-home node, stream it to the serving
        node's daemon (through the link table / the proc link shim),
        read it back — the whole resilient client stack under the
        batch.  Cancellation is checked between phases."""
        home = self._shard_home(node)
        flow = f"srv.{batch.bid}.{next(self._fid)}"
        payload = batch.payload
        nbytes = len(payload)
        land_s = self.cfg.land_timeout_s
        if home.name == node.name:
            # One-node fleet (or every other node dark): a local
            # staging round trip — no cross-node leg exists to take.
            with self._client(node) as c:
                c.register_flow(flow, bytes=nbytes)
                try:
                    self._check(cancel)
                    c.put(flow, payload)
                    dcn.wait_flow_rx(c, flow, nbytes,
                                     timeout_s=land_s)
                    got = c.read(flow, nbytes)
                    if got != payload:
                        raise DcnXferError(
                            f"shard read corrupt on {flow}")
                    return got
                finally:
                    try:
                        c.release_flow(flow)
                    except (DcnXferError, OSError):
                        pass
        with self._client(home) as src, self._client(node) as dst:
            dst.register_flow(flow, peer=home.name, bytes=nbytes)
            src.register_flow(flow, peer=node.name, bytes=nbytes)
            try:
                self._check(cancel)
                src.put(flow, payload)
                dcn.wait_flow_rx(src, flow, nbytes, timeout_s=land_s)
                self._check(cancel)
                src.send(flow, "127.0.0.1", node.daemon.data_port,
                         nbytes)
                self._check(cancel)
                dcn.wait_flow_rx(dst, flow, nbytes, timeout_s=land_s)
                got = dst.read(flow, nbytes)
                if got != payload:
                    raise DcnXferError(
                        f"shard read corrupt on {flow}")
                return got
            finally:
                for client in (src, dst):
                    try:
                        client.release_flow(flow)
                    except (DcnXferError, OSError):
                        pass
