"""Serving under chaos: the fleet-facing request frontend.

Everything below the fleet rig moves *bytes*; this package moves
*requests*.  ``cmd/serve_lm.py`` / ``cmd/serve_resnet.py`` model the
per-node model server; what was missing is the layer production puts
in front of a fleet of them — the layer whose whole job is staying up
while nodes die:

- ``serving.frontend``  ServingFrontend: a bounded admission queue
                        with load shedding (reject-over-collapse),
                        request batching with a max-wait/max-size
                        cutter, hedged retries (a backup attempt on a
                        second node after a latency-percentile
                        deadline, first-response-wins with loser
                        cancellation and exactly-once result dedup by
                        request id), and bounded per-attempt failover
                        — all riding per-node
                        ``ResilientDcnXferClient`` pools for the
                        cross-node shard reads, so every DCN fault
                        the rig can inject exercises this stack too;
- ``serving.breaker``   NodeBreaker: the per-node circuit breaker —
                        consecutive failures eject a node from the
                        dispatch set, a cooldown later one probe
                        request is let through, success closes the
                        breaker, failure re-opens it.

The fleet integration (``workload: serving`` scenarios, serving SLOs
``p99_e2e_ms`` / ``min_qps`` / ``max_error_ratio``, chaos gates) lives
in ``fleet/controller.py`` + ``fleet/telemetry.py``; run it with
``python cmd/fleet_sim.py --workload serving`` or ``make fleet-serve``.
"""

from container_engine_accelerators_tpu.serving.breaker import NodeBreaker
from container_engine_accelerators_tpu.serving.frontend import (
    Request,
    RequestShed,
    ServingConfig,
    ServingFrontend,
)

__all__ = [
    "NodeBreaker",
    "Request",
    "RequestShed",
    "ServingConfig",
    "ServingFrontend",
]
