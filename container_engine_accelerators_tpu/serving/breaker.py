"""NodeBreaker: per-node circuit breaker for the serving dispatch set.

A node that fails every request it is handed must stop being handed
requests — retrying into a black hole burns the hedge budget and the
client retry deadlines of every request routed there, which is how one
dead node degrades a whole frontend.  The breaker is the standard
three-state machine, per node:

- **closed**: requests flow; each success clears the consecutive
  failure count, each failure bumps it.  ``failures`` consecutive
  failures trip the breaker (``serving.breaker.open``).
- **open**: the node is ejected from dispatch for ``cooldown_s`` —
  ``allow`` answers False without touching the node.
- **half-open**: after the cooldown, exactly ONE caller is let
  through as a probe (``serving.breaker.probe``); its success closes
  the breaker (``serving.breaker.close``), its failure re-opens it
  for another cooldown.  Concurrent callers during a probe stay
  rejected, so a recovering node sees one request, not a stampede.

The clock is injectable (``clock=``) so the state machine unit-tests
without sleeping; the frontend passes real ``time.monotonic``.  The
number of currently-open breakers is published as the
``serving.breaker.open_nodes`` gauge so ``agent_top`` shows ejections
live.
"""

import threading
import time
from typing import Callable, Dict, Optional

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import timeseries

DEFAULT_FAILURES = 3
DEFAULT_COOLDOWN_S = 1.0

_CLOSED, _OPEN, _HALF_OPEN = "closed", "open", "half-open"


class _State:
    __slots__ = ("state", "fails", "opened_at", "probing")

    def __init__(self):
        self.state = _CLOSED
        self.fails = 0
        self.opened_at = 0.0
        self.probing = False


class NodeBreaker:
    def __init__(self, failures: int = DEFAULT_FAILURES,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 clock: Optional[Callable[[], float]] = None):
        self.failures = max(1, int(failures))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._nodes: Dict[str, _State] = {}

    def _publish_locked(self) -> None:
        timeseries.gauge(
            "serving.breaker.open_nodes",
            float(sum(1 for s in self._nodes.values()
                      if s.state != _CLOSED)))

    def allow(self, node: str) -> bool:
        """May a request be dispatched to ``node`` right now?  An open
        breaker past its cooldown grants exactly one probe."""
        with self._lock:
            st = self._nodes.get(node)
            if st is None or st.state == _CLOSED:
                return True
            if (st.state == _OPEN
                    and self._clock() - st.opened_at
                    >= self.cooldown_s):
                st.state = _HALF_OPEN
                st.probing = True
                counters.inc("serving.breaker.probe")
                return True
            if st.state == _HALF_OPEN and not st.probing:
                # The previous probe was abandoned (its attempt lost
                # the hedge race before reaching the node): grant a
                # fresh one instead of wedging half-open forever.
                st.probing = True
                counters.inc("serving.breaker.probe")
                return True
            return False  # open inside cooldown, or a probe in flight

    def release_probe(self, node: str) -> None:
        """The probe's attempt was cancelled before it could judge the
        node (hedge-race loser, frontend shutdown): give the probe
        slot back without recording a verdict."""
        with self._lock:
            st = self._nodes.get(node)
            if st is not None and st.state == _HALF_OPEN:
                st.probing = False

    def record_success(self, node: str) -> None:
        with self._lock:
            st = self._nodes.get(node)
            if st is None:
                return
            if st.state == _HALF_OPEN:
                counters.inc("serving.breaker.close")
            st.state = _CLOSED
            st.fails = 0
            st.probing = False
            self._publish_locked()

    def record_failure(self, node: str) -> None:
        with self._lock:
            st = self._nodes.setdefault(node, _State())
            if st.state == _HALF_OPEN:
                # The probe failed: straight back to open, fresh
                # cooldown — no stampede through a flapping node.
                st.state = _OPEN
                st.opened_at = self._clock()
                st.probing = False
                counters.inc("serving.breaker.open")
                self._publish_locked()
                return
            st.fails += 1
            if st.state == _CLOSED and st.fails >= self.failures:
                st.state = _OPEN
                st.opened_at = self._clock()
                counters.inc("serving.breaker.open")
                self._publish_locked()

    def state(self, node: str) -> str:
        with self._lock:
            st = self._nodes.get(node)
            return _CLOSED if st is None else st.state

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {
                name: {"state": st.state, "fails": st.fails}
                for name, st in self._nodes.items()
            }
