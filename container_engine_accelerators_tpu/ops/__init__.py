"""Pallas TPU kernels for the hot ops."""
from container_engine_accelerators_tpu.ops.flash_attention import (
    flash_attention,
)

__all__ = ["flash_attention"]
