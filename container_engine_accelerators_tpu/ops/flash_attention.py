"""Flash attention as a Pallas TPU kernel.

The hot op of the transformer workload (models/transformer.py).  XLA's
stock lowering of dense attention materializes the [B, H, T, T] logits
in HBM; this kernel keeps everything in VMEM with the classic online
softmax: for each Q block, stream K/V blocks, track running max ``m``,
denominator ``l`` and unnormalized accumulator in float32, and write
one normalized [BLOCK_Q, D] tile at the end — O(T) HBM traffic instead
of O(T^2).

Layout maps straight onto the hardware: the QK^T and PV products are
MXU matmuls with f32 accumulation (``preferred_element_type``), the
exp/max/rescale chain runs on the VPU, and the causal path skips K
blocks entirely above the diagonal (not just masks them), halving work.

The op is differentiable via ``jax.custom_vjp``: the backward pass
recomputes attention with plain jnp ops (the standard recompute trick —
nothing is saved but q/k/v) and lets XLA differentiate that; forward
speed is where the kernel matters for training steps.

Use :func:`flash_attention` directly, or through
``models/transformer.py`` which selects it automatically on TPU for
tile-aligned shapes.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is unavailable on CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30

BLOCK_Q = 128
BLOCK_K = 128


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, *, causal, scale, block_k):
    """One grid step: q block (i) of one batch*head against all K/V."""
    q_i = pl.program_id(1)
    q = q_ref[0]  # [BQ, D] — keep the input precision: bf16 operands run
    bq, d = q.shape  # the MXU at full rate; accumulation is f32 via
    t = k_ref.shape[1]  # preferred_element_type, and scale applies to the
    nk = t // block_k  # f32 logits afterwards (exact).

    if causal:
        # Blocks strictly above the diagonal contribute nothing: stop at
        # the block containing this Q tile's last row.
        last_row = q_i * bq + (bq - 1)
        nk_run = last_row // block_k + 1
    else:
        nk_run = nk

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, BK] f32
        if causal:
            rows = q_i * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0
            )
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1
            )
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, nk_run, body, (m0, l0, acc0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _fa_forward(q, k, v, causal, scale, interpret):
    """Pallas forward on [B, T, H, D] inputs."""
    b, t, h, d = q.shape

    def to_bh(x):  # [B, T, H, D] -> [B*H, T, D]
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    qf, kf, vf = to_bh(q), to_bh(k), to_bh(v)
    grid = (b * h, t // BLOCK_Q)
    kernel = functools.partial(
        _fa_kernel, causal=causal, scale=scale, block_k=BLOCK_K
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, BLOCK_Q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, t, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, t, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, BLOCK_Q, d), lambda bh, i: (bh, i, 0)),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _dense_ref(q, k, v, causal, scale):
    """Recompute-backward reference: the shared dense_attention numerics
    (parallel/seq.py is the single source of attention math)."""
    from container_engine_accelerators_tpu.parallel.seq import (
        dense_attention,
    )

    return dense_attention(q, k, v, causal=causal, scale=scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal=False, scale=None, interpret=False):
    """Flash attention on [B, T, H, D]; T must be a multiple of 128.

    ``interpret=True`` runs the kernel in the Pallas interpreter
    (hardware-free, used by the test suite).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _fa_forward(q, k, v, causal, scale, interpret)


def _fa_fwd(q, k, v, causal, scale, interpret):
    return flash_attention(q, k, v, causal, scale, interpret), (q, k, v)


def _fa_bwd(causal, scale, interpret, res, g):
    q, k, v = res
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    _, vjp = jax.vjp(
        lambda q, k, v: _dense_ref(q, k, v, causal, scale), q, k, v
    )
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def supports_flash(t: int, d: int) -> bool:
    """Tile-alignment gate used by callers choosing a fast path."""
    return t % BLOCK_Q == 0 and t >= BLOCK_Q and d in (64, 128, 256)
