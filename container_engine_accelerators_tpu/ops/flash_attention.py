"""Flash attention as a Pallas TPU kernel.

The hot op of the transformer workload (models/transformer.py).  XLA's
stock lowering of dense attention materializes the [B, H, T, T] logits
in HBM; this kernel keeps everything in VMEM with the classic online
softmax: for each Q block, stream K/V blocks, track running max ``m``,
denominator ``l`` and unnormalized accumulator in float32, and write
one normalized [BLOCK_Q, D] tile at the end — O(T) HBM traffic instead
of O(T^2).

Layout maps straight onto the hardware: the QK^T and PV products are
MXU matmuls with f32 accumulation (``preferred_element_type``), the
exp/max/rescale chain runs on the VPU, and the causal path skips K
blocks entirely above the diagonal (not just masks them), halving work.

The op is differentiable via ``jax.custom_vjp`` with Pallas **backward
kernels** (FlashAttention-2 style): the forward additionally saves the
per-row logsumexp; the backward recomputes attention probabilities
*inside VMEM per block* from (q, k, v, lse) — never materializing the
O(T^2) logits in HBM — in two passes: a dQ kernel (grid over Q blocks,
streaming K/V) and a dK/dV kernel (grid over K blocks, streaming Q/dO).
Both skip fully-masked blocks under causal attention rather than
masking them.

Use :func:`flash_attention` directly, or through
``models/transformer.py`` which selects it automatically on TPU for
tile-aligned shapes.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is unavailable on CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30

BLOCK_Q = 128
BLOCK_K = 128


def _mosaic_params(interpret):
    """Grid iterations of every kernel here are independent (each writes
    its own output block), so tell Mosaic both grid dims are parallel —
    it can then overlap DMA and compute across iterations instead of
    assuming a sequential carry.  None in interpret mode / CPU builds."""
    if interpret or pltpu is None:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel")
    )

# Mosaic requires the last two dims of every block to be (8k, 128k) or
# equal to the array dims, so per-row scalars (the logsumexp) cannot be
# stored as a [.., T] array with [.., BLOCK_Q] blocks.  Like the stock
# JAX TPU kernel (pallas/ops/tpu/flash_attention.py, MIN_BLOCK_SIZE),
# lse is carried as [B*H, T, LANES] with the scalar broadcast across a
# full 128-lane vector register.
LSE_LANES = 128


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, scale,
               block_k):
    """One grid step: q block (i) of one batch*head against all K/V."""
    q_i = pl.program_id(1)
    q = q_ref[0]  # [BQ, D] — keep the input precision: bf16 operands run
    bq, d = q.shape  # the MXU at full rate; accumulation is f32 via
    t = k_ref.shape[1]  # preferred_element_type, and scale applies to the
    nk = t // block_k  # f32 logits afterwards (exact).

    if causal:
        # Blocks strictly above the diagonal contribute nothing: stop at
        # the block containing this Q tile's last row.
        last_row = q_i * bq + (bq - 1)
        nk_run = last_row // block_k + 1
    else:
        nk_run = nk

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, BK] f32
        if causal:
            rows = q_i * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0
            )
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1
            )
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nk_run, body, (m0, l0, acc0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[0] = jax.lax.broadcast_in_dim(
        m + jnp.log(l), (bq, LSE_LANES), (0,)
    )


def _to_bh(x):  # [B, T, H, D] -> [B*H, T, D]
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _from_bh(x, b, h):  # [B*H, T, D] -> [B, T, H, D]
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _fa_forward(q, k, v, causal, scale, interpret, block_q, block_k):
    """Pallas forward on [B, T, H, D] -> (out, lse [B*H, T, LSE_LANES])."""
    b, t, h, d = q.shape
    qf, kf, vf = _to_bh(q), _to_bh(k), _to_bh(v)
    grid = (b * h, t // block_q)
    kernel = functools.partial(
        _fa_kernel, causal=causal, scale=scale, block_k=block_k
    )
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, t, LSE_LANES), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, t, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, t, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_q, LSE_LANES), lambda bh, i: (bh, i, 0)),
        ),
        interpret=interpret,
        compiler_params=_mosaic_params(interpret),
    )(qf, kf, vf)
    return _from_bh(out, b, h), lse


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref,
                      *, causal, scale, block_k):
    """dQ for one Q block: stream K/V blocks, recompute P from lse.

    ds = P * (dP - delta) * scale with dP = dO V^T and
    delta_i = dO_i . O_i; dQ = ds K — all products MXU matmuls with f32
    accumulation, P/ds cast to the input dtype for full-rate MXU.
    """
    q_i = pl.program_id(1)
    q = q_ref[0]  # [BQ, D]
    do = do_ref[0]
    o = o_ref[0]
    # lse arrives lane-broadcast [BQ, LSE_LANES]; keep one lane as a
    # [BQ, 1] column so later uses broadcast against [BQ, BK].
    lse = lse_ref[0][:, :1]  # [BQ, 1] f32
    bq, d = q.shape
    t = k_ref.shape[1]
    nk = t // block_k

    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=1
    )  # [BQ]

    if causal:
        last_row = q_i * bq + (bq - 1)
        nk_run = last_row // block_k + 1
    else:
        nk_run = nk

    def body(j, dq_acc):
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, BK] f32
        if causal:
            rows = q_i * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0
            )
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1
            )
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)  # [BQ, BK] f32; 0 where masked
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, BK]
        ds = p * (dp - delta[:, None]) * scale
        return dq_acc + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq0 = jnp.zeros((bq, d), jnp.float32)
    dq = jax.lax.fori_loop(0, nk_run, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, o_ref, lse_ref,
                       dk_ref, dv_ref, *, causal, scale, block_q):
    """dK/dV for one K block: stream Q/dO/O/lse blocks.

    dV = P^T dO; dK = ds^T Q.  Under causal attention, Q blocks strictly
    above this K block's diagonal are skipped (their P column-block is
    all zero), so the loop starts at the diagonal.
    """
    k_j = pl.program_id(1)
    k = k_ref[0]  # [BK, D]
    v = v_ref[0]
    bk, d = k.shape
    t = q_ref.shape[1]
    nq = t // block_q

    start = (k_j * bk) // block_q if causal else 0

    def body(i, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, pl.ds(i * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(i * block_q, block_q), :]
        o_blk = o_ref[0, pl.ds(i * block_q, block_q), :]
        # [BQ, 1] column of the lane-broadcast lse block.
        lse_blk = lse_ref[0, pl.ds(i * block_q, block_q), :][:, :1]
        delta = jnp.sum(
            do_blk.astype(jnp.float32) * o_blk.astype(jnp.float32), axis=1
        )  # [BQ]
        s = scale * jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, BK] f32
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0
            )
            cols = k_j * bk + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1
            )
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse_blk)  # [BQ, BK]
        dv_acc = dv_acc + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BK, D]
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, BK]
        ds = p * (dp - delta[:, None]) * scale
        dk_acc = dk_acc + jax.lax.dot_general(
            ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BK, D]
        return dk_acc, dv_acc

    z = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, nq, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _fa_backward(q, k, v, o, lse, g, causal, scale, interpret, block_q,
                 block_k):
    """Pallas backward on [B,T,H,D] primals; lse is [B*H,T,LSE_LANES]."""
    b, t, h, d = q.shape
    qf, kf, vf = _to_bh(q), _to_bh(k), _to_bh(v)
    of, gf = _to_bh(o), _to_bh(g)

    full = pl.BlockSpec((1, t, d), lambda bh, i: (bh, 0, 0))
    blk_q = pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0))
    blk_k = pl.BlockSpec((1, block_k, d), lambda bh, i: (bh, i, 0))
    lse_full = pl.BlockSpec((1, t, LSE_LANES), lambda bh, i: (bh, 0, 0))
    lse_blk = pl.BlockSpec((1, block_q, LSE_LANES), lambda bh, i: (bh, i, 0))

    dq = pl.pallas_call(
        functools.partial(
            _fa_bwd_dq_kernel, causal=causal, scale=scale, block_k=block_k
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        grid=(b * h, t // block_q),
        in_specs=[blk_q, full, full, blk_q, blk_q, lse_blk],
        out_specs=blk_q,
        interpret=interpret,
        compiler_params=_mosaic_params(interpret),
    )(qf, kf, vf, gf, of, lse)

    dk, dv = pl.pallas_call(
        functools.partial(
            _fa_bwd_dkv_kernel, causal=causal, scale=scale,
            block_q=block_q,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, t, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t, d), v.dtype),
        ),
        grid=(b * h, t // block_k),
        in_specs=[blk_k, blk_k, full, full, full, lse_full],
        out_specs=(blk_k, blk_k),
        interpret=interpret,
        compiler_params=_mosaic_params(interpret),
    )(kf, vf, qf, gf, of, lse)

    return (
        _from_bh(dq, b, h),
        _from_bh(dk, b, h),
        _from_bh(dv, b, h),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, scale=None, interpret=False,
                    block_q=None, block_k=None):
    """Flash attention on [B, T, H, D]; T must be a multiple of 128.

    ``interpret=True`` runs the kernels in the Pallas interpreter
    (hardware-free, used by the test suite).  ``block_q``/``block_k``
    override the Q/K tile sizes (defaults BLOCK_Q/BLOCK_K); T must be a
    multiple of both.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    out, _ = _fa_forward(q, k, v, causal, scale, interpret,
                         block_q or BLOCK_Q, block_k or BLOCK_K)
    return out


def _fa_fwd(q, k, v, causal, scale, interpret, block_q, block_k):
    scale_ = scale if scale is not None else q.shape[-1] ** -0.5
    out, lse = _fa_forward(q, k, v, causal, scale_, interpret,
                           block_q or BLOCK_Q, block_k or BLOCK_K)
    # The lane-broadcast lse is 128 identical copies; keep only one lane
    # in the residual so HBM held from forward to backward is [B*H, T]
    # f32, not 128x that.  The backward re-broadcasts just-in-time.
    return out, (q, k, v, out, lse[..., 0])


def _fa_bwd(causal, scale, interpret, block_q, block_k, res, g):
    q, k, v, o, lse = res
    scale_ = scale if scale is not None else q.shape[-1] ** -0.5
    lse_lanes = jnp.broadcast_to(lse[..., None], (*lse.shape, LSE_LANES))
    return _fa_backward(q, k, v, o, lse_lanes, g, causal, scale_,
                        interpret, block_q or BLOCK_Q, block_k or BLOCK_K)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def supports_flash(t: int, d: int) -> bool:
    """Tile-alignment gate used by callers choosing a fast path."""
    return t % BLOCK_Q == 0 and t >= BLOCK_Q and d in (64, 128, 256)
