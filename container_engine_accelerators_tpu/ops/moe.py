"""Mixture-of-Experts FFN with expert parallelism — TPU-first.

Switch-Transformer-style top-1 routing, built the way TPUs want it: the
dispatch and combine are **dense one-hot einsums** (MXU work, static
shapes) rather than scatters/gathers, so the whole layer jits into a
few batched matmuls.  Expert parallelism is pure GSPMD: shard the
leading expert axis of the expert weights (``expert_sharding``) and XLA
inserts the all-to-all that moves token slots to their experts — no
hand-written collectives, same recipe as the sharding of ``mesh.py``.

Capacity semantics: in training, each expert processes at most
``ceil(capacity_factor * N / E)`` token slots; overflow tokens fall
through the residual (their combine weight is zero), the standard
Switch trade that keeps every shape static for XLA.  Inference/decode
(``no_drop=True``, set by the decode path) routes every token —
capacity = N — because capacity that depends on the token count would
make single-token KV-cache steps drop differently than full forwards.

The reference has no model-code analog (its scaling is infrastructure,
SURVEY.md §2.3); this rounds out the parallelism layer's ep axis next
to dp/tp (mesh.py), sp (seq.py), and pp (pipeline.py).
"""

import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from container_engine_accelerators_tpu.parallel.mesh import MODEL_AXIS


class MoEFFN(nn.Module):
    """Top-1 (Switch) MoE feed-forward: [..., D] -> [..., D].

    ``num_experts`` gated SiLU MLPs; router in f32 for stable softmax.
    Returns (output, aux_loss) where aux_loss is the Switch load-balance
    loss (mean over experts of fraction_routed * mean_gate, scaled by E).
    """

    num_experts: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    capacity_factor: float = 1.25
    # Drop-free routing (capacity = N): inference/decode mode.  Train
    # capacity depends on the token count, so a KV-cache decode step
    # (N = batch) and a full forward (N = batch*T) would drop different
    # tokens and diverge; serving routes every token instead — the
    # decode path sets this (transformer.py Block).
    no_drop: bool = False

    @nn.compact
    def __call__(self, x):
        *lead, d = x.shape
        n = math.prod(lead)
        e = self.num_experts
        capacity = (
            n if self.no_drop
            else max(1, math.ceil(self.capacity_factor * n / e))
        )
        flat = x.reshape(n, d)

        # Router (f32): top-1 expert and its gate probability.
        logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, name="router"
        )(flat.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)  # [N, E]
        expert_idx = jnp.argmax(probs, axis=-1)  # [N]
        gate = jnp.max(probs, axis=-1)  # [N]
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [N, E]

        if self.no_drop:
            # Drop-free: no slot competition, so the slot index IS the
            # token index — a LINEAR [E, N, D] dispatch (rows for
            # non-routed experts are zero and their MLP output is
            # zero).  The capacity form below would build quadratic
            # [N, E, N] dispatch/combine tensors here for nothing.
            dispatch = None
            slots = jnp.einsum(
                "ne,nd->end", onehot.astype(self.dtype),
                flat.astype(self.dtype),
            )  # [E, N, D]
        else:
            # Capacity: position of each token within its expert's
            # queue; tokens past the capacity drop out of the combine
            # (residual carries them).  cumsum keeps it a static-shape
            # VPU op.
            pos = jnp.einsum(
                "ne,ne->n", onehot, jnp.cumsum(onehot, axis=0) - 1.0
            ).astype(jnp.int32)
            keep = pos < capacity
            pos_oh = jax.nn.one_hot(
                pos, capacity, dtype=jnp.float32
            )  # [N, C]
            dispatch = (
                onehot[:, :, None] * pos_oh[:, None, :]
                * keep[:, None, None]
            )  # [N, E, C]

            # Move token slots to experts: dense einsum; under expert-
            # sharded weights GSPMD turns this into the all-to-all.
            slots = jnp.einsum(
                "nec,nd->ecd", dispatch.astype(self.dtype),
                flat.astype(self.dtype),
            )  # [E, C, D]

        wi_gate = self.param(
            "wi_gate", nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, d, self.mlp_dim), jnp.float32,
        )
        wi_up = self.param(
            "wi_up", nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, d, self.mlp_dim), jnp.float32,
        )
        wo = self.param(
            "wo", nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, self.mlp_dim, d), jnp.float32,
        )
        h = nn.silu(
            jnp.einsum("ecd,edh->ech", slots, wi_gate.astype(self.dtype))
        ) * jnp.einsum("ecd,edh->ech", slots, wi_up.astype(self.dtype))
        out_slots = jnp.einsum(
            "ech,ehd->ecd", h, wo.astype(self.dtype)
        )  # [E, C, D]

        if self.no_drop:
            combine = (onehot * gate[:, None]).astype(self.dtype)  # [N, E]
            out = jnp.einsum("ne,end->nd", combine, out_slots)
        else:
            combine = dispatch * gate[:, None, None]  # [N, E, C]
            out = jnp.einsum(
                "nec,ecd->nd", combine.astype(self.dtype), out_slots
            )

        # Switch load-balance aux loss (f32).
        frac_routed = jnp.mean(onehot, axis=0)  # [E]
        mean_gate = jnp.mean(probs, axis=0)  # [E]
        aux = e * jnp.sum(frac_routed * mean_gate)

        return out.reshape(*lead, d).astype(self.dtype), aux


def expert_sharding(mesh: Mesh, params, axis: str = MODEL_AXIS):
    """NamedShardings placing each MoE weight's leading expert axis on
    ``axis`` (expert parallelism); router weights replicate."""

    def spec(path, x):
        name = "/".join(str(p) for p in path)
        if x.ndim == 3 and "router" not in name:
            return NamedSharding(mesh, P(axis))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(spec, params)
