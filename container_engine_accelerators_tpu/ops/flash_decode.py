"""Flash decode: single-query attention over a long KV cache (Pallas).

The serving decode step attends ONE query token per sequence over the
whole cached context.  The XLA path (models/transformer.py
``_decode_attend``) computes masked scores over the full fixed-length
buffer — fine at short contexts, but at long ones it streams the dead
tail of the buffer through the VPU and materializes [B, H, 1, L]
logits.  This kernel is the long-context replacement:

- streams the cache in ``block_k`` chunks with the classic online
  softmax (running max / denominator / accumulator in f32 VMEM
  scratch), writing one [H, D] tile per sequence at the end;
- **skips** chunks entirely beyond the sequence's visible length
  (``pl.when`` on the block start) instead of masking them — the
  savings scale with buffer slack, exactly the regime bucketed
  serving creates;
- handles GQA natively: the cache keeps ``KVH`` heads and the query's
  ``KVH x G`` grouping is computed in-kernel — no repeated K/V pass,
  matching ``_decode_attend``'s grouped einsums;
- keeps the cache in its storage layout [B, L, KVH, D] (blocks carry
  all KV heads, so no transpose copy per step).

Correctness contract (tests/test_flash_decode.py): matches
``_decode_attend``'s masked-einsum math to f32-accumulation tolerance
for every (length, GQA group, block) combination, via interpret mode
on CPU.

Like ops/flash_attention.py, the kernel has no GSPMD partition rule:
single-chip decode only (the tensor-parallel path keeps XLA einsums).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is unavailable on CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30
BLOCK_K = 512


def effective_block_k(cache_len: int, block_k: int = BLOCK_K) -> int:
    """Largest divisor of ``cache_len`` that is <= ``block_k``.

    Any cache length works (a serving cache is bucket + max_new, not
    necessarily a multiple of 512); the bench's roofline math uses the
    same value to model the kernel's block-granular reads."""
    for bk in range(min(block_k, cache_len), 0, -1):
        if cache_len % bk == 0:
            return bk
    return 1  # pragma: no cover — bk=1 always divides
# Mosaic needs the last two block dims (8k, 128k) or equal to the array
# dims; a per-sequence scalar therefore rides as an [8, 128] f32 tile.
_SCALAR_TILE = (8, 128)


def _fd_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, a_scr,
               *, block_k, scale):
    """One grid step: K/V chunk ``kb`` of sequence ``b``, all heads."""
    kb = pl.program_id(1)
    nk = pl.num_programs(1)
    length = len_ref[0, 0, 0].astype(jnp.int32)  # visible keys in [0, L]

    @pl.when(kb == 0)
    def init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        a_scr[...] = jnp.zeros_like(a_scr)

    @pl.when(kb * block_k < length)
    def attend():
        q = q_ref[0]  # [KVH, G, D] — input precision feeds the MXU
        k = k_ref[0]  # [BK, KVH, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k,
            (((2,), (2,)), ((0,), (1,))),  # contract D; batch KVH
            preferred_element_type=jnp.float32,
        ) * scale  # [KVH, G, BK]
        slot = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 2
        )
        s = jnp.where(slot < length, s, NEG_INF)

        m_prev = m_scr[...]  # [KVH, G]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :, None])  # [KVH, G, BK]
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=2)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v,
            (((2,), (0,)), ((0,), (1,))),  # contract BK; batch KVH
            preferred_element_type=jnp.float32,
        )  # [KVH, G, D]
        a_scr[...] = a_scr[...] * alpha[:, :, None] + pv
        m_scr[...] = m_new

    @pl.when(kb == nk - 1)
    def finalize():
        o_ref[0] = (
            a_scr[...] / l_scr[...][:, :, None]
        ).astype(o_ref.dtype)


def flash_decode(q, k_cache, v_cache, lengths, *, scale=None,
                 block_k=BLOCK_K, interpret=False):
    """Single-token attention over a KV cache.

    q: [B, H, D]; k_cache/v_cache: [B, L, KVH, D] (H = KVH * G, query
    head ``kv*G + j`` reads KV head ``kv`` — the grouping of
    models/transformer.py); lengths: [B] visible keys per sequence
    (key slot j participates iff j < lengths[b]).  Returns [B, H, D].
    """
    if pltpu is None:  # pragma: no cover — pallas TPU always importable here
        raise NotImplementedError(
            "flash_decode needs jax.experimental.pallas.tpu"
        )
    b, h, d = q.shape
    _, cache_len, kvh, _ = k_cache.shape
    if h % kvh:
        raise ValueError(f"H={h} not divisible by KVH={kvh}")
    g = h // kvh
    block_k = effective_block_k(cache_len, block_k)
    scale = d ** -0.5 if scale is None else scale

    qg = q.reshape(b, kvh, g, d)
    lens = jnp.broadcast_to(
        lengths.astype(jnp.float32)[:, None, None],
        (b,) + _SCALAR_TILE,
    )
    nk = cache_len // block_k
    grid = (b, nk)
    out = pl.pallas_call(
        functools.partial(_fd_kernel, block_k=block_k, scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,) + _SCALAR_TILE, lambda b_, k_: (b_, 0, 0)),
            pl.BlockSpec((1, kvh, g, d), lambda b_, k_: (b_, 0, 0, 0)),
            pl.BlockSpec((1, block_k, kvh, d),
                         lambda b_, k_: (b_, k_, 0, 0)),
            pl.BlockSpec((1, block_k, kvh, d),
                         lambda b_, k_: (b_, k_, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, kvh, g, d), lambda b_, k_: (b_, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, kvh, g, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((kvh, g), jnp.float32),
            pltpu.VMEM((kvh, g), jnp.float32),
            pltpu.VMEM((kvh, g, d), jnp.float32),
        ],
        compiler_params=(
            None if (interpret or pltpu is None)
            else pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")
            )
        ),
        interpret=interpret,
    )(lens, qg, k_cache, v_cache)
    return out.reshape(b, h, d)
