"""DevicePlugin v1beta1 gRPC service implementation.

TPU-native port of the reference's pluginServiceV1Beta1
(ref: pkg/gpu/nvidia/beta_plugin.go:35-103): ListAndWatch streams the
device list and re-sends it on every health transition; Allocate validates
sharing, maps device IDs to device nodes, and attaches default devices,
library mounts, and the env contract.  PreStartContainer stays a logged
no-op like the reference's (beta_plugin.go:95-103), but — unlike the
reference, whose host GPUs are interchangeable — GetPreferredAllocation
is REAL here: TPU chips sit on an ICI mesh, so the plugin opts into the
kubelet hook and returns ICI-aware picks (deviceplugin/preferred.py).
"""

import logging
import queue

import grpc

from container_engine_accelerators_tpu.deviceplugin import (
    deviceplugin_v1beta1_pb2 as pb,
)
from container_engine_accelerators_tpu.obs import trace
from container_engine_accelerators_tpu.sharing import validate_request

log = logging.getLogger(__name__)

_HEALTH_POLL_S = 0.5


class DevicePluginService:
    def __init__(self, manager):
        self.manager = manager

    # -- small RPCs ----------------------------------------------------------

    def GetDevicePluginOptions(self, request, context):
        # Unlike the reference (beta_plugin.go:95-103, a no-op because host
        # GPUs are interchangeable), TPU chips sit on an ICI mesh, so the
        # plugin opts into the kubelet's preferred-allocation hook.
        return pb.DevicePluginOptions(get_preferred_allocation_available=True)

    def PreStartContainer(self, request, context):
        log.error(
            "device-plugin: PreStart should NOT be called for the GKE TPU "
            "device plugin"
        )
        return pb.PreStartContainerResponse()

    def GetPreferredAllocation(self, request, context):
        resp = pb.PreferredAllocationResponse()
        for creq in request.container_requests:
            ids = self.manager.preferred_allocation(
                list(creq.available_deviceIDs),
                list(creq.must_include_deviceIDs),
                creq.allocation_size,
            )
            log.info(
                "device-plugin: preferred allocation of %d from %d "
                "available: %s",
                creq.allocation_size, len(creq.available_deviceIDs), ids,
            )
            resp.container_responses.add().deviceIDs.extend(ids)
        return resp

    # -- ListAndWatch --------------------------------------------------------

    def _device_list_response(self) -> pb.ListAndWatchResponse:
        resp = pb.ListAndWatchResponse()
        for dev in self.manager.list_devices().values():
            resp.devices.append(pb.Device(ID=dev.id, health=dev.health))
        return resp

    def ListAndWatch(self, request, context):
        log.info("device-plugin: ListAndWatch start")
        yield self._device_list_response()
        while context.is_active():
            try:
                d = self.manager.health_events.get(timeout=_HEALTH_POLL_S)
            except queue.Empty:
                continue
            log.info("device-plugin: %s device marked as %s", d.id, d.health)
            # The re-announce latency the kubelet actually experiences:
            # applying the transition + rebuilding the device list.
            with trace.span("plugin.health_announce",
                            histogram="plugin.health_announce",
                            device=d.id, health=d.health):
                self.manager.set_device_health(d.id, d.health)
                resp = self._device_list_response()
            yield resp

    # -- Allocate ------------------------------------------------------------

    def Allocate(self, request, context):
        resps = pb.AllocateResponse()
        for rqt in request.container_requests:
            with trace.span("plugin.allocate",
                            histogram="plugin.allocate",
                            devices=len(rqt.devicesIDs)):
                self._allocate_one(rqt, resps, context)
        return resps

    def _allocate_one(self, rqt, resps, context):
        try:
            self.manager.verify_allocatable()
            validate_request(
                list(rqt.devicesIDs),
                len(self.manager.list_physical_devices()),
                self.manager.config.sharing.strategy,
            )
            resp = pb.ContainerAllocateResponse()
            seen_nodes = set()
            for device_id in rqt.devicesIDs:
                for spec in self.manager.device_spec(device_id):
                    # Multiple vtpus / sub-slices can map to the same
                    # node; inject each node once.
                    if spec.host_path in seen_nodes:
                        continue
                    seen_nodes.add(spec.host_path)
                    resp.devices.append(
                        pb.DeviceSpec(
                            host_path=spec.host_path,
                            container_path=spec.container_path,
                            permissions=spec.permissions,
                        )
                    )
            for d in self.manager.default_devices:
                resp.devices.append(
                    pb.DeviceSpec(
                        host_path=d, container_path=d, permissions="mrw"
                    )
                )
            for m in self.manager.mount_paths:
                resp.mounts.append(
                    pb.Mount(
                        host_path=m.host_path,
                        container_path=m.container_path,
                        read_only=m.read_only,
                    )
                )
            for k, v in self.manager.envs(list(rqt.devicesIDs)).items():
                resp.envs[k] = v
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        resps.container_responses.append(resp)
