"""kubelet DevicePlugin v1beta1 gRPC wiring.

grpc_tools is not available in this image, so the service layer is wired by
hand with grpcio generic handlers around the protoc-generated message
classes (deviceplugin_v1beta1_pb2).  Method paths and wire format match the
kubelet exactly; the reference gets the same surface from Go codegen
(ref: pkg/gpu/nvidia/beta_plugin.go:35-131).
"""

import grpc

from container_engine_accelerators_tpu.deviceplugin import (
    deviceplugin_v1beta1_pb2 as pb,
)

# kubelet constants (k8s.io/kubelet/pkg/apis/deviceplugin/v1beta1/constants.go)
DEVICE_PLUGIN_PATH = "/var/lib/kubelet/device-plugins/"
KUBELET_SOCKET = "kubelet.sock"
API_VERSION = "v1beta1"
HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"

_DEVICE_PLUGIN_SERVICE = "v1beta1.DevicePlugin"
_REGISTRATION_SERVICE = "v1beta1.Registration"


# ---- server-side wiring ----------------------------------------------------


def add_device_plugin_servicer(server: grpc.Server, servicer) -> None:
    """Register a DevicePlugin servicer (methods: GetDevicePluginOptions,
    ListAndWatch, GetPreferredAllocation, Allocate, PreStartContainer)."""
    handlers = {
        "GetDevicePluginOptions": grpc.unary_unary_rpc_method_handler(
            servicer.GetDevicePluginOptions,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.DevicePluginOptions.SerializeToString,
        ),
        "ListAndWatch": grpc.unary_stream_rpc_method_handler(
            servicer.ListAndWatch,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.ListAndWatchResponse.SerializeToString,
        ),
        "GetPreferredAllocation": grpc.unary_unary_rpc_method_handler(
            servicer.GetPreferredAllocation,
            request_deserializer=pb.PreferredAllocationRequest.FromString,
            response_serializer=pb.PreferredAllocationResponse.SerializeToString,
        ),
        "Allocate": grpc.unary_unary_rpc_method_handler(
            servicer.Allocate,
            request_deserializer=pb.AllocateRequest.FromString,
            response_serializer=pb.AllocateResponse.SerializeToString,
        ),
        "PreStartContainer": grpc.unary_unary_rpc_method_handler(
            servicer.PreStartContainer,
            request_deserializer=pb.PreStartContainerRequest.FromString,
            response_serializer=pb.PreStartContainerResponse.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_DEVICE_PLUGIN_SERVICE, handlers),)
    )


def add_registration_servicer(server: grpc.Server, servicer) -> None:
    """Register a kubelet Registration servicer (used by the KubeletStub in
    tests, mirroring beta_plugin_test.go:35-69)."""
    handlers = {
        "Register": grpc.unary_unary_rpc_method_handler(
            servicer.Register,
            request_deserializer=pb.RegisterRequest.FromString,
            response_serializer=pb.Empty.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_REGISTRATION_SERVICE, handlers),)
    )


# ---- client-side wiring ----------------------------------------------------


class DevicePluginClient:
    """Client stub for the DevicePlugin service (kubelet's role)."""

    def __init__(self, channel: grpc.Channel):
        p = f"/{_DEVICE_PLUGIN_SERVICE}/"
        self.get_device_plugin_options = channel.unary_unary(
            p + "GetDevicePluginOptions",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.DevicePluginOptions.FromString,
        )
        self.list_and_watch = channel.unary_stream(
            p + "ListAndWatch",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.ListAndWatchResponse.FromString,
        )
        self.allocate = channel.unary_unary(
            p + "Allocate",
            request_serializer=pb.AllocateRequest.SerializeToString,
            response_deserializer=pb.AllocateResponse.FromString,
        )
        self.get_preferred_allocation = channel.unary_unary(
            p + "GetPreferredAllocation",
            request_serializer=pb.PreferredAllocationRequest.SerializeToString,
            response_deserializer=pb.PreferredAllocationResponse.FromString,
        )
        self.pre_start_container = channel.unary_unary(
            p + "PreStartContainer",
            request_serializer=pb.PreStartContainerRequest.SerializeToString,
            response_deserializer=pb.PreStartContainerResponse.FromString,
        )


class RegistrationClient:
    """Client stub for the kubelet Registration service (plugin's role)."""

    def __init__(self, channel: grpc.Channel):
        self.register = channel.unary_unary(
            f"/{_REGISTRATION_SERVICE}/Register",
            request_serializer=pb.RegisterRequest.SerializeToString,
            response_deserializer=pb.Empty.FromString,
        )


def register_with_v1beta1_kubelet(
    kubelet_endpoint: str, plugin_endpoint: str, resource_name: str
) -> None:
    """Dial kubelet.sock and Register (ref: beta_plugin.go:110-131)."""
    with grpc.insecure_channel(f"unix:{kubelet_endpoint}") as channel:
        grpc.channel_ready_future(channel).result(timeout=10)
        client = RegistrationClient(channel)
        client.register(
            pb.RegisterRequest(
                version=API_VERSION,
                endpoint=plugin_endpoint,
                resource_name=resource_name,
                # The kubelet only calls GetPreferredAllocation when the
                # registration advertises it.
                options=pb.DevicePluginOptions(
                    get_preferred_allocation_available=True
                ),
            ),
            timeout=10,
        )
