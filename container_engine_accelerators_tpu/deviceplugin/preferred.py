"""Topology-aware preferred allocation — ICI-contiguous chip sets.

The reference explicitly no-ops GetPreferredAllocation
(ref: pkg/gpu/nvidia/beta_plugin.go:95-103) because PCIe GPUs on one
host are interchangeable.  TPU chips are NOT: they sit on an ICI mesh,
and a workload spanning chips that are mesh-adjacent gets full ICI
bandwidth while a scattered set hops through intermediate chips.  So the
TPU plugin implements the kubelet's preferred-allocation hook for real:
given the available device IDs and a requested count, it returns the set
minimizing total pairwise ICI (Manhattan) distance — i.e. the most
compact box the free chips admit.

Selection is exact (brute force over combinations) when the search space
is small, and falls back to seeded greedy growth otherwise.  Devices
with unknown coordinates (no tpulib backend) degrade to a deterministic
natural-order pick so the hook never fails an allocation.
"""

import itertools
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

Coord = Tuple[float, ...]

# Beyond this many candidate subsets, switch from exact search to greedy.
_EXACT_SEARCH_LIMIT = 20_000

_NAT_RE = re.compile(r"(\d+)")


def natural_key(device_id: str):
    """Sort ``accel2`` before ``accel10`` (and ``.../vtpu2`` before 10)."""
    return [
        int(p) if p.isdigit() else p for p in _NAT_RE.split(device_id)
    ]


def pairwise_distance(coords: Sequence[Coord]) -> float:
    """Sum of pairwise Manhattan (ICI hop) distances."""
    total = 0.0
    for i in range(len(coords)):
        for j in range(i + 1, len(coords)):
            total += sum(abs(a - b) for a, b in zip(coords[i], coords[j]))
    return total


def _score(ids: Iterable[str], coords_by_id: Dict[str, Coord]) -> float:
    return pairwise_distance([coords_by_id[i] for i in ids])


def choose_preferred(
    available: List[str],
    must_include: List[str],
    size: int,
    coords_by_id: Optional[Dict[str, Coord]] = None,
) -> List[str]:
    """Pick ``size`` device IDs from ``available`` ⊇ ``must_include``
    minimizing total pairwise ICI distance.

    Returns a naturally-sorted ID list; deterministic for equal scores.
    Degrades gracefully: unknown coordinates → natural-order fill.
    """
    available = sorted(set(available), key=natural_key)
    must = [d for d in sorted(set(must_include), key=natural_key)
            if d in available]
    if size <= 0:
        return []
    if size <= len(must):
        return must[:size]
    if size >= len(available):
        return available

    pool = [d for d in available if d not in must]
    n_extra = size - len(must)

    if coords_by_id is None or any(d not in coords_by_id for d in available):
        # No topology signal — deterministic natural-order fill.
        return sorted(must + pool[:n_extra], key=natural_key)

    n_combos = 1.0
    for i in range(n_extra):
        n_combos *= (len(pool) - i) / (i + 1)
    if n_combos <= _EXACT_SEARCH_LIMIT:
        best = None
        best_score = float("inf")
        for combo in itertools.combinations(pool, n_extra):
            cand = must + list(combo)
            s = _score(cand, coords_by_id)
            if s < best_score:
                best_score = s
                best = cand
        return sorted(best, key=natural_key)

    # Greedy: grow from the must-set (or from each candidate seed when the
    # must-set is empty), always adding the device closest to the current
    # set; keep the best-scoring grown set across seeds.
    seeds = [list(must)] if must else [[d] for d in pool]
    best = None
    best_score = float("inf")
    for seed in seeds:
        cand = list(seed)
        remaining = [d for d in pool if d not in cand]
        while len(cand) < size and remaining:
            nxt = min(
                remaining,
                key=lambda d: (
                    sum(
                        sum(
                            abs(a - b)
                            for a, b in zip(coords_by_id[d], coords_by_id[c])
                        )
                        for c in cand
                    ),
                    natural_key(d),
                ),
            )
            cand.append(nxt)
            remaining.remove(nxt)
        s = _score(cand, coords_by_id)
        if s < best_score:
            best_score = s
            best = cand
    return sorted(best, key=natural_key)
