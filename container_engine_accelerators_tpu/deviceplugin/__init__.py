from container_engine_accelerators_tpu.deviceplugin.manager import TpuManager

__all__ = ["TpuManager"]
