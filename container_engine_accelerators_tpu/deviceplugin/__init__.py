"""Public re-exports for the deviceplugin package."""
from container_engine_accelerators_tpu.deviceplugin.manager import TpuManager

__all__ = ["TpuManager"]
