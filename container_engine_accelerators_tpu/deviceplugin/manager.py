"""TPU device manager: discovery, specs, env contract, serve state machine.

TPU-native re-design of the reference's nvidiaGPUManager
(ref: pkg/gpu/nvidia/manager.go:136-499):

- discovery walks devDirectory for ``accel[0-9]+`` (the reference walks for
  ``nvidia[0-9]+``, manager.go:231-247);
- there is no /dev/nvidiactl analog — libtpu opens the chips directly — so
  default devices are just ``/dev/vfio/vfio`` when present (vfio-tpu nodes);
- sharing expands physical chips/sub-slices into vtpu virtual devices;
- core-sharing (the MPS analog) computes the co-tenancy env contract:
  TPU_CORE_PERCENTAGE + TPU_HBM_LIMIT_BYTES per container, from per-chip
  HBM totals via tpulib (the reference computes
  CUDA_MPS_ACTIVE_THREAD_PERCENTAGE / PINNED_DEVICE_MEM_LIMIT via NVML,
  manager.go:312-325);
- Serve runs the availability state machine faithfully: listen on a
  timestamped socket under the kubelet plugin dir, register, then poll —
  1s for socket deletion (kubelet restart → re-register), 10s for hotplug
  (new chips → rediscover + restart) (manager.go:410-499).
"""

import concurrent.futures
import logging
import os
import queue
import threading
import time
from typing import Dict, List, Optional

import grpc

from container_engine_accelerators_tpu.deviceplugin import api, preferred
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import trace
from container_engine_accelerators_tpu.partition.subslice import (
    SubsliceDeviceManager,
)
from container_engine_accelerators_tpu.sharing import (
    SharingStrategy,
    virtual_device_ids,
    virtual_to_physical_device_id,
)
from container_engine_accelerators_tpu.sharing.gate import CoreSharingGate
from container_engine_accelerators_tpu.tpulib.types import TpuLib
from container_engine_accelerators_tpu.utils import faults
from container_engine_accelerators_tpu.utils.config import TPUConfig
from container_engine_accelerators_tpu.utils.retry import RetryPolicy
from container_engine_accelerators_tpu.utils.device import (
    HEALTHY,
    Device,
    DeviceSpec,
    Mount,
)

from container_engine_accelerators_tpu.utils.devname import DEVICE_RE as TPU_DEVICE_RE

log = logging.getLogger(__name__)

VFIO_CONTROL_DEVICE = "vfio/vfio"

DEVICE_CHECK_INTERVAL_S = 10.0  # hotplug poll (gpuCheckInterval)
SOCKET_CHECK_INTERVAL_S = 1.0  # kubelet-restart poll (pluginSocketCheckInterval)

CORE_PERCENTAGE_ENV = "TPU_CORE_PERCENTAGE"
HBM_LIMIT_ENV = "TPU_HBM_LIMIT_BYTES"
MEM_FRACTION_ENV = "XLA_PYTHON_CLIENT_MEM_FRACTION"

# A kubelet mid-restart refuses Register for a few seconds; ride it out
# instead of crashing the DaemonSet pod (which would race the kubelet's
# own plugin-dir wipe and lose the socket watch).
REGISTER_RETRY = RetryPolicy(
    max_attempts=6, initial_backoff_s=0.5, max_backoff_s=5.0, deadline_s=30.0
)


class TpuManager:
    def __init__(
        self,
        dev_directory: str,
        mount_paths: List[Mount],
        config: TPUConfig,
        lib: Optional[TpuLib] = None,
        resource_name: str = "google.com/tpu",
        device_check_interval_s: float = DEVICE_CHECK_INTERVAL_S,
        socket_check_interval_s: float = SOCKET_CHECK_INTERVAL_S,
    ):
        self.dev_directory = dev_directory
        self.mount_paths = list(mount_paths)
        self.config = config
        self.lib = lib
        self.resource_name = resource_name
        self.devices: Dict[str, Device] = {}
        self.devices_mutex = threading.Lock()
        self.default_devices: List[str] = []
        self.health_events: "queue.Queue[Device]" = queue.Queue()
        self.subslice_manager = (
            SubsliceDeviceManager(lib, dev_directory) if lib is not None else None
        )
        self.total_hbm_per_chip = 0
        self.sharing_gate: Optional[CoreSharingGate] = None
        self.grpc_server: Optional[grpc.Server] = None
        self.socket: str = ""
        self.device_check_interval_s = device_check_interval_s
        self.socket_check_interval_s = socket_check_interval_s
        self._stop = threading.Event()

    # ---- discovery ---------------------------------------------------------

    def check_device_paths(self) -> bool:
        """Installer handshake: at least one TPU device node must exist
        (the reference waits on /dev/nvidiactl + nvidia-uvm,
        nvidia_gpu.go:99-109)."""
        return self._discover_num_chips() > 0

    def _discover_num_chips(self) -> int:
        try:
            entries = os.listdir(self.dev_directory)
        except OSError as e:
            log.error("cannot read %s: %s", self.dev_directory, e)
            return 0
        return sum(1 for f in entries if TPU_DEVICE_RE.match(f))

    def discover_chips(self) -> None:
        for f in sorted(os.listdir(self.dev_directory)):
            if TPU_DEVICE_RE.match(f):
                log.debug("Found TPU chip %r", f)
                self.set_device_health(f, HEALTHY)

    def has_additional_chips_installed(self) -> bool:
        with self.devices_mutex:
            original = len(self.devices)
        return self._discover_num_chips() > original

    def start(self) -> None:
        """Discover devices and set up the node environment
        (ref: manager.go:354-388)."""
        self.default_devices = []
        vfio_ctl = os.path.join(self.dev_directory, VFIO_CONTROL_DEVICE)
        if os.path.exists(vfio_ctl):
            self.default_devices.append(vfio_ctl)

        self.discover_chips()

        if self.config.partition_size:
            if self.subslice_manager is None:
                raise RuntimeError(
                    "partitioning requires a tpulib backend for topology"
                )
            self.subslice_manager.start(self.config.partition_size)

        if self.config.sharing.strategy == SharingStrategy.CORE_SHARING:
            if self.lib is None or self.lib.chip_count() <= 0:
                raise RuntimeError("core-sharing requires TPU chips on the node")
            first_chip = self.lib.chips()[0].name
            self.total_hbm_per_chip = self.lib.hbm_info(first_chip).total_bytes
            if self.total_hbm_per_chip <= 0:
                # Without a known HBM size the co-tenancy env contract would
                # silently become "no limits"; refuse to start instead.
                raise RuntimeError(
                    f"core-sharing requires a valid hbm_total_bytes for "
                    f"{first_chip}; node sysfs contract is incomplete"
                )
            # isMpsHealthy analog (manager.go:376-386): prove the
            # co-tenancy mechanism is enforceable before advertising
            # shared devices.
            self.sharing_gate = CoreSharingGate(self.mount_paths)
            self.sharing_gate.verify()

    # ---- device views ------------------------------------------------------

    def list_physical_devices(self) -> Dict[str, Device]:
        """Snapshot of physical devices (copy: gRPC worker threads iterate
        this concurrently with hotplug rediscovery on the serve thread)."""
        with self.devices_mutex:
            if not self.config.partition_size:
                return dict(self.devices)
            return dict(self.subslice_manager.list_partition_devices())

    def list_devices(self) -> Dict[str, Device]:
        physical = self.list_physical_devices()
        max_clients = self.config.sharing.max_shared_clients_per_tpu
        if max_clients > 0:
            virtual: Dict[str, Device] = {}
            for dev in physical.values():
                # Virtual devices inherit health from their physical device.
                for vid in virtual_device_ids(dev.id, max_clients):
                    virtual[vid] = Device(id=vid, health=dev.health)
            return virtual
        return physical

    def list_health_critical_codes(self) -> List[int]:
        return self.config.health_critical_codes

    def set_device_health(self, name: str, health: str) -> None:
        with self.devices_mutex:
            if TPU_DEVICE_RE.match(name):
                self.devices[name] = Device(id=name, health=health)
                # A chip fault takes down the sub-slice that owns the
                # chip; a chip recovery re-heals the slice only once
                # EVERY member chip is healthy again (the slice is the
                # unit the kubelet actually sees, so without this the
                # health checker's recovery would be a silent no-op on
                # partitioned nodes).
                if self.config.partition_size and self.subslice_manager:
                    slice_id = self.subslice_manager.slice_for_chip(name)
                    if slice_id is None:
                        return
                    if health != HEALTHY:
                        self.subslice_manager.set_device_health(slice_id, health)
                    elif all(
                        self.devices.get(
                            c.name, Device(id=c.name, health="")
                        ).health == HEALTHY
                        for c in self.subslice_manager.members(slice_id)
                    ):
                        prev = self.subslice_manager.list_partition_devices(
                        ).get(slice_id)
                        # Capture the STRING before set_device_health
                        # mutates the (shared) Device object in place.
                        prev_health = None if prev is None else prev.health
                        self.subslice_manager.set_device_health(
                            slice_id, HEALTHY
                        )
                        if prev_health is not None and prev_health != HEALTHY:
                            # The slice the kubelet actually schedules
                            # just came back — count it separately from
                            # per-chip recoveries so a fleet dashboard
                            # can tell "a chip healed" from "capacity
                            # returned".
                            counters.inc("health.slice_recovered")
                            trace.event("health.slice_recover",
                                        slice=slice_id, chip=name)
            elif self.subslice_manager is not None:
                self.subslice_manager.set_device_health(name, health)

    # ---- allocate path -----------------------------------------------------

    def verify_allocatable(self) -> None:
        """Pre-Allocate gate: under core-sharing, re-check the co-tenancy
        mechanism is still enforceable (ValueError rejects the request)."""
        if self.sharing_gate is not None:
            self.sharing_gate.check_allocatable()

    def device_spec(self, device_id: str) -> List[DeviceSpec]:
        """Map one requested device ID to its device nodes
        (ref: manager.go:201-228)."""
        if self.config.sharing.max_shared_clients_per_tpu > 0:
            device_id = virtual_to_physical_device_id(device_id)
        if self.config.partition_size:
            with self.devices_mutex:
                return self.subslice_manager.device_spec(device_id)
        with self.devices_mutex:
            dev = self.devices.get(device_id)
        if dev is None:
            raise ValueError(
                f"invalid allocation request with non-existing device {device_id}"
            )
        if dev.health != HEALTHY:
            raise ValueError(
                f"invalid allocation request with unhealthy device {device_id}"
            )
        node = os.path.join(self.dev_directory, device_id)
        return [DeviceSpec(host_path=node, container_path=node, permissions="mrw")]

    def preferred_allocation(
        self,
        available_ids: List[str],
        must_include_ids: List[str],
        allocation_size: int,
    ) -> List[str]:
        """ICI-contiguous preferred set for the kubelet's
        GetPreferredAllocation hook.

        The reference no-ops this (beta_plugin.go:95-103) — host GPUs are
        interchangeable; TPU chips on an ICI mesh are not.  Device IDs map
        to mesh coordinates (sub-slices to their tile centroid, vtpus to
        their physical device) and the most compact set wins.
        """
        coords = self._device_coords(available_ids)
        return preferred.choose_preferred(
            available_ids, must_include_ids, allocation_size, coords
        )

    def _device_coords(
        self, device_ids: List[str]
    ) -> Optional[Dict[str, preferred.Coord]]:
        """Map advertised device IDs to ICI coordinates; None without a
        topology backend."""
        if self.lib is None:
            return None
        try:
            chip_coords = {c.name: c.coords for c in self.lib.chips()}
        except Exception as e:  # noqa: BLE001 — never fail an allocation
            log.error("preferred-allocation topology query failed: %s", e)
            return None
        out: Dict[str, preferred.Coord] = {}
        for did in device_ids:
            try:
                phys = did
                if self.config.sharing.max_shared_clients_per_tpu > 0 and (
                    "/" in did
                ):
                    phys = virtual_to_physical_device_id(did)
                if self.config.partition_size and self.subslice_manager:
                    members = self.subslice_manager.members(phys)
                    if not members:
                        return None
                    out[did] = tuple(
                        sum(c.coords[axis] for c in members) / len(members)
                        for axis in range(3)
                    )
                elif phys in chip_coords:
                    out[did] = tuple(float(v) for v in chip_coords[phys])
                else:
                    return None
            except ValueError:
                # Malformed ID: degrade to the no-topology fallback rather
                # than failing the kubelet's RPC.
                return None
        return out

    def envs(self, request_device_ids: List[str]) -> Dict[str, str]:
        """Env contract for a container allocation.

        core-sharing: TensorCore fraction + HBM limit, the MPS-env analog
        (ref: manager.go:312-325).  Partitioned: sub-slice topology env so
        libtpu/JAX sees the right chip set and mesh bounds.
        """
        envs: Dict[str, str] = {}
        n = len(request_device_ids)
        if (
            self.config.sharing.strategy == SharingStrategy.CORE_SHARING
            and self.total_hbm_per_chip > 0
        ):
            max_clients = self.config.sharing.max_shared_clients_per_tpu
            core_pct = n * 100 // max_clients
            hbm_limit = n * self.total_hbm_per_chip // max_clients
            envs[CORE_PERCENTAGE_ENV] = str(core_pct)
            envs[HBM_LIMIT_ENV] = str(hbm_limit)
            envs[MEM_FRACTION_ENV] = f"{n / max_clients:.4f}"
        if self.config.partition_size and request_device_ids:
            phys = request_device_ids[0]
            if self.config.sharing.max_shared_clients_per_tpu > 0:
                phys = virtual_to_physical_device_id(phys)
            envs.update(self.subslice_manager.envs(phys))
        return envs

    # ---- serve state machine ----------------------------------------------

    def serve(
        self,
        plugin_mount_path: str,
        kubelet_endpoint: str = api.KUBELET_SOCKET,
        plugin_endpoint: Optional[str] = None,
    ) -> None:
        """Availability state machine (ref: manager.go:410-499): (re)create
        the plugin socket, serve gRPC, register with the kubelet, then watch
        for socket deletion (1s) and chip hotplug (10s); either tears the
        server down and restarts the loop."""
        from container_engine_accelerators_tpu.deviceplugin.service import (
            DevicePluginService,
        )

        register_with_kubelet = os.path.exists(
            os.path.join(plugin_mount_path, kubelet_endpoint)
        )
        log.info(
            "kubelet socket %s; registration %s",
            os.path.join(plugin_mount_path, kubelet_endpoint),
            "enabled" if register_with_kubelet else "disabled",
        )

        while not self._stop.is_set():
            endpoint = plugin_endpoint or f"tpu-{int(time.time())}.sock"
            endpoint_path = os.path.join(plugin_mount_path, endpoint)
            if os.path.exists(endpoint_path):
                os.unlink(endpoint_path)
            log.info("starting device-plugin server at: %s", endpoint_path)

            server = grpc.server(
                concurrent.futures.ThreadPoolExecutor(max_workers=4)
            )
            api.add_device_plugin_servicer(server, DevicePluginService(self))
            server.add_insecure_port(f"unix:{endpoint_path}")
            server.start()
            self.grpc_server = server
            self.socket = endpoint_path

            try:
                if register_with_kubelet:
                    if not self._register_with_retry(
                        os.path.join(plugin_mount_path, kubelet_endpoint),
                        endpoint,
                    ):
                        # Budget exhausted: tear the server down and
                        # restart the loop on a fresh socket rather than
                        # crash — the kubelet may still be coming up.
                        continue
                    log.info("device-plugin registered with the kubelet")

                self._status_check(endpoint_path)
            finally:
                server.stop(grace=1).wait()
                self.grpc_server = None

    def _register_with_retry(self, kubelet_socket: str, endpoint: str) -> bool:
        """Register with the kubelet under REGISTER_RETRY; False when the
        budget is exhausted (caller restarts the serve loop).  Fault site
        ``kubelet.register`` fires before each attempt."""
        last = None
        for attempt in self._retry_attempts():
            if self._stop.is_set():
                return False
            try:
                with trace.span("kubelet.register",
                                histogram="kubelet.register",
                                attempt=attempt, endpoint=endpoint):
                    faults.check("kubelet.register")
                    api.register_with_v1beta1_kubelet(
                        kubelet_socket, endpoint, self.resource_name
                    )
                if attempt > 0:
                    counters.inc("kubelet.register.retried")
                return True
            except (grpc.RpcError, grpc.FutureTimeoutError, OSError) as e:
                # FutureTimeoutError: channel_ready_future never went
                # ready — the kubelet socket exists but nothing answers
                # (mid-restart), the classic transient.
                last = e
                counters.inc("kubelet.register.failed")
                log.error(
                    "kubelet registration attempt %d failed: %s", attempt + 1, e
                )
        log.error("kubelet registration budget exhausted: %s", last)
        return False

    def _retry_attempts(self):
        # Sleep on the stop event so shutdown interrupts the backoff.
        return REGISTER_RETRY.attempts(sleep=self._stop.wait)

    def _status_check(self, endpoint_path: str) -> None:
        last_device_check = time.monotonic()
        while not self._stop.is_set():
            if self._stop.wait(self.socket_check_interval_s):
                return
            # Socket vanished ⇒ kubelet restarted and wiped the plugin dir;
            # tear down and re-register (manager.go:475-481).
            if not os.path.lexists(endpoint_path):
                log.info("plugin socket %s deleted; restarting", endpoint_path)
                counters.inc("kubelet.reregister")
                return
            if time.monotonic() - last_device_check >= self.device_check_interval_s:
                last_device_check = time.monotonic()
                if self.has_additional_chips_installed():
                    log.info("new TPU chips found; rediscovering + restarting")
                    # Full re-start: rediscovers chips AND recomputes
                    # sub-slice partitions / default devices / HBM totals —
                    # discover_chips() alone would leave a stale partition
                    # table advertised to the kubelet.
                    try:
                        self.start()
                    except Exception as e:
                        log.error("rediscovery failed: %s; will retry", e)
                    return

    def stop(self) -> None:
        if self.socket and os.path.exists(self.socket):
            os.unlink(self.socket)
        self._stop.set()
        if self.grpc_server is not None:
            self.grpc_server.stop(grace=1)
