"""TPU sub-slice partitioning — the MIG analog, TPU-native.

The reference slices one physical A100/H100 into MIG partitions and maps
each ``nvidiaN/giM`` to three device nodes
(ref: pkg/gpu/nvidia/mig/mig.go:33-46,73-80,83-212).  A TPU chip is not
hardware-sliceable; the TPU-native unit of partitioning is the **host ICI
mesh**: a node with topology ``2x2x1`` (4 chips) can be carved into
``1x1`` sub-slices (4 single-chip partitions), ``2x1`` (2 partitions), or
``2x2`` (1 partition).  Each partition:

- is advertised as ONE schedulable ``google.com/tpu`` device ``sliceM``;
- maps to ALL member ``/dev/accelN`` nodes on Allocate (the MIG
  one-device→many-nodes shape);
- carries the env contract that tells libtpu/JAX which chips it owns and
  their mesh bounds: ``TPU_VISIBLE_DEVICES``,
  ``TPU_CHIPS_PER_PROCESS_BOUNDS``, ``TPU_PROCESS_BOUNDS``.

Partitions are contiguous axis-aligned boxes tiling the host mesh, so ICI
links inside a partition are always physically present.
"""

import os
from typing import Dict, List, Optional, Tuple

from container_engine_accelerators_tpu.tpulib.types import ChipInfo, TpuLib
from container_engine_accelerators_tpu.utils.device import (
    HEALTHY,
    Device,
    DeviceSpec,
)


def _parse_size(size: str) -> Tuple[int, int, int]:
    parts = [int(p) for p in size.split("x")]
    if not parts or any(p <= 0 for p in parts) or len(parts) > 3:
        raise ValueError(f"invalid partition size {size!r}")
    while len(parts) < 3:
        parts.append(1)
    return tuple(parts)


def compute_subslices(
    chips: List[ChipInfo], partition_size: str
) -> List[List[ChipInfo]]:
    """Tile the host mesh with partition-sized boxes; returns chip groups in
    deterministic slice order.  Partition dims must divide the host bounds
    (the analog of the MIG partition-size table, mig.go:33-46)."""
    if not chips:
        return []
    bounds = chips[0].topology
    psize = _parse_size(partition_size)
    for axis in range(3):
        if bounds[axis] % psize[axis] != 0:
            raise ValueError(
                f"partition size {partition_size!r} does not tile host "
                f"topology {'x'.join(map(str, bounds))}"
            )
    by_coord = {c.coords: c for c in chips}
    if len(by_coord) != len(chips):
        raise ValueError("duplicate chip ICI coordinates")

    tiles = []
    for z0 in range(0, bounds[2], psize[2]):
        for y0 in range(0, bounds[1], psize[1]):
            for x0 in range(0, bounds[0], psize[0]):
                members = []
                for dz in range(psize[2]):
                    for dy in range(psize[1]):
                        for dx in range(psize[0]):
                            coord = (x0 + dx, y0 + dy, z0 + dz)
                            chip = by_coord.get(coord)
                            if chip is None:
                                raise ValueError(
                                    f"no chip at ICI coordinate {coord}; "
                                    f"host reports topology "
                                    f"{'x'.join(map(str, bounds))}"
                                )
                            members.append(chip)
                tiles.append(members)
    return tiles


class SubsliceDeviceManager:
    """Discovers sub-slice partitions and serves their device specs/envs.

    Mirrors the two-sided design of the reference's MIG DeviceManager
    (mig.go:48-80): the partitioner tool programs the layout; this manager
    discovers it and answers the device plugin's queries.
    """

    def __init__(self, lib: TpuLib, dev_directory: str):
        self.lib = lib
        self.dev_directory = dev_directory
        self.partition_size = ""
        self.devices: Dict[str, Device] = {}
        self._members: Dict[str, List[ChipInfo]] = {}

    def start(self, partition_size: str) -> None:
        devices: Dict[str, Device] = {}
        members_map: Dict[str, List[ChipInfo]] = {}
        if partition_size:
            tiles = compute_subslices(self.lib.chips(), partition_size)
            for m, members in enumerate(tiles):
                slice_id = f"slice{m}"
                for chip in members:
                    node = os.path.join(self.dev_directory, chip.name)
                    if not os.path.exists(node):
                        raise FileNotFoundError(
                            f"partition {slice_id} member device node {node} "
                            f"missing"
                        )
                devices[slice_id] = Device(id=slice_id, health=HEALTHY)
                members_map[slice_id] = members
        # Swap in fully-built tables so concurrent readers never observe a
        # half-populated partition map during hotplug re-starts.
        self.partition_size = partition_size
        self.devices = devices
        self._members = members_map

    def list_partition_devices(self) -> Dict[str, Device]:
        return self.devices

    def set_device_health(self, device_id: str, health: str) -> None:
        if device_id in self.devices:
            self.devices[device_id].health = health

    def members(self, device_id: str) -> List[ChipInfo]:
        """Member chips of partition ``device_id`` ([] when unknown)."""
        return list(self._members.get(device_id, []))

    def slice_for_chip(self, chip_name: str) -> Optional[str]:
        """Which partition owns chip ``accelN`` (for health-event routing)."""
        for slice_id, members in self._members.items():
            if any(c.name == chip_name for c in members):
                return slice_id
        return None

    def device_spec(self, device_id: str) -> List[DeviceSpec]:
        dev = self.devices.get(device_id)
        if dev is None:
            raise ValueError(
                f"invalid allocation request with non-existing device {device_id}"
            )
        if dev.health != HEALTHY:
            raise ValueError(
                f"invalid allocation request with unhealthy device {device_id}"
            )
        specs = []
        for chip in self._members[device_id]:
            node = os.path.join(self.dev_directory, chip.name)
            specs.append(
                DeviceSpec(host_path=node, container_path=node, permissions="mrw")
            )
        return specs

    def envs(self, device_id: str) -> Dict[str, str]:
        """libtpu/JAX topology env for a partition's chips."""
        members = self._members.get(device_id)
        if not members:
            return {}
        psize = _parse_size(self.partition_size)
        return {
            "TPU_VISIBLE_DEVICES": ",".join(str(c.index) for c in members),
            "TPU_CHIPS_PER_PROCESS_BOUNDS": ",".join(str(p) for p in psize),
            "TPU_PROCESS_BOUNDS": "1,1,1",
        }
