"""Public re-exports for the partition package."""
from container_engine_accelerators_tpu.partition.subslice import (
    SubsliceDeviceManager,
    compute_subslices,
)

__all__ = ["SubsliceDeviceManager", "compute_subslices"]
