"""NRI device injector — containerd NRI plugin, TPU-native.

Python implementation of the reference's NRI device-injector plugin
(ref: nri_device_injector/nri_device_injector.go): pods annotate
``devices.gke.io/container.<name>`` with a device list, and the plugin
injects those device nodes at CreateContainer time — no device-plugin
involvement, which is how unprivileged DCN/RX-daemon sidecars get their
``/dev/vfio``-style aperture nodes (SURVEY.md §2 #13, #14).

The wire stack (mux framing + ttrpc + NRI protobuf) is implemented
in-repo because the containerd client libraries are Go-only; the
protocol constants mirror github.com/containerd/{nri,ttrpc}.
"""

from container_engine_accelerators_tpu.nri.injector import (
    CTR_DEVICE_KEY_PREFIX,
    get_devices,
    to_linux_device,
)

__all__ = ["CTR_DEVICE_KEY_PREFIX", "get_devices", "to_linux_device"]
