"""Device-injection logic: pod annotation → NRI LinuxDevice adjustments.

Behavioral parity with the reference injector
(ref: nri_device_injector/nri_device_injector.go:126-199): the
annotation ``devices.gke.io/container.<name>`` holds a YAML/JSON list of
``{path, type, major, minor, file_mode, uid, gid}``; duplicates by path
keep the first entry; the device's type/major/minor come from lstat of
the path on the node (annotated values are informational), and the
annotation's file_mode/uid/gid override when non-zero.  For TPU nodes
the annotated paths are ``/dev/accelN`` chips and ``/dev/vfio/*``
aperture nodes (SURVEY.md §2.2).
"""

import os
import stat as stat_module
from typing import Dict, List

import yaml

from container_engine_accelerators_tpu.nri import nri_v1alpha1_pb2 as pb

DEVICE_KEY_PREFIX = "devices.gke.io"
CTR_DEVICE_KEY_PREFIX = DEVICE_KEY_PREFIX + "/container."

BLOCK_DEVICE = "b"
CHAR_DEVICE = "c"
FIFO_DEVICE = "p"


class _NoAliasSafeLoader(yaml.SafeLoader):
    """SafeLoader that rejects anchors/aliases: annotations are untrusted
    pod input, and PyYAML expands aliases without limit (billion-laughs);
    device lists never legitimately need them."""

    def compose_node(self, parent, index):
        if self.check_event(yaml.events.AliasEvent):
            raise yaml.YAMLError("YAML aliases are not allowed")
        return super().compose_node(parent, index)


def get_devices(ctr_name: str, pod_annotations: Dict[str, str]) -> List[dict]:
    """Parse the container's device annotation; [] when absent."""
    raw = (pod_annotations or {}).get(CTR_DEVICE_KEY_PREFIX + ctr_name)
    if raw is None:
        return []
    try:
        parsed = yaml.load(raw, Loader=_NoAliasSafeLoader)
    except yaml.YAMLError as e:
        raise ValueError(f"invalid device annotation for {ctr_name!r}: {e}")
    if parsed is None:
        return []
    if not isinstance(parsed, list):
        raise ValueError(
            f"invalid device annotation for {ctr_name!r}: expected a list"
        )
    devices, seen = [], set()
    for entry in parsed:
        if not isinstance(entry, dict) or "path" not in entry:
            raise ValueError(
                f"invalid device annotation for {ctr_name!r}: "
                f"each entry needs a 'path'"
            )
        if entry["path"] in seen:
            continue
        seen.add(entry["path"])
        devices.append(entry)
    return devices


def to_linux_device(entry: dict, lstat=os.lstat) -> pb.LinuxDevice:
    """Stat the device path and build the NRI device (go:158-199)."""
    path = entry["path"]
    try:
        st = lstat(path)
    except OSError as e:
        raise ValueError(f"failed to get info from device path {path}: {e}")
    mode = st.st_mode
    if stat_module.S_ISBLK(mode):
        dev_type = BLOCK_DEVICE
    elif stat_module.S_ISCHR(mode):
        dev_type = CHAR_DEVICE
    elif stat_module.S_ISFIFO(mode):
        dev_type = FIFO_DEVICE
    else:
        raise ValueError(f"invalid device type {mode:o} from device path {path}")
    device = pb.LinuxDevice(
        path=path,
        type=dev_type,
        major=os.major(st.st_rdev),
        minor=os.minor(st.st_rdev),
    )
    if entry.get("file_mode"):
        device.file_mode.value = _parse_mode(entry["file_mode"])
    if entry.get("uid"):
        device.uid.value = int(entry["uid"])
    if entry.get("gid"):
        device.gid.value = int(entry["gid"])
    return device


def _parse_mode(value) -> int:
    """File modes arrive as ints or strings: YAML 1.1 parses ``0660`` as
    octal int, but ``0o660`` stays a string under PyYAML — accept both
    (Go's yaml.v3, which the reference relies on, takes 0o as int)."""
    if isinstance(value, int):
        return value
    s = str(value).strip()
    if s.startswith(("0o", "0O", "0x", "0X", "0b", "0B")):
        return int(s, 0)
    if s.startswith("0") and s != "0":
        return int(s, 8)
    return int(s)


def create_container_adjustment(
    ctr_name: str, pod_annotations: Dict[str, str], lstat=os.lstat
) -> pb.ContainerAdjustment:
    """The CreateContainer hook body (go:86-123); raises on bad annotations
    so the runtime rejects the container rather than silently starting it
    without its devices."""
    adjust = pb.ContainerAdjustment()
    for entry in get_devices(ctr_name, pod_annotations):
        adjust.linux.devices.append(to_linux_device(entry, lstat=lstat))
    return adjust
