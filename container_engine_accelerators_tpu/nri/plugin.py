"""NRI plugin runtime: register with containerd, serve lifecycle hooks.

Connection flow (mirrors github.com/containerd/nri pkg/stub): dial the
NRI socket, multiplex it (mux.py), serve the ``Plugin`` ttrpc service on
logical conn 1, call ``Runtime.RegisterPlugin`` on logical conn 2, then
answer Configure/Synchronize/CreateContainer events until the runtime
closes the connection.  Subscription is CreateContainer-only, like the
reference plugin (nri_device_injector.go:86).

Resilience (ROADMAP "NRI injector resilience"): containerd restarts are
routine — every upgrade bounces it — and the ttrpc trunk dies with it.
``run()`` therefore reconnects with backoff under the shared
:class:`RetryPolicy` budget and re-registers on the fresh trunk, so a
runtime bounce costs the plugin a few seconds of deafness instead of
its life (and the devices of every container created meanwhile).  A
successful session resets the budget; only the runtime's explicit
``Shutdown`` (or a spent budget — ``nri.reconnect.failed``) ends the
loop.  Each re-established session counts ``nri.reconnect``.
"""

import logging
import socket
import threading
import time
from typing import Optional

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.nri import injector
from container_engine_accelerators_tpu.nri import mux as nri_mux
from container_engine_accelerators_tpu.nri import nri_v1alpha1_pb2 as pb
from container_engine_accelerators_tpu.nri.ttrpc import TtrpcClient, TtrpcServer
from container_engine_accelerators_tpu.utils.retry import RetryPolicy

log = logging.getLogger(__name__)

DEFAULT_NRI_SOCKET = "/var/run/nri/nri.sock"

# Rides out a containerd restart (systemd gives it seconds, not
# minutes) without masking a genuinely absent runtime: connect
# refusals fail instantly, so coverage is the sum of the sleeps.
RECONNECT_RETRY = RetryPolicy(
    max_attempts=8, initial_backoff_s=0.2, max_backoff_s=5.0,
    deadline_s=60.0,
)

# A session that lives at least this long counts as a real recovery
# and resets the consecutive-short-session budget; anything shorter is
# a runtime that accepts and immediately drops us (crash loop,
# half-up socket) and must cost backoff, not a zero-sleep spin.
MIN_SESSION_S = 5.0
PLUGIN_SERVICE = "nri.pkg.api.v1alpha1.Plugin"
RUNTIME_SERVICE = "nri.pkg.api.v1alpha1.Runtime"
PLUGIN_NAME = "device_injector_nri"
PLUGIN_IDX = "10"


def event_mask(*events: int) -> int:
    """Bit (e-1) subscribes Event e (nri pkg/api/event.go)."""
    m = 0
    for e in events:
        m |= 1 << (e - 1)
    return m


class DeviceInjectorPlugin:
    def __init__(
        self,
        socket_path: str = DEFAULT_NRI_SOCKET,
        plugin_name: str = PLUGIN_NAME,
        plugin_idx: str = PLUGIN_IDX,
        lstat=None,
    ):
        self.socket_path = socket_path
        self.plugin_name = plugin_name
        self.plugin_idx = plugin_idx
        self._lstat = lstat  # test seam; None = os.lstat
        self._shutdown = threading.Event()

    # ---- Plugin service handlers (runtime -> us) ---------------------------

    def _configure(self, payload: bytes) -> bytes:
        req = pb.ConfigureRequest.FromString(payload)
        log.info("configured by runtime %s %s", req.runtime_name,
                 req.runtime_version)
        return pb.ConfigureResponse(
            events=event_mask(pb.CREATE_CONTAINER)
        ).SerializeToString()

    def _synchronize(self, payload: bytes) -> bytes:
        req = pb.SynchronizeRequest.FromString(payload)
        log.info("synchronized: %d pods, %d containers",
                 len(req.pods), len(req.containers))
        return pb.SynchronizeResponse().SerializeToString()

    def _create_container(self, payload: bytes) -> bytes:
        req = pb.CreateContainerRequest.FromString(payload)
        ctr, pod = req.container.name, req.pod.name
        log.info("CreateContainer %s/%s/%s", req.pod.namespace, pod, ctr)
        kwargs = {"lstat": self._lstat} if self._lstat else {}
        adjust = injector.create_container_adjustment(
            ctr, dict(req.pod.annotations), **kwargs
        )
        for device in adjust.linux.devices:
            log.info("injecting device %s (%s %d:%d) into %s/%s",
                     device.path, device.type, device.major, device.minor,
                     pod, ctr)
        return pb.CreateContainerResponse(adjust=adjust).SerializeToString()

    def _stop_container(self, payload: bytes) -> bytes:
        return pb.StopContainerResponse().SerializeToString()

    def _state_change(self, payload: bytes) -> bytes:
        return pb.Empty().SerializeToString()

    def _handle_shutdown(self, payload: bytes) -> bytes:
        log.info("runtime is shutting down")
        self._shutdown.set()
        return pb.Empty().SerializeToString()

    # ---- lifecycle ---------------------------------------------------------

    def _build_server(self, conn) -> TtrpcServer:
        server = TtrpcServer(conn)
        for method, handler in [
            ("Configure", self._configure),
            ("Synchronize", self._synchronize),
            ("Shutdown", self._handle_shutdown),
            ("CreateContainer", self._create_container),
            ("StopContainer", self._stop_container),
            ("StateChange", self._state_change),
        ]:
            server.register(PLUGIN_SERVICE, method, handler)
        return server

    def run_on_socket(self, sock) -> None:
        """Serve one connected trunk socket until it closes or the runtime
        announces Shutdown (test seam)."""
        m = nri_mux.Mux(sock)
        server = self._build_server(m.open(nri_mux.PLUGIN_SERVICE_CONN))
        client = TtrpcClient(m.open(nri_mux.RUNTIME_SERVICE_CONN))
        m.start_reader()

        serve_thread = threading.Thread(
            target=server.serve, daemon=True, name="nri-plugin-server"
        )
        serve_thread.start()

        client.call(
            RUNTIME_SERVICE, "RegisterPlugin",
            pb.RegisterPluginRequest(
                plugin_name=self.plugin_name, plugin_idx=self.plugin_idx
            ).SerializeToString(),
        )
        log.info("registered NRI plugin %s (idx %s)",
                 self.plugin_name, self.plugin_idx)
        # Serve until the connection drops, or Shutdown arrives — then close
        # the trunk ourselves to unblock the serve loop.
        while serve_thread.is_alive():
            if self._shutdown.wait(timeout=0.2):
                # Give the serve loop a beat to flush the Shutdown response
                # before tearing down the trunk under it.
                time.sleep(0.2)
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                serve_thread.join(timeout=5)
                break

    def _dial(self):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            sock.connect(self.socket_path)
        except OSError:
            sock.close()
            raise
        return sock

    def run_once(self) -> None:
        """One dial + serve session, no reconnect (the pre-resilience
        contract; ``run()`` wraps this in the backoff loop)."""
        sock = self._dial()
        try:
            self.run_on_socket(sock)
        finally:
            sock.close()

    def run(self, retry: Optional[RetryPolicy] = None) -> None:
        """Serve forever, reconnecting with backoff when the trunk
        dies.  Ends cleanly on the runtime's Shutdown; raises the last
        OSError once a reconnect budget is spent — against a socket
        that stays unreachable, OR a runtime that keeps accepting and
        instantly dropping us (each short-lived session costs a
        backoff sleep and a budget slot; a session that lives past
        ``MIN_SESSION_S`` resets the budget).  Either way counts
        ``nri.reconnect.failed``: graceful degradation, never an
        unbounded spin."""
        policy = retry or RECONNECT_RETRY
        sessions = 0
        short_sessions = 0
        while not self._shutdown.is_set():
            try:
                sock = policy.call(self._dial, retry_on=(OSError,))
            except OSError:
                counters.inc("nri.reconnect.failed")
                log.error("NRI socket %s unreachable through the whole "
                          "reconnect budget; giving up", self.socket_path)
                raise
            if sessions:
                counters.inc("nri.reconnect")
                log.warning("NRI trunk re-established (reconnect #%d); "
                            "re-registering", sessions)
            sessions += 1
            started = time.monotonic()
            try:
                # Registration/serving failures are connection loss:
                # the next lap re-dials.  A TtrpcError surfaces as-is —
                # the runtime actively refusing us is not a blip.
                self.run_on_socket(sock)
            except (OSError, EOFError) as e:
                log.warning("NRI connection lost: %s", e)
            finally:
                try:
                    sock.close()
                except OSError:
                    pass
            if self._shutdown.is_set():
                break
            if time.monotonic() - started >= MIN_SESSION_S:
                short_sessions = 0
                continue
            short_sessions += 1
            if short_sessions >= policy.max_attempts:
                counters.inc("nri.reconnect.failed")
                log.error("NRI runtime dropped %d consecutive sessions "
                          "within %.0fs each; giving up",
                          short_sessions, MIN_SESSION_S)
                raise OSError(
                    f"NRI runtime at {self.socket_path} keeps dropping "
                    f"the trunk ({short_sessions} short sessions)"
                )
            time.sleep(policy.backoff_s(short_sessions - 1))
