"""NRI plugin runtime: register with containerd, serve lifecycle hooks.

Connection flow (mirrors github.com/containerd/nri pkg/stub): dial the
NRI socket, multiplex it (mux.py), serve the ``Plugin`` ttrpc service on
logical conn 1, call ``Runtime.RegisterPlugin`` on logical conn 2, then
answer Configure/Synchronize/CreateContainer events until the runtime
closes the connection.  Subscription is CreateContainer-only, like the
reference plugin (nri_device_injector.go:86).
"""

import logging
import socket
import threading
import time
from typing import Optional

from container_engine_accelerators_tpu.nri import injector
from container_engine_accelerators_tpu.nri import mux as nri_mux
from container_engine_accelerators_tpu.nri import nri_v1alpha1_pb2 as pb
from container_engine_accelerators_tpu.nri.ttrpc import TtrpcClient, TtrpcServer

log = logging.getLogger(__name__)

DEFAULT_NRI_SOCKET = "/var/run/nri/nri.sock"
PLUGIN_SERVICE = "nri.pkg.api.v1alpha1.Plugin"
RUNTIME_SERVICE = "nri.pkg.api.v1alpha1.Runtime"
PLUGIN_NAME = "device_injector_nri"
PLUGIN_IDX = "10"


def event_mask(*events: int) -> int:
    """Bit (e-1) subscribes Event e (nri pkg/api/event.go)."""
    m = 0
    for e in events:
        m |= 1 << (e - 1)
    return m


class DeviceInjectorPlugin:
    def __init__(
        self,
        socket_path: str = DEFAULT_NRI_SOCKET,
        plugin_name: str = PLUGIN_NAME,
        plugin_idx: str = PLUGIN_IDX,
        lstat=None,
    ):
        self.socket_path = socket_path
        self.plugin_name = plugin_name
        self.plugin_idx = plugin_idx
        self._lstat = lstat  # test seam; None = os.lstat
        self._shutdown = threading.Event()

    # ---- Plugin service handlers (runtime -> us) ---------------------------

    def _configure(self, payload: bytes) -> bytes:
        req = pb.ConfigureRequest.FromString(payload)
        log.info("configured by runtime %s %s", req.runtime_name,
                 req.runtime_version)
        return pb.ConfigureResponse(
            events=event_mask(pb.CREATE_CONTAINER)
        ).SerializeToString()

    def _synchronize(self, payload: bytes) -> bytes:
        req = pb.SynchronizeRequest.FromString(payload)
        log.info("synchronized: %d pods, %d containers",
                 len(req.pods), len(req.containers))
        return pb.SynchronizeResponse().SerializeToString()

    def _create_container(self, payload: bytes) -> bytes:
        req = pb.CreateContainerRequest.FromString(payload)
        ctr, pod = req.container.name, req.pod.name
        log.info("CreateContainer %s/%s/%s", req.pod.namespace, pod, ctr)
        kwargs = {"lstat": self._lstat} if self._lstat else {}
        adjust = injector.create_container_adjustment(
            ctr, dict(req.pod.annotations), **kwargs
        )
        for device in adjust.linux.devices:
            log.info("injecting device %s (%s %d:%d) into %s/%s",
                     device.path, device.type, device.major, device.minor,
                     pod, ctr)
        return pb.CreateContainerResponse(adjust=adjust).SerializeToString()

    def _stop_container(self, payload: bytes) -> bytes:
        return pb.StopContainerResponse().SerializeToString()

    def _state_change(self, payload: bytes) -> bytes:
        return pb.Empty().SerializeToString()

    def _handle_shutdown(self, payload: bytes) -> bytes:
        log.info("runtime is shutting down")
        self._shutdown.set()
        return pb.Empty().SerializeToString()

    # ---- lifecycle ---------------------------------------------------------

    def _build_server(self, conn) -> TtrpcServer:
        server = TtrpcServer(conn)
        for method, handler in [
            ("Configure", self._configure),
            ("Synchronize", self._synchronize),
            ("Shutdown", self._handle_shutdown),
            ("CreateContainer", self._create_container),
            ("StopContainer", self._stop_container),
            ("StateChange", self._state_change),
        ]:
            server.register(PLUGIN_SERVICE, method, handler)
        return server

    def run_on_socket(self, sock) -> None:
        """Serve one connected trunk socket until it closes or the runtime
        announces Shutdown (test seam)."""
        m = nri_mux.Mux(sock)
        server = self._build_server(m.open(nri_mux.PLUGIN_SERVICE_CONN))
        client = TtrpcClient(m.open(nri_mux.RUNTIME_SERVICE_CONN))
        m.start_reader()

        serve_thread = threading.Thread(
            target=server.serve, daemon=True, name="nri-plugin-server"
        )
        serve_thread.start()

        client.call(
            RUNTIME_SERVICE, "RegisterPlugin",
            pb.RegisterPluginRequest(
                plugin_name=self.plugin_name, plugin_idx=self.plugin_idx
            ).SerializeToString(),
        )
        log.info("registered NRI plugin %s (idx %s)",
                 self.plugin_name, self.plugin_idx)
        # Serve until the connection drops, or Shutdown arrives — then close
        # the trunk ourselves to unblock the serve loop.
        while serve_thread.is_alive():
            if self._shutdown.wait(timeout=0.2):
                # Give the serve loop a beat to flush the Shutdown response
                # before tearing down the trunk under it.
                time.sleep(0.2)
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                serve_thread.join(timeout=5)
                break

    def run(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(self.socket_path)
        try:
            self.run_on_socket(sock)
        finally:
            sock.close()
