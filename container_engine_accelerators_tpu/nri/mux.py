"""NRI connection multiplexer — two logical byte streams on one socket.

Wire format per github.com/containerd/nri pkg/net/multiplex: each trunk
frame is an 8-byte header — conn id (u32 BE), payload length (u32 BE) —
followed by payload bytes belonging to that logical connection.  Conn 1
(PLUGIN_SERVICE_CONN) carries the runtime→plugin ttrpc session (we are
the ttrpc server); conn 2 (RUNTIME_SERVICE_CONN) carries plugin→runtime
(we are the client).  Payload boundaries carry no meaning: each logical
conn is a plain byte stream.
"""

import struct
import threading
from typing import Dict

from container_engine_accelerators_tpu.analysis import lockwatch
from container_engine_accelerators_tpu.utils import netio

HEADER_LEN = 8
MAX_PAYLOAD = 1 << 24

PLUGIN_SERVICE_CONN = 1
RUNTIME_SERVICE_CONN = 2


class MuxConn:
    """One logical connection: buffered reads, writes via the trunk."""

    def __init__(self, mux: "Mux", conn_id: int):
        self._mux = mux
        self._id = conn_id
        self._buf = bytearray()
        self._cond = threading.Condition()
        self._closed = False

    # -- mux-side ------------------------------------------------------------

    def _feed(self, data: bytes) -> None:
        with self._cond:
            self._buf.extend(data)
            self._cond.notify_all()

    def _close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- user-side -----------------------------------------------------------

    def read_exact(self, n: int) -> bytes:
        with self._cond:
            while len(self._buf) < n:
                if self._closed:
                    raise EOFError("mux connection closed")
                self._cond.wait()
            out = bytes(self._buf[:n])
            del self._buf[:n]
            return out

    def write(self, data: bytes) -> None:
        self._mux.write(self._id, data)


class Mux:
    def __init__(self, sock):
        self._sock = sock
        self._write_lock = threading.Lock()
        self._conns: Dict[int, MuxConn] = {}
        self._reader_started = False

    def open(self, conn_id: int) -> MuxConn:
        conn = self._conns.get(conn_id)
        if conn is None:
            conn = self._conns[conn_id] = MuxConn(self, conn_id)
        return conn

    def write(self, conn_id: int, data: bytes) -> None:
        if len(data) > MAX_PAYLOAD:
            raise ValueError(f"mux payload {len(data)} exceeds maximum")
        frame = struct.pack(">II", conn_id, len(data)) + data
        with self._write_lock:
            # Holding the write lock across the whole frame IS the
            # framing guarantee (two logical conns interleaving bytes
            # would desynchronize the trunk) — a deliberate
            # blocking-under-lock, annotated so `make race` counts it
            # under `allowed`, and a hardened send: containerd trunks
            # carry multi-MiB UpdateContainers payloads, and a short
            # write would break every frame after it.
            with lockwatch.blocking_ok(
                    "nri.mux: trunk frames must not interleave"):
                netio.sendall(self._sock, frame)

    def start_reader(self) -> threading.Thread:
        """Demultiplex trunk frames into logical conns until socket EOF."""
        assert not self._reader_started
        self._reader_started = True
        t = threading.Thread(target=self._read_loop, daemon=True,
                             name="nri-mux-reader")
        t.start()
        return t

    def _recv_exact(self, n: int) -> bytes:
        chunks = bytearray()
        while len(chunks) < n:
            chunk = self._sock.recv(n - len(chunks))
            if not chunk:
                raise EOFError("trunk socket closed")
            chunks.extend(chunk)
        return bytes(chunks)

    def _read_loop(self) -> None:
        try:
            while True:
                conn_id, length = struct.unpack(">II", self._recv_exact(HEADER_LEN))
                if length > MAX_PAYLOAD:
                    # Desynchronized/corrupt trunk: tear down rather than
                    # trying to buffer up to 4 GiB of garbage.
                    raise EOFError(
                        f"mux frame length {length} exceeds maximum; "
                        f"closing desynchronized trunk"
                    )
                payload = self._recv_exact(length) if length else b""
                self.open(conn_id)._feed(payload)
        except (EOFError, OSError):
            pass
        finally:
            for conn in self._conns.values():
                conn._close()
