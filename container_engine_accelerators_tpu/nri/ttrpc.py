"""Minimal ttrpc (containerd's lightweight RPC) — unary calls only.

Wire format per github.com/containerd/ttrpc PROTOCOL.md: each frame is a
10-byte header — payload length (u32 BE), stream id (u32 BE), message
type (u8: 1=request, 2=response), flags (u8) — followed by a protobuf
``Request``/``Response`` envelope (protos/ttrpc/ttrpc.proto).  Client
streams use odd ids.  Max payload 4 MiB.

Server and client here each own one byte-stream connection (an NRI mux
logical conn), so the implementation is a plain blocking read loop —
no stream interleaving is needed for NRI's unary-only surface.
"""

import itertools
import logging
import struct
import threading
from typing import Callable, Dict, Tuple

from container_engine_accelerators_tpu.nri import ttrpc_pb2

log = logging.getLogger(__name__)

MESSAGE_HEADER_LEN = 10
MESSAGE_LENGTH_MAX = 4 << 20
TYPE_REQUEST = 0x1
TYPE_RESPONSE = 0x2

# google.rpc codes used in responses
CODE_OK = 0
CODE_UNKNOWN = 2
CODE_UNIMPLEMENTED = 12


class TtrpcError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"ttrpc error {code}: {message}")
        self.code = code


def read_frame(conn) -> Tuple[int, int, int, bytes]:
    """Read one frame: (stream_id, type, flags, payload).  Raises EOFError
    on clean connection close."""
    header = conn.read_exact(MESSAGE_HEADER_LEN)
    length, stream_id = struct.unpack(">II", header[:8])
    msg_type, flags = header[8], header[9]
    if length > MESSAGE_LENGTH_MAX:
        raise TtrpcError(CODE_UNKNOWN, f"frame length {length} exceeds maximum")
    payload = conn.read_exact(length) if length else b""
    return stream_id, msg_type, flags, payload


def write_frame(conn, stream_id: int, msg_type: int, payload: bytes) -> None:
    header = struct.pack(">IIBB", len(payload), stream_id, msg_type, 0)
    conn.write(header + payload)


# Handler: bytes (request payload) -> bytes (response payload)
Handler = Callable[[bytes], bytes]


class TtrpcServer:
    """Serves unary requests on one connection until EOF."""

    def __init__(self, conn):
        self.conn = conn
        self._handlers: Dict[Tuple[str, str], Handler] = {}
        self._write_lock = threading.Lock()

    def register(self, service: str, method: str, handler: Handler) -> None:
        self._handlers[(service, method)] = handler

    def serve(self) -> None:
        """Blocking serve loop; returns on connection close."""
        while True:
            try:
                stream_id, msg_type, _, payload = read_frame(self.conn)
            except (EOFError, OSError):
                return
            if msg_type != TYPE_REQUEST:
                log.warning("ignoring unexpected frame type %d", msg_type)
                continue
            req = ttrpc_pb2.Request.FromString(payload)
            resp = ttrpc_pb2.Response()
            handler = self._handlers.get((req.service, req.method))
            if handler is None:
                resp.status.code = CODE_UNIMPLEMENTED
                resp.status.message = f"unknown method {req.service}.{req.method}"
            else:
                try:
                    resp.payload = handler(req.payload)
                    resp.status.code = CODE_OK
                except Exception as e:  # deliberately broad: RPC boundary
                    log.exception("%s.%s handler failed", req.service, req.method)
                    resp.status.code = CODE_UNKNOWN
                    resp.status.message = str(e)
            with self._write_lock:
                write_frame(self.conn, stream_id, TYPE_RESPONSE,
                            resp.SerializeToString())


class TtrpcClient:
    """Unary ttrpc client owning one connection (one call at a time)."""

    def __init__(self, conn):
        self.conn = conn
        self._ids = itertools.count(1, 2)  # client streams are odd
        self._lock = threading.Lock()

    def call(self, service: str, method: str, payload: bytes,
             timeout_nano: int = 0) -> bytes:
        req = ttrpc_pb2.Request(
            service=service, method=method, payload=payload,
            timeout_nano=timeout_nano,
        )
        with self._lock:
            stream_id = next(self._ids)
            write_frame(self.conn, stream_id, TYPE_REQUEST,
                        req.SerializeToString())
            while True:
                got_id, msg_type, _, data = read_frame(self.conn)
                if msg_type != TYPE_RESPONSE or got_id != stream_id:
                    log.warning("discarding frame type=%d stream=%d",
                                msg_type, got_id)
                    continue
                resp = ttrpc_pb2.Response.FromString(data)
                if resp.status.code != CODE_OK:
                    raise TtrpcError(resp.status.code, resp.status.message)
                return resp.payload
