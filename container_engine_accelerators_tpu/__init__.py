"""container_engine_accelerators_tpu — a TPU-native re-design of GKE's
node-level accelerator infrastructure stack.

The reference (crankshaw-google/container-engine-accelerators) is the glue
that makes NVIDIA GPUs consumable by Kubernetes pods: a kubelet device
plugin, driver installers, NCCL/GPUDirect comms stacks, a topology-aware
scheduler, MIG partitioning, GPU sharing, health monitoring and metrics.

This package provides the TPU-native equivalent of every component:

- ``deviceplugin``  — kubelet DevicePlugin v1beta1 gRPC server advertising
  ``google.com/tpu`` for ``/dev/accel*`` (ref: pkg/gpu/nvidia/).
- ``tpulib``        — NVML-analog bindings over the C++ ``tpushim`` native
  library: chip enumeration, topology, HBM stats, error-event stream
  (ref: NVML via go-nvml; pkg/gpu/nvidia/metrics/util.go:17-73).
- ``sharing``       — time-sharing / core-sharing virtual devices
  (ref: pkg/gpu/nvidia/gpusharing/).
- ``partition``     — TPU sub-slice partitioning, the MIG analog
  (ref: partition_gpu/, pkg/gpu/nvidia/mig/).
- ``health``        — error-event → Unhealthy device flow
  (ref: pkg/gpu/nvidia/health_check/).
- ``metrics``       — Prometheus duty-cycle/HBM gauges + kubelet
  PodResources join (ref: pkg/gpu/nvidia/metrics/).
- ``scheduler``     — ICI/DCN topology-aware gated-pod scheduler
  (ref: gpudirect-tcpxo/topology-scheduler/).
- ``collectives``   — XLA collectives bandwidth rig over ICI/DCN, the
  nccl-tests analog (ref: gpudirect-tcpx/nccl-test.yaml).
- ``models`` / ``ops`` / ``parallel`` — JAX/Flax workload layer (ResNet-50
  demo, pallas kernels, mesh/sharding helpers; ref: demo/).
"""

__version__ = "0.1.0"

TPU_RESOURCE_NAME = "google.com/tpu"

# Concurrency shim (analysis/lockwatch.py): when TPU_LOCKWATCH=1, patch
# the lock allocators BEFORE any submodule constructs its locks — this
# import hook is what lets `make race` instrument every production
# module (and every fleet worker subprocess, which inherits the env)
# with zero code changes.  Stdlib-only at import; a no-op otherwise.
import os as _os

if _os.environ.get("TPU_LOCKWATCH") == "1":
    from container_engine_accelerators_tpu.analysis import (
        lockwatch as _lockwatch,
    )

    _lockwatch.install()
