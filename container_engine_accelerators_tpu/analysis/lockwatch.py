"""Dynamic lock-order race detector — the ``go test -race`` analog.

Activated by ``TPU_LOCKWATCH=1`` (the package ``__init__`` installs the
shim on import, so any process that imports the stack — pytest, fleet
workers, CLIs — is covered with **no production code changes**), this
module monkey-patches ``threading.Lock``/``threading.RLock`` with
instrumented wrappers and watches three hazard classes:

- **Lock-order inversions.**  Every acquisition made while other locks
  are held adds an edge ``held-site -> acquired-site`` to a global
  lock-order graph (sites are ``file:line`` of the lock's construction,
  so all instances of one structural lock share a node, exactly like
  lockdep's lock classes).  A cycle in that graph — the classic ABBA —
  is a potential deadlock even if this run never interleaved badly.
  False-positive suppression: an opposing edge pair observed while both
  threads held a common **gate** lock cannot interleave and is reported
  under ``suppressed``, not ``inversions``; same-site self-edges (two
  instances of one lock class nested) are reported informationally
  under ``same_site_nesting`` because the graph cannot orient them.

- **Blocking calls under a lock.**  Socket sends/receives/accepts/
  connects on blocking sockets, ``subprocess`` waits, and sleeps of at
  least ``TPU_LOCKWATCH_SLEEP_MS`` (default 10) made while holding any
  watched lock.  Deliberate serialize-a-stream locks (the NRI trunk
  mux, PyXferd's per-peer streams) annotate with
  :func:`blocking_ok` — those sightings land in ``allowed`` with their
  reason, keeping the gate's ``blocking`` count honest.

- **Acquisition stacks.**  The first sighting of every edge and every
  blocking call records a trimmed stack, so the JSONL report points at
  code, not just at lock names.

Scope: only locks *constructed* from first-party code (this repo's
files) are wrapped; stdlib/third-party lock sites (logging, queue,
prometheus, jax) get real locks, which keeps the graph about OUR
ordering contracts and the overhead off foreign hot paths.

Reporting: findings feed ``counters`` (``analysis.lockwatch.*`` — the
flight recorder snapshots them with everything else) and a
machine-readable JSONL report written at process exit when
``TPU_LOCKWATCH_REPORT`` names a file (multi-process runs append; the
checker sums).  ``python -m container_engine_accelerators_tpu.analysis.
lockwatch --check <report>`` is the gate half: exit 0 clean, 1 on any
inversion or unallowed blocking call, 2 on a missing/corrupt report.

Kept stdlib-only at import (counters are imported lazily at finding
time) so installing the shim from the package ``__init__`` cannot
recurse into modules whose locks it is about to wrap.
"""

import atexit
import json
import os
import subprocess
import socket
import sys
import threading
import time
import traceback
from contextlib import contextmanager
from typing import Dict, List, Optional, Set, Tuple

LOCKWATCH_ENV = "TPU_LOCKWATCH"
REPORT_ENV = "TPU_LOCKWATCH_REPORT"
SLEEP_MS_ENV = "TPU_LOCKWATCH_SLEEP_MS"
DEFAULT_SLEEP_MS = 10.0
STACK_LIMIT = 16

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_THIS_FILE = os.path.abspath(__file__)

# Originals, captured at import so install/uninstall are idempotent and
# the instrumentation's own state lock can never be a watched lock.
_RealLock = threading.Lock
_RealRLock = threading.RLock
_real_sleep = time.sleep
_real_popen_wait = subprocess.Popen.wait
_SOCK_METHODS = ("send", "sendall", "sendmsg", "recv", "recv_into",
                 "recvfrom", "accept", "connect")
_real_sock = {m: getattr(socket.socket, m) for m in _SOCK_METHODS}

# Exact plumbing files whose frames are instrumentation noise, never
# user code.  Matched by full path — a *suffix* match would also eat
# first-party files like tests/test_lockwatch.py.
_FRAME_SKIP = frozenset({
    _THIS_FILE,
    os.path.abspath(threading.__file__),
})
_CALLSITE_SKIP = _FRAME_SKIP | frozenset({
    os.path.abspath(socket.__file__),
    os.path.abspath(subprocess.__file__),
})

_active = False
_installed = False
_state = _RealLock()  # guards the graph + finding stores (leaf, unwatched)
_edges: Dict[Tuple[str, str], dict] = {}
_blocking: Dict[Tuple[str, str, str], dict] = {}
_allowed: Dict[Tuple[str, str, str], dict] = {}
_inv_counted = 0  # inversions already fed to the counter (delta base)
_tls = threading.local()


def _tstate():
    st = getattr(_tls, "state", None)
    if st is None:
        st = _tls.state = {"held": [], "guard": False, "allow": []}
    return st


def _sleep_threshold_s() -> float:
    """Sleeps under a lock shorter than this are backoff idiom, not a
    hazard; malformed values degrade to the default (the
    TPU_FAULT_SPEC rule)."""
    raw = os.environ.get(SLEEP_MS_ENV)
    if raw is None:
        return DEFAULT_SLEEP_MS / 1e3
    try:
        ms = float(raw)
        if not ms >= 0:
            raise ValueError("threshold must be >= 0")
        return ms / 1e3
    except ValueError:
        return DEFAULT_SLEEP_MS / 1e3


def _shorten(path: str) -> str:
    """Repo-relative path for sites and stacks — stable across hosts."""
    ap = os.path.abspath(path)
    if ap.startswith(_REPO_ROOT + os.sep):
        return ap[len(_REPO_ROOT) + 1:]
    return path


def _is_first_party(path: str) -> bool:
    ap = os.path.abspath(path)
    return (ap.startswith(_REPO_ROOT + os.sep)
            and ap != _THIS_FILE)


def _construction_site() -> Optional[str]:
    """``file:line`` of the frame that called ``threading.Lock()`` —
    the lock's class identity.  None for non-first-party sites (those
    get real locks)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) not in _FRAME_SKIP:
            if _is_first_party(fn):
                return f"{_shorten(fn)}:{f.f_lineno}"
            return None
        f = f.f_back
    return None


def _stack() -> List[str]:
    """Trimmed, repo-relative acquisition stack (instrumentation and
    interpreter plumbing frames dropped)."""
    out = []
    for fr in traceback.extract_stack(limit=STACK_LIMIT):
        if os.path.abspath(fr.filename) in _FRAME_SKIP:
            continue
        out.append(f"{_shorten(fr.filename)}:{fr.lineno} {fr.name}")
    return out


def _callsite() -> str:
    """First non-instrumentation frame — the dedup key for blocking
    findings (one finding per code location, with a count)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) not in _CALLSITE_SKIP:
            return f"{_shorten(fn)}:{f.f_lineno}"
        f = f.f_back
    return "?"


def _inc(name: str, n: int = 1) -> None:
    """Lazy counters.inc — imported at finding time so this module's
    import (from the package __init__, before anything else) never
    drags obs/ in early.  Guarded: metric emission must not feed the
    graph it is reporting on."""
    st = _tstate()
    if st["guard"]:
        return
    st["guard"] = True
    try:
        from container_engine_accelerators_tpu.metrics import counters
        counters.inc(name, n)
    except Exception:  # lint: disable=swallowed-exception
        pass  # the detector's reporting must never break the detected
    finally:
        st["guard"] = False


# ---------------------------------------------------------------------------
# the wrappers
# ---------------------------------------------------------------------------


class _Held:
    __slots__ = ("lock", "count")

    def __init__(self, lock):
        self.lock = lock
        self.count = 1


def _note_acquired(lock: "_WatchedLock") -> None:
    st = _tstate()
    if st["guard"]:
        return
    held = st["held"]
    for h in held:
        if h.lock is lock:
            h.count += 1  # reentrant re-acquire: no new edges
            return
    if held:
        st["guard"] = True
        try:
            _record_edges(held, lock)
        finally:
            st["guard"] = False
    held.append(_Held(lock))


def _note_released(lock: "_WatchedLock") -> None:
    held = _tstate()["held"]
    for i in range(len(held) - 1, -1, -1):
        if held[i].lock is lock:
            held[i].count -= 1
            if held[i].count <= 0:
                del held[i]
            return


def _record_edges(held: List[_Held], acquired: "_WatchedLock") -> None:
    """One edge per distinct held site -> the acquired site, carrying
    the gate set (other locks held at this sighting), the thread, and
    a first-sighting stack."""
    sites = [h.lock._site for h in held]
    dst = acquired._site
    tname = threading.current_thread().name
    stack = None
    with _state:
        for i, src in enumerate(sites):
            guards = set(sites[:i] + sites[i + 1:])
            e = _edges.get((src, dst))
            if e is None:
                if stack is None:
                    stack = _stack()
                _edges[(src, dst)] = {
                    "guards": guards, "threads": {tname},
                    "count": 1, "stack": stack,
                }
            else:
                e["guards"] &= guards
                e["threads"].add(tname)
                e["count"] += 1


class _WatchedLock:
    """Instrumented ``threading.Lock``: real lock + order bookkeeping."""

    _reentrant = False

    def __init__(self, site: str):
        self._real = _RealLock()
        self._site = site

    def acquire(self, blocking=True, timeout=-1):
        ok = self._real.acquire(blocking, timeout)
        if ok:
            _note_acquired(self)
        return ok

    def release(self):
        _note_released(self)
        self._real.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._real.locked()

    def __repr__(self):
        return f"<lockwatch {type(self).__name__} site={self._site}>"


class _WatchedRLock(_WatchedLock):
    """Instrumented ``threading.RLock`` — also speaks the private
    Condition protocol (``_is_owned``/``_release_save``/
    ``_acquire_restore``) so ``threading.Condition(watched_rlock)``
    keeps working, with the bookkeeping released across waits exactly
    like the lock itself."""

    _reentrant = True

    def __init__(self, site: str):
        self._real = _RLock_orig()
        self._site = site

    def _is_owned(self):
        return self._real._is_owned()

    def _release_save(self):
        st = _tstate()
        count = 0
        for i in range(len(st["held"]) - 1, -1, -1):
            if st["held"][i].lock is self:
                count = st["held"][i].count
                del st["held"][i]
                break
        return (self._real._release_save(), count)

    def _acquire_restore(self, state):
        inner, count = state
        self._real._acquire_restore(inner)
        if count:
            held = _tstate()["held"]
            h = _Held(self)
            h.count = count
            held.append(h)


def _RLock_orig():
    # threading.RLock may itself have been re-bound by install(); the
    # captured original is the only safe allocator here.
    return _RealRLock()


def _lock_factory():
    if _active:
        site = _construction_site()
        if site is not None:
            return _WatchedLock(site)
    return _RealLock()


def _rlock_factory():
    if _active:
        site = _construction_site()
        if site is not None:
            return _WatchedRLock(site)
    return _RealRLock()


# ---------------------------------------------------------------------------
# blocking-call detection
# ---------------------------------------------------------------------------


def _note_blocking(call: str, seconds: Optional[float] = None) -> None:
    st = _tstate()
    if st["guard"] or not st["held"]:
        return
    locks = tuple(h.lock._site for h in st["held"])
    st["guard"] = True
    try:
        site = _callsite()
        key = (call, site, "+".join(locks))
        tname = threading.current_thread().name
        if st["allow"]:
            store, counter = _allowed, "analysis.lockwatch.allowed"
            reason = st["allow"][-1]
        else:
            store, counter = _blocking, "analysis.lockwatch.blocking"
            reason = None
        with _state:
            f = store.get(key)
            if f is None:
                f = store[key] = {
                    "call": call, "site": site, "locks": list(locks),
                    "threads": {tname}, "count": 0, "stack": _stack(),
                }
                if reason is not None:
                    f["reason"] = reason
                if seconds is not None:
                    f["seconds"] = seconds
                new = True
            else:
                f["threads"].add(tname)
                new = False
            f["count"] += 1
    finally:
        st["guard"] = False
    if new:
        _inc(counter)


@contextmanager
def blocking_ok(reason: str):
    """Annotate a deliberate blocking-under-lock region (a lock whose
    whole purpose is serializing one stream's writes).  Sightings
    inside land in the report's ``allowed`` list — named, counted,
    visible — instead of failing the gate.  Free when the shim is
    inactive."""
    if not _active:
        yield
        return
    st = _tstate()
    st["allow"].append(reason)
    try:
        yield
    finally:
        st["allow"].pop()


def _watched_sleep(seconds):
    try:
        if _active and seconds >= _sleep_threshold_s():
            _note_blocking("time.sleep", seconds=seconds)
    except TypeError:
        pass
    return _real_sleep(seconds)


def _watched_popen_wait(self, timeout=None):
    if _active:
        _note_blocking("subprocess.wait")
    return _real_popen_wait(self, timeout=timeout)


def _make_sock_wrapper(name, orig):
    def wrapper(self, *args, **kwargs):
        if _active:
            try:
                blocking = self.gettimeout() != 0
            except OSError:
                blocking = True
            if blocking:
                _note_blocking(f"socket.{name}")
        return orig(self, *args, **kwargs)

    wrapper.__name__ = name
    return wrapper


# ---------------------------------------------------------------------------
# install / report / gate
# ---------------------------------------------------------------------------


def enabled(env=None) -> bool:
    env = env if env is not None else os.environ
    return env.get(LOCKWATCH_ENV) == "1"


def install() -> bool:
    """Arm the shim: patch the lock allocators and the blocking-call
    surfaces, and register the exit-time report writer.  Idempotent;
    returns True when newly installed."""
    global _active, _installed
    if _installed:
        _active = True
        return False
    _installed = True
    _active = True
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    time.sleep = _watched_sleep
    subprocess.Popen.wait = _watched_popen_wait
    for m in _SOCK_METHODS:
        setattr(socket.socket, m, _make_sock_wrapper(m, _real_sock[m]))
    atexit.register(_atexit_report)
    return True


def uninstall() -> None:
    """Restore every patched surface.  Locks handed out while active
    keep their bookkeeping (release still balances), but no new edges
    or findings are recorded."""
    global _active, _installed
    _active = False
    _installed = False
    threading.Lock = _RealLock
    threading.RLock = _RealRLock
    time.sleep = _real_sleep
    subprocess.Popen.wait = _real_popen_wait
    for m in _SOCK_METHODS:
        setattr(socket.socket, m, _real_sock[m])


def reset() -> None:
    """Drop the graph and the finding stores — test isolation."""
    global _inv_counted
    with _state:
        _edges.clear()
        _blocking.clear()
        _allowed.clear()
        _inv_counted = 0


def _cycles() -> Tuple[List[dict], List[dict], List[dict]]:
    """(inversions, suppressed, same_site_nesting) from the edge set.

    Two-node cycles (the ABBA shape) are judged pairwise; a pair whose
    opposing sightings always shared a common gate lock cannot
    interleave and is suppressed.  Larger strongly-connected components
    are reported whole, with the same all-edges gate test."""
    with _state:
        edges = {k: {"guards": set(v["guards"]),
                     "threads": set(v["threads"]),
                     "count": v["count"], "stack": list(v["stack"])}
                 for k, v in _edges.items()}
    inversions: List[dict] = []
    suppressed: List[dict] = []
    nesting: List[dict] = []
    pair_nodes: Set[str] = set()
    for (src, dst), e in sorted(edges.items()):
        if src == dst:
            nesting.append({"site": src, "count": e["count"],
                            "threads": sorted(e["threads"]),
                            "stack": e["stack"]})
            continue
        if (dst, src) in edges and src < dst:
            rev = edges[(dst, src)]
            entry = {
                "cycle": [src, dst],
                "threads": sorted(e["threads"] | rev["threads"]),
                "counts": {f"{src}->{dst}": e["count"],
                           f"{dst}->{src}": rev["count"]},
                "stacks": {f"{src}->{dst}": e["stack"],
                           f"{dst}->{src}": rev["stack"]},
            }
            gates = e["guards"] & rev["guards"]
            pair_nodes.update((src, dst))
            if gates:
                entry["gates"] = sorted(gates)
                suppressed.append(entry)
            else:
                inversions.append(entry)
    # Longer cycles: SCCs of the remaining graph (pairwise cycles are
    # already judged above; exclude their nodes so one ABBA does not
    # also surface as its enclosing component).
    adj: Dict[str, Set[str]] = {}
    for (src, dst) in edges:
        if src != dst and src not in pair_nodes and dst not in pair_nodes:
            adj.setdefault(src, set()).add(dst)
            adj.setdefault(dst, set())
    for comp in _sccs(adj):
        if len(comp) < 2:
            continue
        comp_edges = [(s, d) for (s, d) in edges
                      if s in comp and d in comp and s != d]
        gates = None
        threads: Set[str] = set()
        for k in comp_edges:
            g = edges[k]["guards"]
            gates = set(g) if gates is None else gates & g
            threads |= edges[k]["threads"]
        entry = {
            "cycle": sorted(comp),
            "threads": sorted(threads),
            "counts": {f"{s}->{d}": edges[(s, d)]["count"]
                       for (s, d) in comp_edges},
            "stacks": {f"{s}->{d}": edges[(s, d)]["stack"]
                       for (s, d) in comp_edges},
        }
        if gates:
            entry["gates"] = sorted(gates)
            suppressed.append(entry)
        else:
            inversions.append(entry)
    return inversions, suppressed, nesting


def _sccs(adj: Dict[str, Set[str]]) -> List[List[str]]:
    """Iterative Tarjan — the graph is tiny but recursion limits are
    not a failure mode a detector gets to have."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]
    for root in adj:
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on.add(nxt)
                    work.append((nxt, iter(sorted(adj.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)
    return out


def findings() -> dict:
    """Everything the detector knows, as one JSON-ready blob.
    Idempotent for the counter: only inversions NEW since the last
    call are fed to it (assert_clean + the atexit report must not
    double-count one finding)."""
    global _inv_counted
    inversions, suppressed, nesting = _cycles()
    if len(inversions) > _inv_counted:
        _inc("analysis.lockwatch.inversions",
             len(inversions) - _inv_counted)
        _inv_counted = len(inversions)

    def _flat(store):
        with _state:
            return [dict(v, threads=sorted(v["threads"]))
                    for _, v in sorted(store.items())]

    with _state:
        n_edges = len(_edges)
    return {
        "inversions": inversions,
        "suppressed": suppressed,
        "same_site_nesting": nesting,
        "blocking": _flat(_blocking),
        "allowed": _flat(_allowed),
        "edges": n_edges,
    }


def assert_clean() -> None:
    """Raise AssertionError on any gate-failing finding — the
    in-process hook for tests."""
    f = findings()
    problems = []
    if f["inversions"]:
        problems.append(f"{len(f['inversions'])} lock-order inversion(s)")
    if f["blocking"]:
        problems.append(f"{len(f['blocking'])} blocking call(s) under "
                        f"a lock")
    assert not problems, (
        f"lockwatch: {', '.join(problems)}: "
        + json.dumps({k: f[k] for k in ('inversions', 'blocking')},
                     default=sorted)
    )


def write_report(path: str) -> dict:
    """Append this process's findings to ``path`` as JSONL: one
    summary line (``{"lockwatch": 1, ...counts, "pid": ...}``) then
    one line per finding, each tagged with its kind.  Multi-process
    runs (fleet workers) append to the same file; the checker sums."""
    blob = findings()
    lines = [json.dumps({
        "lockwatch": 1, "pid": os.getpid(),
        "edges": blob["edges"],
        "inversions": len(blob["inversions"]),
        "blocking": len(blob["blocking"]),
        "allowed": len(blob["allowed"]),
        "suppressed": len(blob["suppressed"]),
        "same_site_nesting": len(blob["same_site_nesting"]),
    })]
    for kind in ("inversions", "suppressed", "same_site_nesting",
                 "blocking", "allowed"):
        for entry in blob[kind]:
            lines.append(json.dumps(dict(entry, kind=kind),
                                    default=sorted))
    with open(path, "a") as fh:
        fh.write("\n".join(lines) + "\n")
    return blob


def _atexit_report() -> None:
    path = os.environ.get(REPORT_ENV)
    if not path or not _active:
        return
    try:
        write_report(path)
    except OSError:  # pragma: no cover - a bad path must not mask exit
        pass


def check_report(path: str) -> Tuple[int, dict]:
    """Read an (appended, multi-process) JSONL report; return
    (exit_code, totals).  Exit contract: 0 clean, 1 findings, 2
    missing/corrupt report (an internal error, not a verdict)."""
    totals = {"processes": 0, "edges": 0, "inversions": 0,
              "blocking": 0, "allowed": 0, "suppressed": 0,
              "same_site_nesting": 0}
    details: List[dict] = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if rec.get("lockwatch") == 1:
                    totals["processes"] += 1
                    for k in ("edges", "inversions", "blocking",
                              "allowed", "suppressed",
                              "same_site_nesting"):
                        totals[k] += int(rec.get(k, 0))
                elif rec.get("kind") in ("inversions", "blocking"):
                    details.append(rec)
    except (OSError, ValueError) as e:
        return 2, {"error": str(e), "path": path}
    if totals["processes"] == 0:
        return 2, dict(totals, error="no lockwatch summary lines "
                                     "(did the run have "
                                     "TPU_LOCKWATCH=1?)", path=path)
    code = 1 if (totals["inversions"] or totals["blocking"]) else 0
    return code, dict(totals, details=details)


def main(argv: Optional[List[str]] = None) -> int:
    """``--check <report.jsonl>`` gate CLI (the ``make race`` tail)."""
    import argparse

    parser = argparse.ArgumentParser(
        description="lockwatch report checker: exit 0 clean, 1 on "
                    "inversions/blocking-under-lock, 2 on a bad report")
    parser.add_argument("--check", required=True, metavar="REPORT",
                        help="JSONL report written under "
                             "TPU_LOCKWATCH_REPORT")
    args = parser.parse_args(argv)
    code, totals = check_report(args.check)
    print(json.dumps(totals, indent=2, default=sorted))
    if code == 0:
        print(f"lockwatch: clean ({totals['processes']} process(es), "
              f"{totals['edges']} edge(s), "
              f"{totals['allowed']} allowed blocking site(s), "
              f"{totals['suppressed']} gate-suppressed pair(s))")
    elif code == 1:
        print(f"lockwatch: FAIL — {totals['inversions']} inversion(s), "
              f"{totals['blocking']} blocking-under-lock finding(s)",
              file=sys.stderr)
    else:
        print(f"lockwatch: internal error: {totals.get('error')}",
              file=sys.stderr)
    return code


if __name__ == "__main__":  # pragma: no cover - exercised via CLI tests
    sys.exit(main())
