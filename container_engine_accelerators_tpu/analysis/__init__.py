"""Concurrency-correctness toolchain: the stack's ``-race`` analog.

The reference repo runs Go's race detector on every unit suite
(ref Makefile:20-36, ``test = unit suite with race detection``); this
package is the Python port's equivalent, grown after the pipelined DCN
data plane, PyXferd, the fleet coordinator, and the metric server
crossed fifteen thread-spawn sites and eighteen lock sites with no
tooling watching them:

- ``lockwatch`` — a dynamic lock-order race detector: instrumented
  ``threading.Lock``/``RLock`` wrappers (monkey-patch shim, activated
  by ``TPU_LOCKWATCH=1`` — production modules need no code changes)
  that record per-thread acquisition stacks, build a cross-thread
  lock-order graph, and report order cycles (potential deadlock /
  ABBA inversion) plus blocking calls made while holding a lock
  (socket IO, ``subprocess`` waits, long sleeps).  ``make race`` runs
  the DCN/fleet/obs suites under it and gates on zero findings.

- ``lint`` — an AST invariant engine enforcing the project rules
  previous PRs learned the hard way: hardened sends only
  (``utils/netio``), injectable clocks in clock-sensitive modules,
  no bare/broad-swallowed excepts, explicit ``daemon=`` decisions on
  every thread, no fire-and-forget non-daemon spawns, and no
  undocumented counter/gauge/histogram/series names.  ``make lint``
  (``cmd/agent_lint.py``) gates on zero findings; inline
  ``# lint: disable=<rule>`` suppressions must name their rule.
"""
