"""AST invariant lint engine: the project rules, as one registry.

Previous PRs each learned an invariant the hard way and pinned it with
an ad-hoc test (the no-undocumented-counters README lint buried in
``tests/test_obs.py`` was the first); this module generalizes that into
a pluggable rule framework over the package's ASTs so every invariant
lives in ONE registry, runs from ONE gate (``make lint`` /
``cmd/agent_lint.py``), and fails with a ``file:line`` finding instead
of a tribal-knowledge review comment.

Rules (each a ``@rule`` function; ``--list-rules`` prints this table):

- ``raw-socket-send``     — ``.sendall(...)`` outside ``utils/netio``:
  the bench rig's loopback stack truncates large single-syscall
  payloads, so every send must ride the capped short-write-proof
  helpers (the PR 6 lesson, learned at ``nri/mux.py``).
- ``naive-clock``         — wall-clock reads (``time.time()``,
  ``datetime.now()``) in modules whose contract is injectable clocks
  (``obs/timeseries.py``, ``utils/retry.py``): tests drive those
  clocks; a stray wall read re-introduces sleep-based flakiness.
- ``bare-except``         — ``except:`` swallows ``KeyboardInterrupt``
  and ``SystemExit``; name the exceptions.
- ``swallowed-exception`` — a broad catch (``Exception`` or wider)
  whose whole body is ``pass``/``continue``: in a daemon thread body
  that silently eats the error that should have fed a counter or the
  flight recorder.
- ``thread-daemon``       — ``threading.Thread(...)`` without an
  explicit ``daemon=``: lifetime must be a decision, not a default.
- ``unjoined-thread``     — ``threading.Thread(...).start()`` as one
  expression with ``daemon`` not ``True``: a non-daemon thread nobody
  holds a reference to can never be joined and will wedge interpreter
  shutdown.
- ``undocumented-metric`` — every literal ``counters.inc`` /
  ``histo.observe`` / ``trace.span(histogram=...)`` /
  ``timeseries.record|gauge|gauge_add`` name, and every gauge family
  the MetricServer exports, must appear backticked in the README
  metrics tables (placeholder segments — ``{x}`` in source, ``<x>`` in
  the README — compare as wildcards).
- ``undocumented-span``   — every span-name literal passed to
  ``trace.span`` / ``trace.event`` / ``trace.record_span`` must
  appear backticked in the README span table (same registry and
  placeholder machinery as ``undocumented-metric``): the span
  vocabulary IS an API — ``agent_trace --critical-path``, the
  critical-path shapes, and the fleet report all key on it.

Suppressions are inline and must name their rule:
``# lint: disable=<rule>[,<rule>...]`` on the finding's line.

Exit-code contract (the CI gate): 0 clean, 1 findings, 2 internal
error (unreadable path, syntax error in a linted file).  Stdlib-only,
like everything else in analysis/.
"""

import ast
import dataclasses
import os
import re
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_ROOTS = ("container_engine_accelerators_tpu", "cmd")

# Modules whose public functions take injectable clocks (``now=`` /
# ``sleep=`` / ``monotonic=`` parameters) — wall-clock reads inside
# them break the test contract.  Matched by repo-relative path suffix.
CLOCK_MODULES = (
    "container_engine_accelerators_tpu/obs/timeseries.py",
    "container_engine_accelerators_tpu/utils/retry.py",
)

# The one module allowed to touch raw socket send primitives.
NETIO_MODULES = (
    "container_engine_accelerators_tpu/utils/netio.py",
)

METRICS_SOURCE = "container_engine_accelerators_tpu/metrics/metrics.py"

SUPPRESS_RE = re.compile(r"#\s*lint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Config:
    """Where to look and which modules carry special contracts.  The
    defaults lint the shipped package; tests override with synthetic
    roots/snippets."""

    def __init__(self,
                 roots: Optional[Iterable[str]] = None,
                 repo_root: str = REPO_ROOT,
                 readme: Optional[str] = None,
                 clock_modules: Iterable[str] = CLOCK_MODULES,
                 netio_modules: Iterable[str] = NETIO_MODULES,
                 metrics_source: Optional[str] = None):
        self.repo_root = repo_root
        self.roots = [os.path.join(repo_root, r) if not os.path.isabs(r)
                      else r
                      for r in (roots if roots is not None
                                else DEFAULT_ROOTS)]
        self.readme = (readme if readme is not None
                       else os.path.join(repo_root, "README.md"))
        self.clock_modules = tuple(clock_modules)
        self.netio_modules = tuple(netio_modules)
        if metrics_source is None:
            cand = os.path.join(repo_root, METRICS_SOURCE)
            metrics_source = cand if os.path.exists(cand) else ""
        self.metrics_source = metrics_source

    def relpath(self, path: str) -> str:
        ap = os.path.abspath(path)
        if ap.startswith(self.repo_root + os.sep):
            return ap[len(self.repo_root) + 1:].replace(os.sep, "/")
        return ap.replace(os.sep, "/")

    def _suffix_match(self, path: str, entries: Iterable[str]) -> bool:
        rel = self.relpath(path)
        return any(rel == e or rel.endswith("/" + e) for e in entries)

    def is_clock_module(self, path: str) -> bool:
        return self._suffix_match(path, self.clock_modules)

    def is_netio_module(self, path: str) -> bool:
        return self._suffix_match(path, self.netio_modules)


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    doc: str
    check: Callable
    project: bool = False  # project rules see the whole file set once


RULES: Dict[str, Rule] = {}


def rule(name: str, doc: str, project: bool = False):
    def register(fn):
        RULES[name] = Rule(name, doc, fn, project)
        return fn
    return register


# -- AST helpers -------------------------------------------------------------


def _literal_name(node) -> Optional[str]:
    """A metric-name argument as a normalized string: plain constants
    stay themselves; f-string placeholders become ``<>`` wildcards.
    None for anything dynamic (a variable is not a *name literal*)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("<>")
        return "".join(parts)
    return None


def normalize_placeholders(name: str) -> str:
    """``fault.fired.{site}`` / ``fault.fired.<site>`` -> a comparable
    ``fault.fired.<>`` — how source-side f-strings and README-side
    placeholder rows agree on one spelling."""
    return re.sub(r"\{[^}]*\}|<[^>]*>", "<>", name)


def _attr_chain(node) -> List[str]:
    """``a.b.c`` -> ["a", "b", "c"]; empty when the base is a call or
    subscript (dynamic)."""
    out: List[str] = []
    while isinstance(node, ast.Attribute):
        out.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        out.append(node.id)
        out.reverse()
        return out
    return []


def _is_thread_call(call: ast.Call) -> bool:
    chain = _attr_chain(call.func)
    return chain[-2:] == ["threading", "Thread"] or chain == ["Thread"]


def _daemon_kw(call: ast.Call) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == "daemon":
            return kw.value
    return None


# -- per-file rules ----------------------------------------------------------


@rule("raw-socket-send",
      "raw .sendall() outside utils/netio — large single-syscall sends "
      "truncate on this rig; use netio.sendall/sendall_parts")
def _raw_socket_send(tree, cfg: Config, path: str):
    if cfg.is_netio_module(path):
        return
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "sendall"):
            continue
        chain = _attr_chain(node.func)
        # netio.sendall(...) / utils.netio.sendall(...) are the fix,
        # not the finding.
        if len(chain) >= 2 and chain[-2] == "netio":
            continue
        yield Finding(
            "raw-socket-send", path, node.lineno,
            "raw .sendall() — route through "
            "utils/netio.sendall (short-write hardened, capped per "
            "syscall)")


@rule("naive-clock",
      "wall-clock read in an injectable-clock module — take now=/"
      "sleep= parameters instead")
def _naive_clock(tree, cfg: Config, path: str):
    if not cfg.is_clock_module(path):
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if chain[-2:] == ["time", "time"] or (
                chain and chain[0] == "datetime"
                and chain[-1] in ("now", "utcnow", "today")):
            yield Finding(
                "naive-clock", path, node.lineno,
                f"{'.'.join(chain)}() in a module whose contract is "
                f"injectable clocks — accept a now=/monotonic= "
                f"parameter")


@rule("bare-except",
      "bare except: swallows KeyboardInterrupt/SystemExit — name the "
      "exceptions")
def _bare_except(tree, cfg: Config, path: str):
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            yield Finding(
                "bare-except", path, node.lineno,
                "bare except: — catch explicit exception types")


_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node) -> bool:
    if type_node is None:
        return True
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(e) for e in type_node.elts)
    return False


@rule("swallowed-exception",
      "broad except whose body is only pass/continue — a daemon "
      "thread dies silently; log it or feed a counter")
def _swallowed(tree, cfg: Config, path: str):
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node.type):
            continue
        if all(isinstance(s, (ast.Pass, ast.Continue)) for s in node.body):
            yield Finding(
                "swallowed-exception", path, node.lineno,
                "broad exception silently swallowed — log, count "
                "(metrics/counters), or narrow the type")


@rule("thread-daemon",
      "threading.Thread without an explicit daemon= — thread lifetime "
      "must be a decision, not a default")
def _thread_daemon(tree, cfg: Config, path: str):
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and _is_thread_call(node)
                and _daemon_kw(node) is None):
            yield Finding(
                "thread-daemon", path, node.lineno,
                "threading.Thread(...) without daemon= — decide (and "
                "document) whether this thread may outlive its owner")


@rule("unjoined-thread",
      "threading.Thread(...).start() fire-and-forget with daemon not "
      "True — nobody can ever join it")
def _unjoined(tree, cfg: Config, path: str):
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "start"
                and isinstance(node.value.func.value, ast.Call)
                and _is_thread_call(node.value.func.value)):
            continue
        daemon = _daemon_kw(node.value.func.value)
        if (isinstance(daemon, ast.Constant) and daemon.value is True):
            continue
        yield Finding(
            "unjoined-thread", path, node.lineno,
            "non-daemon Thread(...).start() with no reference kept — "
            "keep the handle and join it, or mark daemon=True")


# -- project rule: the metric surface vs. the README -------------------------


def metric_names(files: Iterable[str]) -> Dict[str, List[Tuple[str, str,
                                                               int]]]:
    """Every literal metric name in ``files`` by category:
    ``{"counter"|"histogram"|"series": [(name, path, line), ...]}``.
    Categories map to README spellings: counters/series normalize
    f-string placeholders to wildcards, same as README ``<x>``
    segments."""
    out: Dict[str, List[Tuple[str, str, int]]] = {
        "counter": [], "histogram": [], "series": [],
    }
    for path in files:
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            arg0 = _literal_name(node.args[0]) if node.args else None
            if chain[-2:] == ["counters", "inc"] and arg0:
                out["counter"].append((arg0, path, node.lineno))
            elif chain[-2:] == ["histo", "observe"] and arg0:
                out["histogram"].append((arg0, path, node.lineno))
            elif (len(chain) >= 2 and chain[-2] == "timeseries"
                    and chain[-1] in ("record", "gauge", "gauge_add")
                    and arg0):
                out["series"].append((arg0, path, node.lineno))
            for kw in node.keywords:
                if kw.arg == "histogram":
                    name = _literal_name(kw.value)
                    if name:
                        out["histogram"].append((name, path,
                                                 node.lineno))
    return out


def span_names(files: Iterable[str]) -> List[Tuple[str, str, int]]:
    """Every literal span name passed to ``trace.span`` /
    ``trace.event`` / ``trace.record_span`` in ``files``, as
    ``(name, path, line)``.  F-string placeholders normalize to
    wildcards like metric names; dynamic names are not literals and
    are skipped."""
    out: List[Tuple[str, str, int]] = []
    for path in files:
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if chain[-2:] not in (["trace", "span"],
                                  ["trace", "event"],
                                  ["trace", "record_span"]):
                continue
            name = _literal_name(node.args[0]) if node.args else None
            if name:
                out.append((name, path, node.lineno))
    return out


@rule("undocumented-span",
      "span-name literal missing from the README span table — the "
      "span vocabulary is an API (critical-path shapes, agent_trace) "
      "and every name is documented",
      project=True)
def _undocumented_span(files: List[str], cfg: Config):
    documented = documented_tokens(cfg.readme)
    # Every sighting is its own finding (line-scoped suppressions,
    # same rationale as undocumented-metric).
    for name, path, line in span_names(files):
        norm = normalize_placeholders(name)
        if norm in documented:
            continue
        yield Finding(
            "undocumented-span", cfg.relpath(path), line,
            f"span name {name!r} is not documented in "
            f"{os.path.basename(cfg.readme)} — add it to the span "
            f"table (placeholders may be spelled <x>)")


def gauge_families(metrics_source: str) -> Set[str]:
    """Gauge families straight from the exporter source — the
    ``g("name"`` helper calls in ``MetricServer.__init__``."""
    if not metrics_source or not os.path.exists(metrics_source):
        return set()
    src = open(metrics_source).read()
    return set(re.findall(r"\bg\(\s*\n?\s*\"([a-z_]+)\"", src))


def documented_tokens(readme_path: str) -> Set[str]:
    """Every backticked token in the README, placeholder-normalized —
    the document side of the documented-or-fail bar."""
    try:
        readme = open(readme_path).read()
    except OSError:
        return set()
    return {normalize_placeholders(tok)
            for tok in re.findall(r"`([^`\n]+)`", readme)}


@rule("undocumented-metric",
      "counter/histogram/series/gauge-family name missing from the "
      "README metrics tables — every exported name is documented",
      project=True)
def _undocumented_metric(files: List[str], cfg: Config):
    documented = documented_tokens(cfg.readme)
    names = metric_names(files)
    # Every sighting is its own finding: suppressions are line-scoped,
    # so deduping by name here would let one suppressed site hide an
    # un-suppressed use of the same undocumented name elsewhere.
    for kind, entries in names.items():
        for name, path, line in entries:
            norm = normalize_placeholders(name)
            if norm in documented:
                continue
            yield Finding(
                "undocumented-metric", cfg.relpath(path), line,
                f"{kind} name {name!r} is not documented in "
                f"{os.path.basename(cfg.readme)} — add a metrics-table "
                f"row (placeholders may be spelled <x>)")
    for fam in sorted(gauge_families(cfg.metrics_source)):
        if fam not in documented:
            yield Finding(
                "undocumented-metric", cfg.relpath(cfg.metrics_source), 1,
                f"exported gauge family {fam!r} is not documented in "
                f"{os.path.basename(cfg.readme)}")


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------


def iter_py_files(roots: Iterable[str]) -> List[str]:
    out: List[str] = []
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, dirnames, files in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for f in sorted(files):
                if f.endswith(".py") and not f.endswith("_pb2.py"):
                    out.append(os.path.join(dirpath, f))
    return sorted(out)


def _suppressions(src: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",")
                      if r.strip()}
    return out


def lint_file(path: str, cfg: Config,
              rules: Optional[Iterable[str]] = None,
              src: Optional[str] = None,
              supp: Optional[Dict[int, Set[str]]] = None) -> List[Finding]:
    """Per-file rules over one source file, suppressions applied.
    Raises OSError/SyntaxError to the caller — an unlintable file is
    an internal error (exit 2), not a silent skip.  ``src``/``supp``
    let a caller that already read and scanned the file skip doing
    either twice."""
    if src is None:
        with open(path) as fh:
            src = fh.read()
    tree = ast.parse(src, filename=path)
    if supp is None:
        supp = _suppressions(src)
    findings: List[Finding] = []
    for r in RULES.values():
        if r.project or (rules is not None and r.name not in rules):
            continue
        for f in r.check(tree, cfg, cfg.relpath(path)):
            if r.name not in supp.get(f.line, ()):
                findings.append(f)
    return findings


def lint(cfg: Optional[Config] = None,
         rules: Optional[Iterable[str]] = None,
         ) -> Tuple[List[Finding], List[str]]:
    """The whole gate: every per-file rule over every file under
    ``cfg.roots``, then the project rules over the file set.  Returns
    (findings, internal_errors)."""
    cfg = cfg or Config()
    rules = set(rules) if rules is not None else None
    files = iter_py_files(cfg.roots)
    findings: List[Finding] = []
    errors: List[str] = []
    suppressions: Dict[str, Dict[int, Set[str]]] = {}
    for path in files:
        try:
            with open(path) as fh:
                src = fh.read()
            supp = suppressions[cfg.relpath(path)] = _suppressions(src)
            findings.extend(lint_file(path, cfg, rules, src=src,
                                      supp=supp))
        except (OSError, SyntaxError) as e:
            errors.append(f"{path}: {e}")
    for r in RULES.values():
        if not r.project or (rules is not None and r.name not in rules):
            continue
        for f in r.check(files, cfg):
            supp = suppressions.get(f.path, {})
            if r.name not in supp.get(f.line, ()):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, errors
