"""Checkpoint/resume for the training demos (orbax).

The reference delegates checkpointing to its external demo images
(``--model_dir`` to GCS, ref: demo/gpu-training/generate_job.sh:62) and
recovery to Kubernetes restart semantics (SURVEY.md §5).  A restarted
training pod therefore needs in-tree save/restore to actually resume:
this module wraps orbax so the driver checkpoints the full train state
(step, params, batch_stats, opt_state) and a rescheduled pod continues
from the last saved step instead of epoch 0.

Orbax is sharding-aware: saves stream each host's shards of a GSPMD
array, and restores lay shards out to match the target state's
shardings — so the same checkpoint round-trips across restarts of a
multi-host mesh with no gather through host 0.
"""

import logging
from typing import Optional, Tuple

import jax
import orbax.checkpoint as ocp

from container_engine_accelerators_tpu.models.train import TrainState
from container_engine_accelerators_tpu.utils import faults
from container_engine_accelerators_tpu.utils.retry import RetryPolicy

log = logging.getLogger(__name__)

# Checkpoint targets are typically GCS-fuse / NFS mounts that flap under
# node pressure; a failed interval save must not kill a training job
# that could checkpoint fine 100ms later.  Small budget: a save that
# fails 3 times is a real outage and should surface.
SAVE_RETRY = RetryPolicy(max_attempts=3, initial_backoff_s=0.2,
                         max_backoff_s=2.0)


class TrainCheckpointer:
    """Save/restore TrainState under ``directory`` keyed by step."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.manager = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def _tree(self, state: TrainState):
        # tx/apply_fn are static (pytree_node=False) and not serialized.
        return {
            "step": state.step,
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
        }

    def save(self, state: TrainState, wait: bool = False) -> None:
        step = int(jax.device_get(state.step))

        # Transient filesystem faults (and the armed ``checkpoint.save``
        # site) retry under a small budget.  A failed attempt may still
        # have committed (the error hit after orbax's atomic rename), so
        # each retry first checks whether the step already landed —
        # re-saving a recorded step raises in orbax.
        last: Optional[Exception] = None
        for attempt in SAVE_RETRY.attempts():
            try:
                # The dedupe probe sits INSIDE the try: it touches the
                # same flaky filesystem the retry exists for.
                if attempt and self.manager.latest_step() == step:
                    log.warning("checkpoint step %d landed despite the "
                                "previous attempt's error; continuing", step)
                    return
                faults.check("checkpoint.save")
                self.manager.save(
                    step, args=ocp.args.StandardSave(self._tree(state))
                )
                if wait:
                    self.manager.wait_until_finished()
                return
            except OSError as e:
                log.warning("checkpoint save attempt %d for step %d "
                            "failed: %s", attempt + 1, step, e)
                last = e
        raise last

    def restore_latest(
        self, state: TrainState
    ) -> Tuple[TrainState, Optional[int]]:
        """Restore the newest checkpoint onto ``state``'s shardings.

        Returns (state, step) — unchanged state and None when there is no
        checkpoint yet (first boot of the Job).
        """
        step = self.manager.latest_step()
        if step is None:
            return state, None
        target = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, self._tree(state)
        )
        restored = self.manager.restore(
            step, args=ocp.args.StandardRestore(target)
        )
        log.info("restored checkpoint at step %d", step)
        return (
            state.replace(
                step=restored["step"],
                params=restored["params"],
                batch_stats=restored["batch_stats"],
                opt_state=restored["opt_state"],
            ),
            step,
        )

    def close(self) -> None:
        self.manager.wait_until_finished()
        self.manager.close()
