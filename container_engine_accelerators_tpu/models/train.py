"""Sharded ResNet training step.

TPU-native replacement for the reference's TF benchmark training jobs
(demo/gpu-training/generate_job.sh:54-77): SGD momentum + cosine schedule,
cross-entropy, bf16 compute.  Parallelism is GSPMD: the step is jitted
over a (data, model) Mesh with the batch sharded on ``data`` and weights
tensor-parallel on ``model`` (parallel/mesh.py); XLA inserts the psum /
all-gather collectives over ICI — there is no NCCL/MPI analog to port.

BatchNorm statistics are computed over the *global* batch automatically:
under GSPMD every reduction in the traced program is global, so no
explicit axis_name plumbing is needed.
"""

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import FrozenDict

from container_engine_accelerators_tpu.parallel.mesh import (
    batch_sharding,
    replicated,
    shard_params,
)


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    batch_stats: Any
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    apply_fn: Callable = struct.field(pytree_node=False)


def cosine_sgd(
    base_lr: float = 0.1,
    momentum: float = 0.9,
    total_steps: int = 10_000,
    warmup_steps: int = 500,
    weight_decay: float = 1e-4,
) -> optax.GradientTransformation:
    """The demo sweep's optimizer family (batch-scaled SGD momentum)."""
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=base_lr,
        warmup_steps=warmup_steps,
        decay_steps=total_steps,
    )
    return optax.chain(
        optax.add_decayed_weights(weight_decay),
        optax.sgd(schedule, momentum=momentum, nesterov=True),
    )


def create_train_state(
    model, rng, sample_input, tx: Optional[optax.GradientTransformation] = None
) -> TrainState:
    # On accelerators, jit the init: eager flax init dispatches every op
    # individually (on the tunneled TPU backend each bounces through the
    # tunnel).  On CPU eager dispatch is cheap and XLA compile is not —
    # jitting there made tiny-model test inits 5-10x slower.
    init_fn = model.init
    if jax.default_backend() != "cpu":
        init_fn = jax.jit(model.init, static_argnames=("train",))
    variables = init_fn(rng, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", FrozenDict())
    tx = tx or cosine_sgd()
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        tx=tx,
        apply_fn=model.apply,
    )


def train_step(state: TrainState, images, labels) -> Tuple[TrainState, dict]:
    """One optimizer step; fully jittable, donate `state` for in-place HBM."""

    def loss_fn(params):
        logits, mutated = state.apply_fn(
            {"params": params, "batch_stats": state.batch_stats},
            images,
            train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()
        return loss, (logits, mutated["batch_stats"])

    (loss, (logits, new_stats)), grads = jax.value_and_grad(
        loss_fn, has_aux=True
    )(state.params)
    updates, new_opt_state = state.tx.update(
        grads, state.opt_state, state.params
    )
    new_params = optax.apply_updates(state.params, updates)
    metrics = {
        "loss": loss,
        "accuracy": jnp.mean(jnp.argmax(logits, -1) == labels),
    }
    return (
        state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
        ),
        metrics,
    )


def make_sharded_train_step(mesh, state: TrainState):
    """Jit train_step over the mesh with real dp/tp shardings.

    Returns (jitted_step, placed_state): params/opt_state laid out
    tensor-parallel, batch_stats replicated, batch sharded on data.
    """
    param_sh = shard_params(state.params, mesh)
    # Momentum/trace buffers have identical shapes to their parameters, so
    # the same shape-driven rule lays them out tensor-parallel; scalar
    # leaves (schedule counts) come out replicated.
    opt_sh = shard_params(state.opt_state, mesh)
    rep = replicated(mesh)
    state_sh = TrainState(
        step=rep,
        params=param_sh,
        batch_stats=jax.tree_util.tree_map(lambda _: rep, state.batch_stats),
        opt_state=opt_sh,
        tx=state.tx,
        apply_fn=state.apply_fn,
    )
    data_sh = batch_sharding(mesh)

    placed_state = jax.device_put(state, state_sh)
    jitted = jax.jit(
        train_step,
        in_shardings=(state_sh, data_sh, data_sh),
        out_shardings=(state_sh, rep),
        donate_argnums=(0,),
    )
    return jitted, placed_state
