"""Public re-exports for the models package."""
from container_engine_accelerators_tpu.models.resnet import ResNet, resnet

__all__ = ["ResNet", "resnet"]
