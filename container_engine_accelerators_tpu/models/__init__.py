"""Public re-exports for the models package."""
from container_engine_accelerators_tpu.models.inception import (
    InceptionV3,
    inception_v3,
)
from container_engine_accelerators_tpu.models.resnet import ResNet, resnet

__all__ = ["InceptionV3", "ResNet", "inception_v3", "resnet"]
