"""Weight quantization for serving: int8 kernels, f32 scales.

Decode reads the whole parameter set from HBM for every generated
token, so weight precision is a first-order tokens/sec lever on TPU —
the param-traffic twin of GQA's cache-traffic lever:

- ``cast_floats(params, bf16)``: 2x less traffic than f32, numerics
  near-identical (the compute path already runs bf16).
- ``quantize_params(params)``: 4x less traffic — per-output-channel
  symmetric int8 for every attention/MLP kernel, dequantized inside
  the matmul (XLA fuses the int8 load + scale into the operand read,
  so the stored int8 array is what crosses HBM).

The reference has no quantization story (its serving demo is a stock
TF-Serving pod, demo/serving/tensorflow-serving.yaml); this is
TPU-first serving design, validated hardware-free by an exactness
test: the quantized model must produce token-identical greedy decodes
to a float model loaded with the DEQUANTIZED weights.

Embedding table and RMSNorm scales stay float (the embed read is a
per-token row gather, and norm scales are vectors — neither is a
traffic term); MoE expert FFNs keep their own float path.
"""

from typing import Any, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from flax import linen as nn


def _flatten_axes(shape, axis):
    """Normalize DenseGeneral-style ``axis`` to a tuple of positive dims."""
    if isinstance(axis, int):
        axis = (axis,)
    return tuple(a % len(shape) for a in axis)


def quantize_kernel(
    w: jax.Array, contract_axes: Sequence[int]
) -> Tuple[jax.Array, jax.Array]:
    """Per-output-channel symmetric int8 quantization.

    ``contract_axes`` are the kernel's contraction (input) dims —
    explicit rather than positional, because kernels stacked by
    ``nn.scan`` carry a leading layer axis that must NOT be reduced.
    One f32 scale per remaining (layer x output) channel:
    ``scale = max|w| / 127`` over the contraction dims.
    """
    w = jnp.asarray(w, jnp.float32)
    contract_axes = tuple(contract_axes)
    amax = jnp.max(jnp.abs(w), axis=contract_axes, keepdims=True)
    scale_k = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale_k), -127, 127).astype(jnp.int8)
    return q, jnp.squeeze(scale_k, contract_axes).astype(jnp.float32)


def dequantize_kernel(
    q: jax.Array, scale: jax.Array, contract_axes: Sequence[int]
) -> jax.Array:
    """f32 kernel carrying exactly the values the quantized matmul uses."""
    return q.astype(jnp.float32) * jnp.expand_dims(
        scale, tuple(contract_axes)
    )


class QDenseGeneral(nn.Module):
    """Drop-in for ``nn.DenseGeneral(use_bias=False)`` with int8 kernel.

    Declares ``kernel_q`` (int8, the float kernel's shape) and
    ``scale`` (f32, one per output channel) and contracts exactly as
    DenseGeneral does: dequantize in f32, cast to the compute dtype,
    ``lax.dot_general`` over ``axis``.  Parameters are produced by
    :func:`quantize_params` from a trained float tree — this module's
    own initializer exists only to give ``init`` the right shapes.
    """

    features: Union[int, Sequence[int]]
    axis: Union[int, Sequence[int]] = -1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        features = (
            (self.features,) if isinstance(self.features, int)
            else tuple(self.features)
        )
        axis = _flatten_axes(x.shape, self.axis)
        in_dims = tuple(x.shape[a] for a in axis)
        kernel_shape = in_dims + features
        kernel_q = self.param(
            "kernel_q",
            lambda _, s: jnp.zeros(s, jnp.int8), kernel_shape,
        )
        scale = self.param(
            "scale", lambda _, s: jnp.ones(s, jnp.float32), features
        )
        w = dequantize_kernel(
            kernel_q, scale, range(len(axis))
        ).astype(self.dtype)
        y = jax.lax.dot_general(
            x.astype(self.dtype), w,
            ((axis, tuple(range(len(axis)))), ((), ())),
        )
        return y


def quantize_params(params) -> Any:
    """Trained float param tree -> the matching ``quant=True`` tree.

    Every module dict holding a ``kernel`` (attention q/k/v/out, MLP
    gate/up/down) becomes ``{kernel_q, scale}``; everything else
    (embed, norms, MoE experts) passes through unchanged.  Contraction
    dims are identified the way the model declares them — the ``out``
    projection contracts its (heads, head_dim) pair, every other
    kernel its first module-level dim — offset by one inside the
    ``blocks`` scan stack, whose kernels carry a leading layer axis.
    """
    def walk(tree, name="", stacked=False):
        if not isinstance(tree, dict):
            return tree
        if name == "moe":
            return tree  # MoE expert FFNs keep their float path
        stacked = stacked or name == "blocks"
        if "kernel" in tree and len(tree) == 1:
            w = tree["kernel"]
            off = 1 if stacked else 0
            n = 2 if name == "out" else 1
            q, scale = quantize_kernel(w, range(off, off + n))
            return {"kernel_q": q, "scale": scale}
        return {k: walk(v, k, stacked) for k, v in tree.items()}

    return walk(params)


def cast_floats(tree, dtype=jnp.bfloat16):
    """Cast float leaves (f32/f64) to ``dtype``; ints pass through."""
    def cast(x):
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            return jnp.asarray(x, dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)


def serving_params(params, weights: str):
    """Trained params -> serving weights (``f32`` | ``bf16`` | ``int8``).

    ``int8`` quantizes every kernel (scales stay f32) and carries the
    rest — embed, norms — in bf16; pair it with a model built with
    ``quant=True``.
    """
    if weights == "f32":
        return params
    if weights == "bf16":
        return cast_floats(params)
    if weights == "int8":
        return quantize_params(cast_floats(params))
    raise ValueError(f"unknown weights mode {weights!r}")


def param_bytes(tree) -> int:
    return sum(
        x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(tree)
    )
