"""Inception-v3 in Flax — the reference demo's second TPU model family.

The reference's TPU demo runs Inception-v3 alongside ResNet
(ref: demo/tpu-training/inception-v3-tpu.yaml:66-73, a TF 1.x TPU models
job on cloud-tpus.google.com/v2).  TPU-native re-design matching
models/resnet.py: Flax + XLA, bfloat16 compute / float32 params, NHWC,
static control flow so every mixed block fuses onto the MXU.

Architecture follows the standard Inception-v3 channel plan (stem →
3×InceptionA → InceptionB → 4×InceptionC → InceptionD → 2×InceptionE →
global pool → head); aux head omitted (inference/demo parity does not
need it and it would complicate the shared train_step).
"""

import functools
from typing import Any, Tuple

import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any


class ConvBNAct(nn.Module):
    """Conv + BatchNorm + ReLU, the Inception primitive."""

    features: int
    kernel: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"

    @nn.compact
    def __call__(self, x):
        x = self.conv(self.features, self.kernel, self.strides,
                      padding=self.padding)(x)
        x = self.norm()(x)
        return nn.relu(x)


def _pool(x, window, strides, kind="avg"):
    fn = nn.avg_pool if kind == "avg" else nn.max_pool
    return fn(x, (window, window), strides=(strides, strides),
              padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    cba: ModuleDef

    @nn.compact
    def __call__(self, x):
        b1 = self.cba(64, (1, 1))(x)
        b5 = self.cba(48, (1, 1))(x)
        b5 = self.cba(64, (5, 5))(b5)
        b3 = self.cba(64, (1, 1))(x)
        b3 = self.cba(96, (3, 3))(b3)
        b3 = self.cba(96, (3, 3))(b3)
        bp = _pool(x, 3, 1)
        bp = self.cba(self.pool_features, (1, 1))(bp)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    """Grid reduction 35x35 -> 17x17."""

    cba: ModuleDef

    @nn.compact
    def __call__(self, x):
        b3 = self.cba(384, (3, 3), strides=(2, 2))(x)
        bd = self.cba(64, (1, 1))(x)
        bd = self.cba(96, (3, 3))(bd)
        bd = self.cba(96, (3, 3), strides=(2, 2))(bd)
        bp = _pool(x, 3, 2, "max")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    """Factorized 7x7 block."""

    channels_7x7: int
    cba: ModuleDef

    @nn.compact
    def __call__(self, x):
        c7 = self.channels_7x7
        b1 = self.cba(192, (1, 1))(x)
        b7 = self.cba(c7, (1, 1))(x)
        b7 = self.cba(c7, (1, 7))(b7)
        b7 = self.cba(192, (7, 1))(b7)
        bd = self.cba(c7, (1, 1))(x)
        bd = self.cba(c7, (7, 1))(bd)
        bd = self.cba(c7, (1, 7))(bd)
        bd = self.cba(c7, (7, 1))(bd)
        bd = self.cba(192, (1, 7))(bd)
        bp = _pool(x, 3, 1)
        bp = self.cba(192, (1, 1))(bp)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    """Grid reduction 17x17 -> 8x8."""

    cba: ModuleDef

    @nn.compact
    def __call__(self, x):
        b3 = self.cba(192, (1, 1))(x)
        b3 = self.cba(320, (3, 3), strides=(2, 2))(b3)
        b7 = self.cba(192, (1, 1))(x)
        b7 = self.cba(192, (1, 7))(b7)
        b7 = self.cba(192, (7, 1))(b7)
        b7 = self.cba(192, (3, 3), strides=(2, 2))(b7)
        bp = _pool(x, 3, 2, "max")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    """Expanded-filter-bank output block."""

    cba: ModuleDef

    @nn.compact
    def __call__(self, x):
        b1 = self.cba(320, (1, 1))(x)
        b3 = self.cba(384, (1, 1))(x)
        b3 = jnp.concatenate(
            [self.cba(384, (1, 3))(b3), self.cba(384, (3, 1))(b3)], axis=-1)
        bd = self.cba(448, (1, 1))(x)
        bd = self.cba(384, (3, 3))(bd)
        bd = jnp.concatenate(
            [self.cba(384, (1, 3))(bd), self.cba(384, (3, 1))(bd)], axis=-1)
        bp = _pool(x, 3, 1)
        bp = self.cba(192, (1, 1))(bp)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    """Inception-v3 with the standard channel plan.

    ``a_blocks``/``c_blocks``/``e_blocks`` parameterize the per-stage
    repeat plan (defaults = the standard 3/4/2 architecture); tests use
    a 1/1/1 plan so the compile cost under test scales with one block of
    each type, not the full graph.
    """

    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    a_blocks: Tuple[int, ...] = (32, 64, 64)  # InceptionA pool_features
    c_blocks: Tuple[int, ...] = (128, 160, 160, 192)  # InceptionC 7x7 ch
    e_blocks: int = 2

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-3,
            dtype=self.dtype,
            axis_name=None,
        )
        cba = functools.partial(ConvBNAct, conv=conv, norm=norm)

        x = jnp.asarray(x, self.dtype)
        # Stem: 299x299x3 -> 35x35x192 ("VALID" pads dropped for SAME —
        # keeps shapes power-of-two-friendly for XLA tiling).
        x = cba(32, (3, 3), strides=(2, 2))(x)
        x = cba(32, (3, 3))(x)
        x = cba(64, (3, 3))(x)
        x = _pool(x, 3, 2, "max")
        x = cba(80, (1, 1))(x)
        x = cba(192, (3, 3))(x)
        x = _pool(x, 3, 2, "max")

        for pool_features in self.a_blocks:
            x = InceptionA(pool_features, cba=cba)(x)
        x = InceptionB(cba=cba)(x)
        for c7 in self.c_blocks:
            x = InceptionC(c7, cba=cba)(x)
        x = InceptionD(cba=cba)(x)
        for _ in range(self.e_blocks):
            x = InceptionE(cba=cba)(x)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return jnp.asarray(x, jnp.float32)


def inception_v3(**kwargs) -> InceptionV3:
    """Build Inception-v3 (the demo's second model family)."""
    return InceptionV3(**kwargs)
