"""ResNet in Flax — the flagship training workload.

The reference ships ResNet as its GPU training demo (TF benchmarks image,
sweeping depths 34-152 and batch sizes, demo/gpu-training/generate_job.sh:
19-24) and as the TPU demo (TF 1.x TPU models, demo/tpu-training/
resnet-tpu.yaml:69-73).  This is the TPU-native re-design: Flax + XLA,
bfloat16 compute / float32 params, NHWC layout (TPU-preferred), and no
data-dependent Python control flow so the whole step jits onto the MXU.

Depths 18/34 use basic blocks; 50/101/152 use bottlenecks, matching the
torchvision/TF channel plan the reference demo sweeps.
"""

import functools
from typing import Any, Callable, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any

STAGE_SIZES = {
    18: [2, 2, 2, 2],
    34: [3, 4, 6, 3],
    50: [3, 4, 6, 3],
    101: [3, 4, 23, 3],
    152: [3, 8, 36, 3],
}


class ResNetBlock(nn.Module):
    """Basic residual block (depths 18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckResNetBlock(nn.Module):
    """Bottleneck residual block (depths 50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    small_inputs: bool = False  # CIFAR-style stem for 32x32 test inputs

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        act = nn.relu

        x = jnp.asarray(x, self.dtype)
        if self.small_inputs:
            x = conv(self.num_filters, (3, 3), (1, 1), name="conv_init")(x)
            x = norm(name="bn_init")(x)
            x = act(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
            x = norm(name="bn_init")(x)
            x = act(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    conv=conv,
                    norm=norm,
                    act=act,
                    strides=strides,
                )(x)

        x = jnp.mean(x, axis=(1, 2))
        # Classifier in float32 for numerically stable softmax/loss.
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


def resnet(depth: int = 50, **kwargs) -> ResNet:
    """Build a ResNet of any depth the reference demo sweeps (34-152)."""
    if depth not in STAGE_SIZES:
        raise ValueError(f"unsupported ResNet depth {depth}; "
                         f"choose from {sorted(STAGE_SIZES)}")
    block_cls = ResNetBlock if depth < 50 else BottleneckResNetBlock
    return ResNet(
        stage_sizes=STAGE_SIZES[depth], block_cls=block_cls, **kwargs
    )
