"""Continuous-batching decode engine (slot-based serving).

Per-request `generate()` leaves the chip idle between requests and
pays each request's decode serially.  This engine keeps ONE compiled
single-token step running over a fixed fleet of ``max_slots`` decode
lanes; requests join a free slot mid-flight (batched MXU prefill, then
their K/V lives in that slot's cache region) and leave when done — the
TPU-idiomatic shape of vLLM-style continuous batching: static shapes,
on-device state, no recompiles as traffic changes.

The model hooks that make this possible (models/transformer.py):
``cache_index`` is a per-sample vector with vmapped writes, and
``positions`` may be [B, T] — every slot sits at its own depth in the
same step.  Inactive slots still compute (static shapes) but their
state is frozen and their lane is fully overwritten at the next
insert, so garbage never leaks between requests.

Greedy decode (the exactness-testable mode): the engine's interleaved
output is TOKEN-IDENTICAL to per-request ``generate()`` — pinned by
tests/test_batching.py at the tested shapes.  One honest caveat: the
fleet's [slots, 1, D] decode matmuls may tile differently from
generate()'s [1, 1, D], and a bf16 argmax near-tie can flip on that
rounding; prefill is batch-1 in both paths and always agrees exactly
(cmd/bench_serving.py gates on that and reports the full-sequence
agreement fraction).

The reference's serving story is a stock single-model TF-Serving pod
scaled by an HPA on duty cycle (demo/serving/tensorflow-serving.yaml);
this engine is the TPU-first replacement for the inner serving loop.
"""

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from container_engine_accelerators_tpu.models.generate import (
    _rewind_cache_index,
    init_cache,
    prefill,
    prefill_continue,
    prefix_bucket_len,
    splice_prefix,
)


def bucket_len(n: int, cap: int) -> int:
    """Smallest power of two >= n, capped (one compile per bucket)."""
    b = 1
    while b < n and b < cap:
        b *= 2
    return min(b, cap)


class DecodeEngine:
    """Fixed-fleet continuous-batching decoder (greedy).

    ``max_len`` is each slot's cache length: every request needs
    ``bucket(prompt) <= max_len`` and ``prompt_len + max_new <= max_len``.
    """

    def __init__(self, model, params, max_slots: int, max_len: int,
                 eos_id: Optional[int] = None):
        if not model.decode:
            raise ValueError("DecodeEngine needs a model with decode=True")
        self.model, self.params = model, params
        self.max_slots, self.max_len = max_slots, max_len
        self.eos_id = eos_id

        self.cache = init_cache(model, max_slots, max_len)
        self.pos = jnp.zeros((max_slots,), jnp.int32)
        self.last_tok = jnp.zeros((max_slots,), jnp.int32)
        self.active = jnp.zeros((max_slots,), bool)

        self._free = list(range(max_slots))
        self._req: Dict[int, dict] = {}  # slot -> {id, tokens, remaining}
        self._results: Dict[int, List[int]] = {}
        self._next_id = 0

        def _prefill(prompt, prompt_len):
            cache, last = prefill(model, params, prompt, prompt_len,
                                  self.max_len)
            tok0 = jnp.argmax(last, axis=-1).astype(jnp.int32)
            return cache, tok0

        def _prefill_pfx(prefix_kv, prefix_len, suffix, suffix_len):
            # Prefix-cache composition: splice the stored block into a
            # fresh slot-shaped cache, continue-prefill only the suffix
            # (models/prefix_cache.py semantics inside one slot lane).
            cache = init_cache(model, 1, self.max_len)
            cache = splice_prefix(cache, prefix_kv, prefix_len, 1)
            cache, last = prefill_continue(
                model, params, cache, suffix, prefix_len,
                prefix_len + suffix_len)
            tok0 = jnp.argmax(last, axis=-1).astype(jnp.int32)
            return cache, tok0

        # jit caches one trace per prompt BUCKET width; insert and step
        # trace once (slot index and cursors are traced operands).
        self._prefill = jax.jit(_prefill)
        self._prefill_pfx = jax.jit(_prefill_pfx)
        self._insert_slot = jax.jit(self._insert_slot_impl)
        self._step = jax.jit(self._step_impl)

    # ---- jitted kernels -------------------------------------------------

    def _insert_slot_impl(self, cache, pos, last_tok, active,
                          slot_cache, tok0, slot, start_pos):
        def put(full, one):
            start = (0, slot) + (0,) * (full.ndim - 2)
            return jax.lax.dynamic_update_slice(
                full, one.astype(full.dtype), start
            )

        cache = jax.tree_util.tree_map(put, cache, slot_cache)
        return (
            cache,
            pos.at[slot].set(start_pos),
            last_tok.at[slot].set(tok0[0]),
            active.at[slot].set(True),
        )

    def _step_impl(self, cache, pos, last_tok, active):
        logits, mutated = self.model.apply(
            {"params": self.params, "cache": cache},
            last_tok[:, None],
            positions=pos[:, None],
            mutable=["cache"],
        )
        nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
        new_pos = jnp.where(active, pos + 1, pos)
        new_tok = jnp.where(active, nxt, last_tok)
        # The model advanced every slot's write cursor; re-pin it to the
        # engine's per-slot positions so frozen (inactive) lanes stay
        # frozen.  (Their garbage write this step lands inside their own
        # lane, which the next insert overwrites wholesale.)
        cache = _rewind_cache_index(mutated["cache"], new_pos)
        return cache, new_pos, new_tok, nxt

    # ---- host API -------------------------------------------------------

    def submit(self, prompt_ids: List[int], max_new: int,
               prefix=None) -> int:
        """Claim a free slot, prefill it, emit the first token.
        Returns a request id; raises if the fleet is full.

        ``prefix`` is an optional ``(prefix_kv, prefix_len)`` entry
        from :class:`~.prefix_cache.PrefixCache` (built with this
        engine's model/params): the slot starts from the spliced block
        and ``prompt_ids`` are treated as the SUFFIX — same exactness
        contract as the per-request prefix path.
        """
        if not self._free:
            raise RuntimeError("no free slot — step() until one drains")
        plen = len(prompt_ids)
        if prefix is None:
            bucket = bucket_len(plen, self.max_len)
            start = 0
        else:
            pfx_bucket = prefix_bucket_len(prefix[0])
            start = int(prefix[1])
            # The suffix block writes at slots [start, start+bucket);
            # a clamped dynamic_update_slice would silently break the
            # slot==position invariant, so over-long requests are
            # rejected up front.
            bucket = bucket_len(plen, self.max_len)
            if pfx_bucket > self.max_len or start + bucket > self.max_len:
                raise ValueError(
                    f"spliced request needs prefix bucket {pfx_bucket} "
                    f"and suffix bucket slots [{start}, {start + bucket})"
                    f"; slot holds {self.max_len}"
                )
        if plen > bucket or start + plen + max_new > self.max_len:
            raise ValueError(
                f"request needs {start}+{plen}+{max_new} tokens; slot "
                f"holds {self.max_len}"
            )
        slot = self._free.pop()
        prompt = jnp.asarray(
            [list(prompt_ids) + [0] * (bucket - plen)], jnp.int32
        )
        if prefix is None:
            slot_cache, tok0 = self._prefill(prompt, plen)
        else:
            slot_cache, tok0 = self._prefill_pfx(
                prefix[0], prefix[1], prompt, plen)
        plen = start + plen  # global depth of the slot's cursor
        self.cache, self.pos, self.last_tok, self.active = (
            self._insert_slot(self.cache, self.pos, self.last_tok,
                              self.active, slot_cache, tok0, slot, plen)
        )
        rid = self._next_id
        self._next_id += 1
        first = int(tok0[0])
        self._req[slot] = {"id": rid, "tokens": [first],
                           "remaining": max_new - 1}
        if self._req[slot]["remaining"] <= 0 or first == self.eos_id:
            self._retire(slot)
        return rid

    def _retire(self, slot: int):
        req = self._req.pop(slot)
        self._results[req["id"]] = req["tokens"]
        self.active = self.active.at[slot].set(False)
        self._free.append(slot)

    def step(self) -> int:
        """One decode step for the whole fleet; returns live-slot count."""
        if not self._req:
            return 0
        self.cache, self.pos, self.last_tok, nxt = self._step(
            self.cache, self.pos, self.last_tok, self.active
        )
        tokens = np.asarray(nxt)
        for slot in list(self._req):
            req = self._req[slot]
            tok = int(tokens[slot])
            req["tokens"].append(tok)
            req["remaining"] -= 1
            if req["remaining"] <= 0 or tok == self.eos_id:
                self._retire(slot)
        return len(self._req)

    def run_until_drained(self, max_steps: int = 100_000):
        for _ in range(max_steps):
            if self.step() == 0:
                return
        raise RuntimeError("engine did not drain")

    def result(self, rid: int) -> Optional[List[int]]:
        """Generated tokens (first token included) once finished."""
        return self._results.get(rid)

    def take_result(self, rid: int) -> Optional[List[int]]:
        """Like :meth:`result` but removes the entry — long-running
        servers must take, not peek, or finished requests accumulate
        for the process lifetime."""
        return self._results.pop(rid, None)


class EngineLoop:
    """Thread-safe request facade + background stepper for DecodeEngine.

    HTTP handler threads call :meth:`generate`; one daemon thread steps
    the fleet whenever any slot is live.  A single condition variable
    serializes every engine mutation and doubles as the completion /
    free-slot signal — the engine itself stays single-threaded.
    """

    def __init__(self, engine: DecodeEngine):
        import threading

        self.engine = engine
        self.cond = threading.Condition()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            with self.cond:
                while not self.engine._req:
                    self.cond.wait()
                self.engine.step()
                self.cond.notify_all()

    def generate(self, prompt_ids: List[int], max_new: int,
                 timeout: float = 300.0, prefix=None) -> List[int]:
        """Submit and block until done; returns the generated tokens."""
        return self.generate_many([prompt_ids], max_new, timeout,
                                  prefix=prefix)[0]

    def generate_many(self, prompts: List[List[int]], max_new: int,
                      timeout: float = 300.0,
                      prefix=None) -> List[List[int]]:
        """Run several prompts CONCURRENTLY across the fleet.

        Submits each prompt as soon as a slot frees (earlier prompts
        keep decoding meanwhile) and returns all outputs in input
        order — a k-prompt request on a k-slot engine costs ~one
        request's wall clock, not k.
        """
        import time

        deadline = time.monotonic() + timeout
        rids: List[Optional[int]] = [None] * len(prompts)
        outs: List[Optional[List[int]]] = [None] * len(prompts)
        pending = set(range(len(prompts)))
        unsubmitted = list(range(len(prompts)))
        with self.cond:
            while pending:
                progressed = False
                while unsubmitted and self.engine._free:
                    i = unsubmitted.pop(0)
                    rids[i] = self.engine.submit(prompts[i], max_new,
                                                 prefix=prefix)
                    progressed = True
                if progressed:
                    self.cond.notify_all()
                for i in list(pending):
                    if rids[i] is None:
                        continue
                    got = self.engine.take_result(rids[i])
                    if got is not None:
                        outs[i] = got
                        pending.discard(i)
                        progressed = True
                if pending and not progressed:
                    if not self.cond.wait(deadline - time.monotonic()):
                        raise TimeoutError(
                            "generation timed out or no free decode slot"
                        )
        return outs
