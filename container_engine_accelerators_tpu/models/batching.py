"""Continuous-batching decode engine (slot-based serving).

Per-request `generate()` leaves the chip idle between requests and
pays each request's decode serially.  This engine keeps ONE compiled
single-token step running over a fixed fleet of ``max_slots`` decode
lanes; requests join a free slot mid-flight (batched MXU prefill, then
their K/V lives in that slot's cache region) and leave when done — the
TPU-idiomatic shape of vLLM-style continuous batching: static shapes,
on-device state, no recompiles as traffic changes.

The model hooks that make this possible (models/transformer.py):
``cache_index`` is a per-sample vector with vmapped writes, and
``positions`` may be [B, T] — every slot sits at its own depth in the
same step.  Inactive slots still compute (static shapes) but their
state is frozen and their lane is fully overwritten at the next
insert, so garbage never leaks between requests.

Greedy decode (the exactness-testable mode): the engine's interleaved
output is TOKEN-IDENTICAL to per-request ``generate()`` — pinned by
tests/test_batching.py at the tested shapes.  One honest caveat: the
fleet's [slots, 1, D] decode matmuls may tile differently from
generate()'s [1, 1, D], and a bf16 argmax near-tie can flip on that
rounding; prefill is batch-1 in both paths and always agrees exactly
(cmd/bench_serving.py gates on that and reports the full-sequence
agreement fraction).

The reference's serving story is a stock single-model TF-Serving pod
scaled by an HPA on duty cycle (demo/serving/tensorflow-serving.yaml);
this engine is the TPU-first replacement for the inner serving loop.
"""

import logging
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)

from container_engine_accelerators_tpu.models.generate import (
    _rewind_cache_index,
    init_cache,
    prefill,
    prefill_continue,
    prefix_bucket_len,
    splice_prefix,
)


def bucket_len(n: int, cap: int) -> int:
    """Smallest power of two >= n, capped (one compile per bucket)."""
    b = 1
    while b < n and b < cap:
        b *= 2
    return min(b, cap)


def _lane_put(full, one, slot):
    """Overwrite lane ``slot`` of a fleet cache tree with a one-lane
    tree — the ONE copy of the lane-write layout rule: the slot axis
    is every cache leaf's second axis (a leading ``nn.scan`` layer
    axis precedes it).  Shared by the target insert
    (``_insert_slot``) and the speculative draft-lane insert."""
    def put(f, o):
        start = (0, slot) + (0,) * (f.ndim - 2)
        return jax.lax.dynamic_update_slice(f, o.astype(f.dtype), start)

    return jax.tree_util.tree_map(put, full, one)


# ---- shared jitted kernels ----------------------------------------------
#
# MODULE-level jits with the flax module as a static argument, not
# per-instance jits of bound closures: a flax module is a frozen
# dataclass (hash/eq by config), so every engine built on an equal
# model SHARES one trace per shape.  Per-instance `jax.jit(closure)`
# gave each engine its own cache key by function identity — in a
# process that builds several engines (the test suite, bench warm+timed
# runs, a server restart) that recompiled identical programs; VERDICT
# r4 item 6 priced that at minutes of pure duplicate compile time.
# jit caches one trace per prompt BUCKET width; insert and step trace
# once (slot index and cursors are traced operands).

def _prefill_pfx_core(model, params, prefix_kv, prefix_len, suffix,
                      suffix_len, max_len):
    """Prefix-cache composition — the ONE copy of the splice rule for
    slot lanes: splice the stored block into a fresh slot-shaped
    cache, continue-prefill only the suffix (models/prefix_cache.py
    semantics inside one lane).  Shared by the greedy and sampled
    prefill heads."""
    cache = init_cache(model, 1, max_len)
    cache = splice_prefix(cache, prefix_kv, prefix_len, 1)
    return prefill_continue(
        model, params, cache, suffix, prefix_len,
        prefix_len + suffix_len)


@partial(jax.jit, static_argnames=("model", "max_len"))
def _prefill_slot(model, params, prompt, prompt_len, max_len):
    cache, last = prefill(model, params, prompt, prompt_len, max_len)
    tok0 = jnp.argmax(last, axis=-1).astype(jnp.int32)
    return cache, tok0


@partial(jax.jit, static_argnames=("model", "max_len"))
def _prefill_slot_pfx(model, params, prefix_kv, prefix_len, suffix,
                      suffix_len, max_len):
    cache, last = _prefill_pfx_core(model, params, prefix_kv,
                                    prefix_len, suffix, suffix_len,
                                    max_len)
    tok0 = jnp.argmax(last, axis=-1).astype(jnp.int32)
    return cache, tok0


@partial(jax.jit, static_argnames=("model", "max_len"))
def _prefill_slot_sampled(model, params, prompt, prompt_len, max_len,
                          key, temperature):
    """Sampled-lane prefill: the first token comes from the request's
    OWN key chain — ``key, sub = split(key)`` then categorical, exactly
    generate.py's ``sample_from`` — so a sampled request's tokens are a
    pure function of (params, prompt, seed), independent of what else
    is in the fleet."""
    cache, last = prefill(model, params, prompt, prompt_len, max_len)
    key, sub = jax.random.split(key)
    tok0 = jax.random.categorical(
        sub, last / temperature).astype(jnp.int32)
    return cache, tok0, key


@partial(jax.jit, static_argnames=("model", "max_len"))
def _prefill_slot_pfx_sampled(model, params, prefix_kv, prefix_len,
                              suffix, suffix_len, max_len, key,
                              temperature):
    cache, last = _prefill_pfx_core(model, params, prefix_kv,
                                    prefix_len, suffix, suffix_len,
                                    max_len)
    key, sub = jax.random.split(key)
    tok0 = jax.random.categorical(
        sub, last / temperature).astype(jnp.int32)
    return cache, tok0, key


@jax.jit
def _insert_slot(cache, pos, last_tok, active, slot_cache, tok0, slot,
                 start_pos):
    cache = _lane_put(cache, slot_cache, slot)
    return (
        cache,
        pos.at[slot].set(start_pos),
        last_tok.at[slot].set(tok0[0]),
        active.at[slot].set(True),
    )


_lane_put_jit = jax.jit(_lane_put)


@partial(jax.jit, static_argnames=("model", "any_sampled"))
def _fleet_step(model, params, cache, pos, last_tok, active, keys,
                temps, any_sampled):
    """One decode step for the whole fleet, mixed greedy/sampled.

    Greedy slots (``temps == 0``) take the argmax; sampled slots draw
    from their OWN key chain (``key, sub = split(key)`` then a
    per-row categorical at the slot's temperature — bitwise
    generate.py's ``sample_from`` for a batch-1 row, so the fleet's
    sampled output is token-identical to per-request
    ``generate(seed=...)`` and independent of fleet composition).
    A slot's key advances only while it is sampled AND active —
    greedy/retired slots never consume randomness.

    ``any_sampled`` is STATIC (one extra cached trace): an all-greedy
    fleet — the serving default — must not pay the per-step RNG-bit
    generation and [slots, vocab] categorical it would discard.
    """
    logits, mutated = model.apply(
        {"params": params, "cache": cache},
        last_tok[:, None],
        positions=pos[:, None],
        mutable=["cache"],
    )
    row = logits[:, 0, :]
    greedy_tok = jnp.argmax(row, axis=-1).astype(jnp.int32)
    if any_sampled:
        split = jax.vmap(jax.random.split)(keys)  # [S, 2, 2]
        new_keys, subs = split[:, 0], split[:, 1]
        sampled = temps > 0
        safe_t = jnp.where(sampled, temps, 1.0)
        samp_tok = jax.vmap(jax.random.categorical)(
            subs, row / safe_t[:, None]).astype(jnp.int32)
        nxt = jnp.where(sampled, samp_tok, greedy_tok)
        keys = jnp.where((sampled & active)[:, None], new_keys, keys)
    else:
        nxt = greedy_tok
    new_pos = jnp.where(active, pos + 1, pos)
    new_tok = jnp.where(active, nxt, last_tok)
    # The model advanced every slot's write cursor; re-pin it to the
    # engine's per-slot positions so frozen (inactive) lanes stay
    # frozen.  (Their garbage write this step lands inside their own
    # lane, which the next insert overwrites wholesale.)
    cache = _rewind_cache_index(mutated["cache"], new_pos)
    return cache, new_pos, new_tok, nxt, keys


@partial(jax.jit, static_argnames=("draft_model", "max_len"))
def _prefill_draft_lane(draft_model, draft_params, prompt, prompt_len,
                        max_len):
    cache, _ = prefill(draft_model, draft_params, prompt, prompt_len,
                       max_len)
    return cache


@partial(jax.jit, static_argnames=("draft_model", "max_len"))
def _prefill_draft_lane_pfx(draft_model, draft_params, prefix_kv,
                            prefix_len, suffix, suffix_len, max_len):
    cache = init_cache(draft_model, 1, max_len)
    cache = splice_prefix(cache, prefix_kv, prefix_len, 1)
    cache, _ = prefill_continue(
        draft_model, draft_params, cache, suffix, prefix_len,
        prefix_len + suffix_len)
    return cache


@partial(jax.jit,
         static_argnames=("model", "draft_model", "k", "any_sampled"))
def _spec_fleet_step(model, draft_model, params, draft_params, t_cache,
                     d_cache, pos, last_tok, active, keys, temps, k,
                     any_sampled):
    """One speculative round for the whole fleet — ONE kernel for both
    lane kinds, like ``_fleet_step``: greedy lanes use the argmax-match
    acceptance rule and never consume randomness; sampled lanes
    (``temps > 0``) run the per-slot rejection round, bit-matching
    generate_speculative_sampled's B=1 rng discipline — per round
    ``(rkey, kd, ka, kr) = split(key, 4)`` per slot, draft proposals
    from ``categorical(fold_in(kd, i), logits/temp)``, acceptance
    ``u*q < p`` with ``u = uniform(ka, (k,))``, residual/bonus from
    ``categorical(kr, log(max(p-q,0) or p))``.  ``any_sampled`` is
    STATIC: an all-greedy fleet's trace carries no RNG work at all.
    """
    s = active.shape[0]
    if any_sampled:
        rounds = jax.vmap(lambda key: jax.random.split(key, 4))(keys)
        new_keys, kd, ka, kr = (rounds[:, 0], rounds[:, 1],
                                rounds[:, 2], rounds[:, 3])
        sampled = temps > 0
        safe_t = jnp.where(sampled, temps, 1.0)

    def dstep(c, i):
        cache, tok, p = c
        logits, mut = draft_model.apply(
            {"params": draft_params, "cache": cache},
            tok[:, None], positions=p[:, None], mutable=["cache"],
        )
        row = logits[:, 0, :]
        greedy_nxt = jnp.argmax(row, axis=-1).astype(jnp.int32)
        if not any_sampled:
            return (mut["cache"], greedy_nxt, p + 1), (greedy_nxt, 0.0)
        srow = row / safe_t[:, None]
        keys_i = jax.vmap(jax.random.fold_in, in_axes=(0, None))(kd, i)
        samp_nxt = jax.vmap(jax.random.categorical)(
            keys_i, srow).astype(jnp.int32)
        nxt = jnp.where(sampled, samp_nxt, greedy_nxt)
        return (mut["cache"], nxt, p + 1), (
            nxt, jax.nn.softmax(srow, axis=-1))

    # k+1 draft steps (the extra one keeps the draft cache complete
    # when every proposal is accepted — speculative.py's rule).
    (d_cache, _, _), (draft_toks, draft_qs) = jax.lax.scan(
        dstep, (d_cache, last_tok, pos), jnp.arange(k + 1))
    drafts = draft_toks.transpose(1, 0)[:, :k]       # [S, k]

    chunk = jnp.concatenate([last_tok[:, None], drafts], axis=1)
    pos_chunk = pos[:, None] + jnp.arange(k + 1, dtype=jnp.int32)[None]
    logits, mut = model.apply(
        {"params": params, "cache": t_cache},
        chunk, positions=pos_chunk, mutable=["cache"],
    )
    t_cache = mut["cache"]
    tgt_choice = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    g_matches = (drafts == tgt_choice[:, :k]).astype(jnp.int32)
    if any_sampled:
        qs = draft_qs.transpose(1, 0, 2)[:, :k, :]   # [S, k, V]
        ps = jax.nn.softmax(logits / safe_t[:, None, None], axis=-1)
        p_at = jnp.take_along_axis(
            ps[:, :k, :], drafts[..., None], axis=-1)[..., 0]
        q_at = jnp.take_along_axis(qs, drafts[..., None], axis=-1)[..., 0]
        u = jax.vmap(lambda key: jax.random.uniform(key, (k,)))(ka)
        s_matches = (u * q_at < p_at).astype(jnp.int32)
        matches = jnp.where(sampled[:, None], s_matches, g_matches)
    else:
        matches = g_matches
    m = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)  # [S]

    g_next = jnp.take_along_axis(tgt_choice, m[:, None], axis=1)[:, 0]
    if any_sampled:
        qs_pad = jnp.concatenate(
            [qs, jnp.zeros_like(ps[:, :1, :])], axis=1)
        p_m = jnp.take_along_axis(ps, m[:, None, None], axis=1)[:, 0, :]
        q_m = jnp.take_along_axis(qs_pad, m[:, None, None], axis=1)[:, 0, :]
        res = jnp.maximum(p_m - q_m, 0.0)
        res_sum = jnp.sum(res, axis=-1, keepdims=True)
        safe = jnp.where(res_sum > 0, res, p_m)
        s_next = jax.vmap(jax.random.categorical)(
            kr, jnp.log(safe + 1e-30)).astype(jnp.int32)
        next_tok = jnp.where(sampled, s_next, g_next)
        keys = jnp.where((sampled & active)[:, None], new_keys, keys)
    else:
        next_tok = g_next

    row = jnp.concatenate([drafts, jnp.zeros((s, 1), jnp.int32)], axis=1)
    row = row.at[jnp.arange(s), m].set(next_tok)
    new_pos = jnp.where(active, pos + m + 1, pos)
    new_tok = jnp.where(active, next_tok, last_tok)
    t_cache = _rewind_cache_index(t_cache, new_pos)
    d_cache = _rewind_cache_index(d_cache, new_pos)
    return t_cache, d_cache, new_pos, new_tok, row, m, keys


class DecodeEngine:
    """Fixed-fleet continuous-batching decoder (greedy).

    ``max_len`` is each slot's cache length: every request needs
    ``bucket(prompt) <= max_len`` and ``prompt_len + max_new <= max_len``.
    """

    # Extra tail slots a request must leave free in its lane; the
    # speculative subclass sets this to k (a final verify round may
    # write up to k positions past the last emitted token).
    _margin = 0

    def __init__(self, model, params, max_slots: int, max_len: int,
                 eos_id: Optional[int] = None, mesh=None):
        if not model.decode:
            raise ValueError("DecodeEngine needs a model with decode=True")
        self.model, self.params = model, params
        self.max_slots, self.max_len = max_slots, max_len
        self.eos_id = eos_id
        self.mesh = mesh

        self.cache = self._place_cache(init_cache(model, max_slots,
                                                  max_len))
        self.pos = self._place(jnp.zeros((max_slots,), jnp.int32))
        self.last_tok = self._place(jnp.zeros((max_slots,), jnp.int32))
        self.active = self._place(jnp.zeros((max_slots,), bool))
        # Per-slot sampling state: each sampled request carries its own
        # key chain (seeded at submit), so its tokens do not depend on
        # what else shares the fleet; temp 0 marks a greedy lane.
        self.rngs = self._place(
            jnp.zeros((max_slots,) + jax.random.PRNGKey(0).shape,
                      jax.random.PRNGKey(0).dtype))
        self.temps = self._place(jnp.zeros((max_slots,), jnp.float32))

        self._free = list(range(max_slots))
        self._req: Dict[int, dict] = {}  # slot -> {id, tokens, remaining}
        self._results: Dict[int, List[int]] = {}
        self._next_id = 0
        # The jitted kernels are module-level with `model` static (see
        # the block above _prefill_slot): every engine on an equal
        # model shares one trace per shape.

    def _prefill(self, prompt, prompt_len):
        return _prefill_slot(self.model, self.params, prompt,
                             prompt_len, self.max_len)

    def _prefill_pfx(self, prefix_kv, prefix_len, suffix, suffix_len):
        return _prefill_slot_pfx(self.model, self.params, prefix_kv,
                                 prefix_len, suffix, suffix_len,
                                 self.max_len)

    # ---- tensor-parallel placement --------------------------------------
    #
    # With ``mesh`` set (serve_lm --tp --slots), params arrive
    # Megatron-sharded (parallel.shard_params) and the engine's
    # PERSISTENT state must live on the same mesh — the fleet cache
    # shards its KV-head axis over the model axis (each chip holds the
    # heads it computes; GSPMD inserts the decode all-reduce), while
    # the cursor/token/active vectors replicate.  Without a mesh both
    # helpers are identity, and single-device behavior is unchanged.

    def _place(self, x):
        if self.mesh is None:
            return x
        from container_engine_accelerators_tpu.parallel.mesh import (
            replicated,
        )

        return jax.device_put(x, replicated(self.mesh))

    def _place_cache(self, cache):
        if self.mesh is None:
            return cache
        from jax.sharding import NamedSharding, PartitionSpec

        from container_engine_accelerators_tpu.parallel.mesh import (
            MODEL_AXIS,
            replicated,
        )

        msize = self.mesh.shape.get(MODEL_AXIS, 1)
        fallback = [False]

        def spec(leaf):
            # KV leaves are [..., B, T, heads, dim] (splice_prefix's
            # layout rule); shard the heads axis when it divides.
            if leaf.ndim >= 4 and leaf.shape[-2] % msize == 0:
                s = [None] * leaf.ndim
                s[-2] = MODEL_AXIS
                return NamedSharding(self.mesh, PartitionSpec(*s))
            if leaf.ndim >= 4:
                fallback[0] = True
            return replicated(self.mesh)

        placed = jax.device_put(
            cache, jax.tree_util.tree_map(spec, cache))
        if fallback[0]:
            # Per-chip cache memory will NOT scale 1/tp — an operator
            # who sized slots for sharded lanes must hear about it.
            log.warning(
                "fleet KV heads do not divide the model axis (%d-way); "
                "cache replicated on every chip", msize)
        return placed

    # ---- host API -------------------------------------------------------

    def submit(self, prompt_ids: List[int], max_new: int,
               prefix=None, temperature: float = 0.0,
               seed: int = 0) -> int:
        """Claim a free slot, prefill it, emit the first token.
        Returns a request id; raises if the fleet is full.

        ``prefix`` is an optional ``(prefix_kv, prefix_len)`` entry
        from :class:`~.prefix_cache.PrefixCache` (built with this
        engine's model/params): the slot starts from the spliced block
        and ``prompt_ids`` are treated as the SUFFIX — same exactness
        contract as the per-request prefix path.

        ``temperature > 0`` makes this a SAMPLED lane: tokens are drawn
        from the request's own ``PRNGKey(seed)`` chain with exactly
        generate()'s split/categorical discipline, so the output equals
        per-request ``generate(..., temperature, rng=PRNGKey(seed))``
        regardless of what else shares the fleet.
        """
        if not self._free:
            raise RuntimeError("no free slot — step() until one drains")
        plen = len(prompt_ids)
        if prefix is None:
            bucket = bucket_len(plen, self.max_len)
            start = 0
        else:
            pfx_bucket = prefix_bucket_len(prefix[0])
            start = int(prefix[1])
            # The suffix block writes at slots [start, start+bucket);
            # a clamped dynamic_update_slice would silently break the
            # slot==position invariant, so over-long requests are
            # rejected up front.
            bucket = bucket_len(plen, self.max_len)
            if pfx_bucket > self.max_len or start + bucket > self.max_len:
                raise ValueError(
                    f"spliced request needs prefix bucket {pfx_bucket} "
                    f"and suffix bucket slots [{start}, {start + bucket})"
                    f"; slot holds {self.max_len}"
                )
        if (plen > bucket
                or start + plen + max_new + self._margin > self.max_len):
            raise ValueError(
                f"request needs {start}+{plen}+{max_new}+{self._margin} "
                f"tokens; slot holds {self.max_len}"
            )
        slot = self._free.pop()
        prompt = jnp.asarray(
            [list(prompt_ids) + [0] * (bucket - plen)], jnp.int32
        )
        sampled = bool(temperature and temperature > 0)
        if sampled:
            key = jax.random.PRNGKey(int(seed))
            if prefix is None:
                slot_cache, tok0, key = _prefill_slot_sampled(
                    self.model, self.params, prompt, plen,
                    self.max_len, key, jnp.float32(temperature))
            else:
                slot_cache, tok0, key = _prefill_slot_pfx_sampled(
                    self.model, self.params, prefix[0], prefix[1],
                    prompt, plen, self.max_len, key,
                    jnp.float32(temperature))
            self.rngs = self.rngs.at[slot].set(key)
            self.temps = self.temps.at[slot].set(temperature)
        else:
            if prefix is None:
                slot_cache, tok0 = self._prefill(prompt, plen)
            else:
                slot_cache, tok0 = self._prefill_pfx(
                    prefix[0], prefix[1], prompt, plen)
            self.temps = self.temps.at[slot].set(0.0)
        plen = start + plen  # global depth of the slot's cursor
        self.cache, self.pos, self.last_tok, self.active = (
            _insert_slot(self.cache, self.pos, self.last_tok,
                         self.active, slot_cache, tok0, slot, plen)
        )
        self._insert_aux(slot, prompt, plen - start)
        rid = self._next_id
        self._next_id += 1
        first = int(tok0[0])
        self._req[slot] = {"id": rid, "tokens": [first],
                           "remaining": max_new - 1,
                           "sampled": sampled}
        if self._req[slot]["remaining"] <= 0 or first == self.eos_id:
            self._retire(slot)
        return rid

    def _insert_aux(self, slot: int, prompt, plen) -> None:
        """Subclass hook: extra per-lane state for a freshly claimed
        slot (the speculative engine prefills its draft lane here)."""

    def _retire(self, slot: int):
        req = self._req.pop(slot)
        self._results[req["id"]] = req["tokens"]
        self.active = self.active.at[slot].set(False)
        self._free.append(slot)

    def step(self) -> int:
        """One decode step for the whole fleet; returns live-slot count."""
        if not self._req:
            return 0
        (self.cache, self.pos, self.last_tok, nxt,
         self.rngs) = _fleet_step(
            self.model, self.params, self.cache, self.pos,
            self.last_tok, self.active, self.rngs, self.temps,
            any(r["sampled"] for r in self._req.values()),
        )
        tokens = np.asarray(nxt)
        for slot in list(self._req):
            req = self._req[slot]
            tok = int(tokens[slot])
            req["tokens"].append(tok)
            req["remaining"] -= 1
            if req["remaining"] <= 0 or tok == self.eos_id:
                self._retire(slot)
        return len(self._req)

    def run_until_drained(self, max_steps: int = 100_000):
        for _ in range(max_steps):
            if self.step() == 0:
                return
        raise RuntimeError("engine did not drain")

    def result(self, rid: int) -> Optional[List[int]]:
        """Generated tokens (first token included) once finished."""
        return self._results.get(rid)

    def take_result(self, rid: int) -> Optional[List[int]]:
        """Like :meth:`result` but removes the entry — long-running
        servers must take, not peek, or finished requests accumulate
        for the process lifetime."""
        return self._results.pop(rid, None)


class SpecDecodeEngine(DecodeEngine):
    """Speculative continuous batching: draft/verify rounds over the
    slot fleet (VERDICT r4 item 2 — the production serving shape).

    Each :meth:`step` is one speculative ROUND for every live slot:
    the draft fleet proposes ``k`` tokens per slot (k+1 single-token
    steps over a parallel draft cache fleet), the target verifies all
    slots in ONE chunked [slots, k+1] forward, and each slot accepts
    its longest matching prefix plus the target's own token — the
    per-slot form of models/speculative.py's round, so the interleaved
    fleet output is TOKEN-IDENTICAL to per-request
    ``generate_speculative`` (pinned in tests/test_batching.py).

    Cursor discipline: ``pos[slot]`` advances by ``m+1`` and BOTH
    caches' write cursors rewind to it each round — stale draft/verify
    writes past the cursor are dead slots under the visibility mask,
    exactly like bucket padding (generate.py ``_rewind_cache_index``).
    A final round can write up to ``k`` positions past the last token
    a request keeps, so admission reserves ``_margin = k`` tail slots.

    ``prefix`` in :meth:`submit` is ``(target_kv, draft_kv,
    prefix_len)`` — each model's own spliced block, as in
    ``generate_speculative(prefix=)``.

    SAMPLED lanes (``temperature > 0`` at submit) run the rejection
    round per slot (``_spec_fleet_step``'s sampled path), bit-matching
    ``generate_speculative_sampled``'s B=1 rng discipline on the
    request's own seed chain — so sampled lanes are token-identical
    to the per-request rejection sampler regardless of fleet
    composition, while greedy lanes in the same fleet keep the
    argmax contract.  An all-greedy fleet keeps the randomness-free
    trace.

    Acceptance telemetry: ``spec_rounds`` / ``spec_drafted`` /
    ``spec_accepted`` accumulate across rounds (live slots only);
    acceptance rate is the lever that decides the realized speedup.
    """

    def __init__(self, model, params, draft_model, draft_params,
                 max_slots: int, max_len: int, k: int = 4,
                 eos_id: Optional[int] = None):
        if k < 1:
            raise ValueError("k must be >= 1")
        if not draft_model.decode:
            raise ValueError("SpecDecodeEngine needs a decode=True draft")
        self.draft_model, self.draft_params = draft_model, draft_params
        self.k = k
        self._margin = k
        self._pending_draft = None
        super().__init__(model, params, max_slots, max_len, eos_id)
        self.d_cache = init_cache(draft_model, max_slots, max_len)
        self.spec_rounds = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        # Round kernel + draft prefills are the module-level shared
        # jits (_spec_fleet_step etc.): engines on equal model pairs
        # share one trace per shape.

    # ---- host API -------------------------------------------------------

    def submit(self, prompt_ids: List[int], max_new: int,
               prefix=None, temperature: float = 0.0,
               seed: int = 0) -> int:
        if prefix is not None:
            t_kv, d_kv, pfx_len = prefix
            self._pending_draft = (d_kv, pfx_len)
            prefix = (t_kv, pfx_len)
        else:
            self._pending_draft = None
        return super().submit(prompt_ids, max_new, prefix=prefix,
                              temperature=temperature, seed=seed)

    def _insert_aux(self, slot: int, prompt, plen) -> None:
        if self._pending_draft is None:
            lane = _prefill_draft_lane(self.draft_model,
                                       self.draft_params, prompt, plen,
                                       self.max_len)
        else:
            d_kv, pfx_len = self._pending_draft
            lane = _prefill_draft_lane_pfx(
                self.draft_model, self.draft_params, d_kv, pfx_len,
                prompt, plen, self.max_len)
        self.d_cache = _lane_put_jit(self.d_cache, lane, slot)

    def step(self) -> int:
        """One speculative round for the whole fleet; sampled lanes
        (if any) run the rejection round, greedy lanes the argmax
        round — an all-greedy fleet keeps its randomness-free trace."""
        if not self._req:
            return 0
        (self.cache, self.d_cache, self.pos, self.last_tok, row, m,
         self.rngs) = _spec_fleet_step(
            self.model, self.draft_model, self.params,
            self.draft_params, self.cache, self.d_cache, self.pos,
            self.last_tok, self.active, self.rngs, self.temps, self.k,
            any(r["sampled"] for r in self._req.values()),
        )
        rows = np.asarray(row)
        accepts = np.asarray(m)
        self.spec_rounds += 1
        for slot in list(self._req):
            req = self._req[slot]
            acc = int(accepts[slot])
            self.spec_drafted += self.k
            self.spec_accepted += acc
            for tok in rows[slot][: acc + 1].tolist():
                tok = int(tok)
                req["tokens"].append(tok)
                req["remaining"] -= 1
                if req["remaining"] <= 0 or tok == self.eos_id:
                    self._retire(slot)
                    break
        return len(self._req)


class EngineLoop:
    """Thread-safe request facade + background stepper for DecodeEngine.

    HTTP handler threads call :meth:`generate`; one daemon thread steps
    the fleet whenever any slot is live.  A single condition variable
    serializes every engine mutation and doubles as the completion /
    free-slot signal — the engine itself stays single-threaded.
    """

    def __init__(self, engine: DecodeEngine):
        import threading

        self.engine = engine
        self.cond = threading.Condition()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            with self.cond:
                while not self.engine._req:
                    self.cond.wait()
                self.engine.step()
                self.cond.notify_all()

    def generate(self, prompt_ids: List[int], max_new: int,
                 timeout: float = 300.0, prefix=None,
                 temperature: float = 0.0, seed: int = 0) -> List[int]:
        """Submit and block until done; returns the generated tokens."""
        return self.generate_many([prompt_ids], max_new, timeout,
                                  prefix=prefix, temperature=temperature,
                                  seeds=[seed])[0]

    def generate_many(self, prompts: List[List[int]], max_new: int,
                      timeout: float = 300.0, prefix=None,
                      temperature: float = 0.0,
                      seeds=None) -> List[List[int]]:
        """Run several prompts CONCURRENTLY across the fleet.

        Submits each prompt as soon as a slot frees (earlier prompts
        keep decoding meanwhile) and returns all outputs in input
        order — a k-prompt request on a k-slot engine costs ~one
        request's wall clock, not k.  ``temperature > 0`` makes every
        prompt a sampled lane on its own ``seeds[i]`` key chain.
        """
        import time

        if seeds is None:
            seeds = list(range(len(prompts)))
        deadline = time.monotonic() + timeout
        rids: List[Optional[int]] = [None] * len(prompts)
        outs: List[Optional[List[int]]] = [None] * len(prompts)
        pending = set(range(len(prompts)))
        unsubmitted = list(range(len(prompts)))
        with self.cond:
            while pending:
                progressed = False
                while unsubmitted and self.engine._free:
                    i = unsubmitted.pop(0)
                    rids[i] = self.engine.submit(
                        prompts[i], max_new, prefix=prefix,
                        temperature=temperature, seed=seeds[i])
                    progressed = True
                if progressed:
                    self.cond.notify_all()
                for i in list(pending):
                    if rids[i] is None:
                        continue
                    got = self.engine.take_result(rids[i])
                    if got is not None:
                        outs[i] = got
                        pending.discard(i)
                        progressed = True
                if pending and not progressed:
                    if not self.cond.wait(deadline - time.monotonic()):
                        raise TimeoutError(
                            "generation timed out or no free decode slot"
                        )
        return outs
