"""Autoregressive generation for the transformer LM (KV-cache decode).

Serving-side counterpart of models/lm_train.py: the model is rebuilt
with ``decode=True`` so attention appends to fixed-length cache
variables.  Generation is two-phase, the shape TPU serving wants:

1. **Batched prefill** — ONE forward over the whole (padded) prompt
   fills every layer's KV cache and yields the first next-token
   logits.  This is MXU-dense work (prompt-length matmuls), replacing
   the prompt-length chain of single-token steps a naive decode loop
   would serialize.
2. **Decode scan** — one jitted single-token step scanned over the
   remaining ``max_new_tokens - 1`` positions (greedy at
   ``temperature=0``, categorical otherwise).

The scan keeps the whole loop on-device: no per-token host
round-trips, static shapes throughout, one compile for any prompt of
the same padded length.
"""

from typing import Optional

import jax
import jax.numpy as jnp


def init_cache(model, batch: int, max_len: int):
    """Zero-filled cache pytree for ``max_len`` tokens (no FLOPs spent:
    shapes come from ``eval_shape``)."""
    shapes = jax.eval_shape(
        model.init,
        jax.random.PRNGKey(0),
        jnp.ones((batch, max_len), jnp.int32),
    )
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"]
    )


def _rewind_cache_index(cache, position):
    """Set every layer's ``cache_index`` to ``position`` (traced ok).

    After a prefill over a PADDED prompt the write cursor sits past the
    pad slots; rewinding it to the true prompt length makes decode
    overwrite those slots in order, and the visibility mask (key slot
    <= query position) hides any slot not yet overwritten — so pads
    never influence the continuation.
    """
    def rewind(path, leaf):
        if path and getattr(path[-1], "key", None) == "cache_index":
            return jnp.zeros_like(leaf) + jnp.asarray(
                position, leaf.dtype
            )
        return leaf

    return jax.tree_util.tree_map_with_path(rewind, cache)


def splice_prefix(cache, prefix_kv, prefix_len, batch: int):
    """Write a stored prefix KV block into slot 0 of a fresh cache and
    cue the cursor at ``prefix_len`` (the prefix-cache primitive; see
    models/prefix_cache.py for the host-side store).  The stored block
    is [.., 1, PFX, ..] and broadcasts over the batch — a shared prefix
    is shared by every sequence."""
    def splice(path, big, small):
        key = getattr(path[-1], "key", None)
        if key in ("cached_key", "cached_value"):
            # Leaf layout is [..., B, T, heads, dim] — under nn.scan a
            # leading layer axis precedes the batch axis, so address
            # batch as ndim-4, never axis 0.
            bshape = small.shape[:-4] + (batch,) + small.shape[-3:]
            block = jnp.broadcast_to(small, bshape)
            return jax.lax.dynamic_update_slice(
                big, block.astype(big.dtype), (0,) * big.ndim)
        if key == "cache_index":
            return jnp.zeros_like(big) + jnp.asarray(prefix_len, big.dtype)
        return big

    return jax.tree_util.tree_map_with_path(splice, cache, prefix_kv)


def prefix_bucket_len(prefix_kv) -> int:
    """Bucket (T-axis) length of a stored prefix KV tree."""
    return next(
        leaf.shape[-3]
        for leaf in jax.tree_util.tree_leaves(prefix_kv)
        if leaf.ndim >= 4
    )


def prefill_continue(model, params, cache, tokens: jax.Array, start,
                     true_end):
    """Continue a prefill: one MXU-dense forward over ``tokens`` [B, S]
    at positions ``start + arange(S)`` into an EXISTING cache whose
    write cursor sits at ``start`` -> (cache cued at ``true_end``, last
    real position's logits).

    ``start`` and ``true_end`` may be traced; ``true_end`` is the total
    number of real tokens now in the cache (``start`` + the count of
    real leading ``tokens`` — the tail beyond it is bucket padding with
    the usual dead-slot semantics).  This is the chunked-continuation
    primitive shared by :func:`prefill` (start=0), the prefix-cache
    suffix path (models/prefix_cache.py), and conceptually by the
    speculative verify chunk (models/speculative.py inlines the same
    apply pattern to keep its per-round logits).
    """
    start = jnp.asarray(start, jnp.int32)
    cache, hidden = _forward_chunk(model, params, cache, tokens, start)
    cache = _rewind_cache_index(cache, true_end)
    h_last = jax.lax.dynamic_index_in_dim(
        hidden, jnp.maximum(true_end - start - 1, 0), axis=1,
        keepdims=False,
    )
    return cache, _project_last(params, h_last)


def _forward_chunk(model, params, cache, tokens, start):
    """One decode-mode forward of ``tokens`` [B, S] at positions
    ``start + arange(S)`` -> (cache with cursor advanced to the chunk
    end, hidden [B, S, D])."""
    s = tokens.shape[1]
    hidden, mutated = model.apply(
        {"params": params, "cache": cache},
        tokens,
        positions=jnp.asarray(start, jnp.int32)
        + jnp.arange(s, dtype=jnp.int32),
        mutable=["cache"],
        project=False,
    )
    return mutated["cache"], hidden


def _project_last(params, h_row):
    """LM-head projection of one hidden row [B, D] -> logits [B, V]."""
    emb = params["embed"]["embedding"]
    return jnp.dot(
        h_row, emb.T.astype(h_row.dtype),
        preferred_element_type=jnp.float32,
    )


def prefill(model, params, prompt: jax.Array, prompt_len, max_len: int):
    """Batched prefill -> (cache cued at ``prompt_len``, last logits).

    One MXU-dense forward over the (padded) ``prompt`` [B, P] writes
    every layer's K/V into a fresh ``max_len``-token cache; the write
    cursor is rewound to ``prompt_len`` (traced ok) and only the last
    real position's hidden row is projected to logits — the model's
    B*P*vocab LM-head matmul is skipped (``project=False``).  Shared
    by :func:`generate` and the continuous-batching engine
    (models/batching.py).
    """
    cache = init_cache(model, prompt.shape[0], max_len)
    return prefill_continue(model, params, cache, prompt, 0, prompt_len)


def prefill_chunked(model, params, prompt: jax.Array, prompt_len,
                    max_len: int, chunk: int):
    """Prefill in ``chunk``-token pieces -> (cache at ``prompt_len``,
    last logits) — numerically identical to :func:`prefill`.

    The single-shot prefill's decode-mode attention materializes a
    [B, P, L] score tensor; at long context that P*L term owns peak
    memory.  Chunking caps it at [B, chunk, L] per piece while the
    matmuls stay MXU-dense, the standard long-prompt TTFT/memory trade
    (each chunk attends the cache written so far — exactly the chunked
    continuation the speculative verifier already exercises).

    ``prompt_len`` may be traced (bucket padding): every chunk advances
    the cursor to its own end, each chunk yields its candidate for the
    "last real token" hidden row, and the candidates are selected by
    which chunk actually contains ``prompt_len - 1`` — then the cursor
    rewinds to ``prompt_len`` with the usual dead-slot semantics.
    """
    b, plen = prompt.shape
    if chunk < 1:
        raise ValueError("chunk must be >= 1")
    if chunk >= plen:
        return prefill(model, params, prompt, prompt_len, max_len)
    prompt_len = jnp.asarray(prompt_len, jnp.int32)
    cache = init_cache(model, b, max_len)

    # The full-size chunks run under ONE lax.scan so trace/compile cost
    # stays constant in prompt length (a Python loop would unroll
    # ceil(P/chunk) transformer forwards into the graph — worst exactly
    # in the long-context regime this helper targets); the remainder
    # chunk, if any, runs once eagerly.
    def step(carry, i):
        cache, h_last = carry
        start = i * chunk
        toks = jax.lax.dynamic_slice(prompt, (0, start), (b, chunk))
        cache, hidden = _forward_chunk(model, params, cache, toks, start)
        # Candidate for the hidden row of token prompt_len-1; ascending
        # chunks make "overwrite whenever prompt_len-1 >= start" select
        # exactly the containing chunk.
        idx = jnp.clip(prompt_len - 1 - start, 0, chunk - 1)
        cand = jax.lax.dynamic_index_in_dim(
            hidden, idx, axis=1, keepdims=False)
        h_last = jnp.where(prompt_len - 1 >= start, cand, h_last)
        return (cache, h_last), None

    n_full = plen // chunk
    emb_dim = params["embed"]["embedding"].shape[1]
    h0 = jnp.zeros((b, emb_dim), model.dtype)  # chunk 0 always overwrites
    (cache, h_last), _ = jax.lax.scan(
        step, (cache, h0), jnp.arange(n_full, dtype=jnp.int32))
    start = n_full * chunk
    if start < plen:
        cache, hidden = _forward_chunk(
            model, params, cache, prompt[:, start:], start)
        idx = jnp.clip(prompt_len - 1 - start, 0, plen - start - 1)
        cand = jax.lax.dynamic_index_in_dim(
            hidden, idx, axis=1, keepdims=False)
        h_last = jnp.where(prompt_len - 1 >= start, cand, h_last)
    cache = _rewind_cache_index(cache, prompt_len)
    return cache, _project_last(params, h_last.astype(model.dtype))


def generate(
    model,
    params,
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    prompt_len=None,
    prefill_chunk: Optional[int] = None,
):
    """Generate ``max_new_tokens`` past ``prompt`` [B, P] -> [B, P+N].

    ``model`` must be constructed with ``decode=True``.  Jittable with
    static ``max_new_tokens``; ``temperature`` may be a TRACED scalar
    when sampling (only greedy-vs-sampling is structural — a Python
    0 / 0.0 selects greedy; anything else, including a tracer, samples),
    so servers can take the value from the request without recompiling.

    ``prompt_len`` (optional, may be a TRACED scalar) is the number of
    leading ``prompt`` tokens that are real; the rest of the prompt
    array is bucket padding.  Pad K/V do land in cache slots during the
    batched prefill, but they are dead on arrival: the causal mask
    keeps them out of every real prompt position's attention, the write
    cursor is rewound to ``prompt_len`` so decode overwrites them in
    order, and slots beyond the current position are always masked.
    This is the seam that lets a server bucket prompt lengths (pad to a
    power of two) with one compile per bucket and numerics identical to
    the exact-length call.

    Output layout: positions ``[0, prompt_len)`` echo the real prompt,
    ``[prompt_len, prompt_len + max_new_tokens)`` are generated.  With
    bucket padding (``prompt_len < P``) the tail beyond that range is
    meaningless — consumers slice ``[:, :prompt_len + max_new_tokens]``
    (cmd/serve_lm.py does).
    """
    if not model.decode:
        raise ValueError("generate() needs a model built with decode=True")
    b, plen = prompt.shape
    if prompt_len is None:
        prompt_len = plen
    max_len = plen + max_new_tokens

    # Phase 1: batched prefill (shared helper; see prefill()).
    # ``prefill_chunk`` bounds the [B, P, L] attention-score tensor for
    # long prompts (prefill_chunked) — numerics identical either way.
    if prefill_chunk:
        cache, last = prefill_chunked(model, params, prompt, prompt_len,
                                      max_len, prefill_chunk)
    else:
        cache, last = prefill(model, params, prompt, prompt_len, max_len)
    gen = decode_loop(model, params, cache, last, prompt_len,
                      max_new_tokens, temperature, rng, prompt.dtype)

    out = jnp.concatenate(
        [prompt, jnp.zeros((b, max_new_tokens), prompt.dtype)], axis=1
    )
    return jax.lax.dynamic_update_slice(out, gen, (0, prompt_len))


def decode_loop(model, params, cache, last_logits, prompt_len,
                max_new_tokens: int, temperature, rng, dtype):
    """Phase-2 decode: sample from ``last_logits`` then scan
    ``max_new_tokens - 1`` single-token steps -> generated [B, N].

    The cache must be cued at ``prompt_len`` (what :func:`prefill` or
    the prefix-cache suffix path leaves behind).  ``temperature``
    follows generate()'s greedy-vs-sampling rule (Python 0 is
    structural greedy; any tracer samples).
    """
    greedy = isinstance(temperature, (int, float)) and temperature == 0
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    def sample_from(nxt_logits, rng):
        if greedy:
            return jnp.argmax(nxt_logits, axis=-1).astype(dtype), rng
        rng, sub = jax.random.split(rng)
        tok = jax.random.categorical(sub, nxt_logits / temperature)
        return tok.astype(dtype), rng

    tok0, rng = sample_from(last_logits, rng)

    def step(carry, pos):
        cache, tok, rng = carry
        step_logits, mutated = model.apply(
            {"params": params, "cache": cache},
            tok[:, None],
            positions=jnp.full((1,), pos, jnp.int32),
            mutable=["cache"],
        )
        nxt, rng = sample_from(step_logits[:, 0, :], rng)
        return (mutated["cache"], nxt, rng), nxt

    # Step j feeds the token at output position prompt_len + j (tok0
    # first), so the scan covers max_new_tokens - 1 further positions.
    positions = prompt_len + jnp.arange(max_new_tokens - 1, dtype=jnp.int32)
    (_, _, _), rest = jax.lax.scan(step, (cache, tok0, rng), positions)
    return jnp.concatenate([tok0[:, None], rest.transpose(1, 0)], axis=1)
