"""Autoregressive generation for the transformer LM (KV-cache decode).

Serving-side counterpart of models/lm_train.py: the model is rebuilt
with ``decode=True`` so attention appends to fixed-length cache
variables, and one jitted single-token step is scanned over the target
length — prompt tokens teacher-forced, the rest sampled (greedy at
``temperature=0``, categorical otherwise).  The scan keeps the whole
loop on-device: no per-token host round-trips, static shapes
throughout, one compile for any prompt of the same padded length.
"""

from typing import Optional

import jax
import jax.numpy as jnp


def init_cache(model, batch: int, max_len: int):
    """Zero-filled cache pytree for ``max_len`` tokens (no FLOPs spent:
    shapes come from ``eval_shape``)."""
    shapes = jax.eval_shape(
        model.init,
        jax.random.PRNGKey(0),
        jnp.ones((batch, max_len), jnp.int32),
    )
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"]
    )


def generate(
    model,
    params,
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    prompt_len=None,
):
    """Generate ``max_new_tokens`` past ``prompt`` [B, P] -> [B, P+N].

    ``model`` must be constructed with ``decode=True``.  Jittable with
    static ``max_new_tokens``; ``temperature`` may be a TRACED scalar
    when sampling (only greedy-vs-sampling is structural — a Python
    0 / 0.0 selects greedy; anything else, including a tracer, samples),
    so servers can take the value from the request without recompiling.

    ``prompt_len`` (optional, may be a TRACED scalar) is the number of
    leading ``prompt`` tokens that are real; the rest of the prompt
    array is free padding that never enters the computation — teacher
    forcing stops at ``prompt_len`` and the model generates its own
    continuation from there.  This is the seam that lets a server
    bucket prompt lengths (pad to a power of two) without a compile per
    exact length AND without pad tokens ever reaching the KV cache:
    every token fed is either real prompt or previously generated.
    Defaults to the full (static) prompt width.
    """
    if not model.decode:
        raise ValueError("generate() needs a model built with decode=True")
    greedy = isinstance(temperature, (int, float)) and temperature == 0
    b, plen = prompt.shape
    if prompt_len is None:
        prompt_len = plen
    max_len = plen + max_new_tokens
    cache = init_cache(model, b, max_len)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    padded_prompt = prompt

    def step(carry, i):
        cache, tok, rng = carry
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            tok[:, None],
            positions=jnp.full((1,), i, jnp.int32),
            mutable=["cache"],
        )
        nxt_logits = logits[:, 0, :]
        if greedy:
            sampled = jnp.argmax(nxt_logits, axis=-1)
        else:
            rng, sub = jax.random.split(rng)
            sampled = jax.random.categorical(sub, nxt_logits / temperature)
        sampled = sampled.astype(prompt.dtype)
        # Teacher-force while still inside the (possibly traced-length)
        # prompt; the index clamp keeps the gather in-bounds — the
        # gathered value is unused once past prompt_len.
        in_prompt = i + 1 < prompt_len
        nxt = jnp.where(
            in_prompt,
            jax.lax.dynamic_index_in_dim(
                padded_prompt, jnp.minimum(i + 1, plen - 1), axis=1,
                keepdims=False,
            ),
            sampled,
        )
        return (mutated["cache"], nxt, rng), nxt

    (cache, _, _), toks = jax.lax.scan(
        step,
        (cache, prompt[:, 0], rng),
        jnp.arange(max_len - 1),
    )
    # toks[i] is the token at position i+1.
    return jnp.concatenate([prompt[:, :1], toks.transpose(1, 0)], axis=1)
