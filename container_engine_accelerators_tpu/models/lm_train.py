"""Training step for the transformer LM, dense or sequence-parallel.

Dense mode is a plain jitted step (GSPMD shards the batch like the
ResNet path).  Sequence-parallel mode wraps loss+grad in ``shard_map``
over the mesh's data axis: tokens/labels arrive sharded along the
sequence, params replicated; each device computes its shard's loss
terms and local grads, and one ``psum`` per reduction makes both
global.  The optimizer then runs outside the shard_map under the same
jit — XLA keeps params resident and the collectives on ICI.

Next-token labels are built *globally* before sharding (the label of a
shard's last position lives in the next shard), so the step takes
(tokens, labels, mask) rather than shifting internally.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from container_engine_accelerators_tpu.models.train import TrainState
from container_engine_accelerators_tpu.parallel.mesh import DATA_AXIS
from container_engine_accelerators_tpu.parallel.seq import (
    _ring_positions,
    to_zigzag,
)


def next_token_targets(
    tokens: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """(labels, mask) for causal LM: predict token t+1 at position t."""
    labels = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones(tokens.shape, jnp.float32).at[:, -1].set(0.0)
    return labels, mask


def prepare_seq_parallel_batch(
    tokens: jax.Array,
    seq_parallel: Optional[str] = None,
    n_shards: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(tokens', labels', mask') ready for ``make_lm_train_step``.

    Labels/mask always derive from the ORIGINAL sequence order (a
    shard's last label lives in the next shard); for ``ring-zigzag``
    all three are then reordered into zigzag storage order so plain
    contiguous GSPMD sharding lands chunk pair (i, 2n-1-i) on rank i
    (``n_shards`` = sequence-parallel degree).  Loss/metrics are
    position sums, so they are invariant to the reorder.
    """
    labels, mask = next_token_targets(tokens)
    if seq_parallel == "ring-zigzag":
        if n_shards is None:
            raise ValueError("ring-zigzag batch prep needs n_shards")
        tokens, labels, mask = (
            to_zigzag(x, n_shards) for x in (tokens, labels, mask)
        )
    return tokens, labels, mask


def create_lm_train_state(
    model, rng, sample_tokens, tx: Optional[optax.GradientTransformation] = None
) -> TrainState:
    # Sequence-parallel attention only traces inside shard_map (it needs a
    # bound mesh axis), but the param structure is identical to dense —
    # the schemes differ only in attention *math* — so init a dense clone.
    init_model = model
    if getattr(model, "seq_parallel", None):
        init_model = model.clone(seq_parallel=None)
    # No param depends on sequence length (Embed/Dense/RMSNorm only), so
    # init on a short dummy sequence: a full-length dense init would
    # materialize the [B, H, T, T] attention matrix the sequence-parallel
    # path exists to avoid (e.g. 131072^2 logits at demo scale).
    init_tokens = sample_tokens[:1, : min(sample_tokens.shape[1], 128)]
    # Accelerators: jitted init (eager init bounces every op through the
    # tunnel); CPU: eager (compile costs more than it saves) — see
    # models/train.py:create_train_state.
    init_fn = init_model.init
    if jax.default_backend() != "cpu":
        init_fn = jax.jit(init_model.init)
    variables = init_fn(rng, init_tokens)
    tx = tx or optax.adamw(3e-4, weight_decay=0.1)
    params = variables["params"]
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats={},
        opt_state=tx.init(params),
        tx=tx,
        apply_fn=model.apply,
    )


MOE_AUX_WEIGHT = 0.01  # Switch load-balance coefficient


def _loss(apply_fn, params, tokens, labels, mask, positions):
    logits, mutated = apply_fn(
        {"params": params}, tokens, positions, mutable=["losses"]
    )
    per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    # MoE models sow their Switch load-balance loss; dense models sow
    # nothing and the sum is empty.
    aux = sum(
        jnp.sum(v)
        for v in jax.tree_util.tree_leaves(mutated.get("losses", {}))
    )
    denom = mask.sum()
    return (per_tok * mask).sum() + MOE_AUX_WEIGHT * aux * denom, denom


def make_lm_train_step(
    mesh: Mesh, state: TrainState, seq_parallel: Optional[str] = None,
    param_sharding: str = "megatron",
):
    """Jit the LM step over ``mesh``.

    Returns (step_fn, placed_state); ``step_fn(state, tokens, labels,
    mask) -> (state, metrics)``.  ``seq_parallel`` None shards the batch
    axis (pure dp); "ring"/"ring-zigzag"/"ulysses" shard the sequence
    axis across DATA_AXIS (the model must have been built with the
    matching ``seq_parallel=`` so its attention uses the axis).
    ring-zigzag additionally expects inputs in zigzag storage order —
    build them with :func:`prepare_seq_parallel_batch`.

    ``param_sharding`` (dense mode only): "megatron" shards weights
    over MODEL_AXIS and replicates them along the data axis; "fsdp"
    additionally shards every weight and its optimizer buffers over
    DATA_AXIS (ZeRO-3 — per-chip param+Adam memory drops by the dp
    degree, GSPMD all-gathers weights just-in-time and reduce-scatters
    grads).  The math is identical; only the layout moves.
    """
    rep = NamedSharding(mesh, P())
    apply_fn = state.apply_fn
    tx = state.tx

    if param_sharding not in ("megatron", "fsdp"):
        raise ValueError(f"unknown param_sharding {param_sharding!r}")
    if seq_parallel is not None and param_sharding != "megatron":
        raise ValueError(
            f"param_sharding={param_sharding!r} applies to dense mode "
            f"only — the sequence-parallel path runs under shard_map "
            f"with replicated params (its in_specs are P())"
        )

    if seq_parallel is None:
        from container_engine_accelerators_tpu.parallel.mesh import (
            shard_params,
            shard_params_fsdp,
        )

        # "megatron": tensor parallelism over MODEL_AXIS (same rule as
        # the ResNet path) — params and their same-shaped optimizer
        # buffers shard the largest divisible weight axis, replicated
        # along data.  "fsdp" additionally shards over the data axis
        # (validated above).
        shard = shard_params_fsdp if param_sharding == "fsdp" else shard_params

        state_sh = TrainState(
            step=rep,
            params=shard(state.params, mesh),
            batch_stats=jax.tree_util.tree_map(
                lambda _: rep, state.batch_stats
            ),
            opt_state=shard(state.opt_state, mesh),
            tx=tx,
            apply_fn=apply_fn,
        )
        placed = jax.device_put(state, state_sh)
        data_sh = NamedSharding(mesh, P(DATA_AXIS))

        def step(s, tokens, labels, mask):
            def loss_fn(params):
                num, den = _loss(
                    apply_fn, params, tokens, labels, mask,
                    jnp.arange(tokens.shape[1]),
                )
                return num / den

            loss, grads = jax.value_and_grad(loss_fn)(s.params)
            updates, opt_state = tx.update(grads, s.opt_state, s.params)
            return (
                s.replace(
                    step=s.step + 1,
                    params=optax.apply_updates(s.params, updates),
                    opt_state=opt_state,
                ),
                {"loss": loss},
            )

        jitted = jax.jit(
            step,
            in_shardings=(state_sh, data_sh, data_sh, data_sh),
            out_shardings=(state_sh, rep),
            donate_argnums=(0,),
        )
        return jitted, placed

    # Sequence parallel: tokens [B, T] sharded along T over DATA_AXIS;
    # params replicated (shard_map's in_specs P() requires it).
    state_sh = jax.tree_util.tree_map(lambda _: rep, state)
    placed = jax.device_put(state, state_sh)
    seq_spec = P(None, DATA_AXIS)
    seq_sh = NamedSharding(mesh, seq_spec)

    def shard_loss_grad(params, tokens, labels, mask):
        tq = tokens.shape[1]
        # Positions must match the storage layout: contiguous shards for
        # ring/ulysses; zigzag chunk pairs for ring-zigzag (the rotary
        # embedding and the ring mask both consume these).
        layout = "zigzag" if seq_parallel == "ring-zigzag" else "contiguous"
        positions = _ring_positions(
            layout, lax.axis_index(DATA_AXIS), tq, lax.axis_size(DATA_AXIS)
        )

        def loss_fn(p):
            num, den = _loss(apply_fn, p, tokens, labels, mask, positions)
            return lax.psum(num, DATA_AXIS) / lax.psum(den, DATA_AXIS)

        # No explicit grad psum: params enter replicated (in_specs P()),
        # so shard_map autodiff inserts the cross-device sum as the
        # transpose of the implicit replication broadcast — an explicit
        # psum here would multiply every gradient by the axis size.
        return jax.value_and_grad(loss_fn)(params)

    sharded = jax.shard_map(
        shard_loss_grad,
        mesh=mesh,
        in_specs=(P(), seq_spec, seq_spec, seq_spec),
        out_specs=(P(), P()),
    )

    def step(s, tokens, labels, mask):
        loss, grads = sharded(s.params, tokens, labels, mask)
        updates, opt_state = tx.update(grads, s.opt_state, s.params)
        return (
            s.replace(
                step=s.step + 1,
                params=optax.apply_updates(s.params, updates),
                opt_state=opt_state,
            ),
            {"loss": loss},
        )

    jitted = jax.jit(
        step,
        in_shardings=(state_sh, seq_sh, seq_sh, seq_sh),
        out_shardings=(state_sh, rep),
        donate_argnums=(0,),
    )
    return jitted, placed
