"""Prefix caching: prefill a shared prompt prefix once, reuse its KV
across requests.

Serving fleets front most requests with the same system prompt; plain
``generate()`` re-runs the full prefill for every request, re-spending
MXU FLOPs (and wall-clock TTFT) on tokens whose KV never changes.  This
module caches the prefix's KV block after one prefill and splices it
into each request's fresh cache, so the per-request prefill covers only
the suffix.

TPU-first mechanics — everything rides the invariants the serving
stack already pins:

- the stored block is the prefix prefill's cache at its power-of-two
  BUCKET length (one compile per bucket, like prompt bucketing); slots
  beyond the true ``prefix_len`` hold dead pad KV;
- splicing is a ``dynamic_update_slice`` of the block into slot 0 of
  the request's zero cache, cursor set to ``prefix_len`` — from there
  the suffix continues through :func:`generate.prefill_continue` at
  positions ``prefix_len + arange(S)`` and decode proceeds normally;
- dead slots (prefix pad, suffix pad, anything beyond the cursor) are
  invisible by the slot<=position mask until overwritten in order —
  the same dead-slot argument as bucket padding (generate.py), just
  starting from a non-zero cursor.

The compile-cache footprint is (prefix buckets) x (suffix buckets) —
bounded log^2, nothing request-controlled (the ADVICE r03 lesson).

Exactness contract (tests/test_prefix_cache.py): splice + suffix
prefill + decode == ``generate()`` over the concatenated prompt,
token-for-token, greedy and seeded-sampled, MHA and GQA.

The reference has no serving runtime; the in-framework altitude analog
is the continuous-batching engine (models/batching.py), which shares
the bucket grammar via ``bucket_len``.
"""

import threading
from collections import OrderedDict
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from container_engine_accelerators_tpu.models.batching import bucket_len
from container_engine_accelerators_tpu.models.generate import (
    decode_loop,
    init_cache,
    prefill,
    prefill_continue,
    prefix_bucket_len,
    splice_prefix,
)

# The splice/bucket primitives live in generate.py (shared with the
# continuous-batching engine); re-exported here for callers that think
# in prefix-cache terms.
_splice_prefix = splice_prefix


@partial(jax.jit, static_argnames=("model",))
def _build_prefix(model, params, pfx, plen):
    """Prefill a prefix block — shared across PrefixCache instances on
    an equal model (flax modules hash by config)."""
    return prefill(model, params, pfx, plen, pfx.shape[1])[0]


def generate_with_prefix(
    model,
    params,
    prefix_kv,
    prefix_len,
    suffix: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    suffix_len=None,
):
    """Generate past (cached prefix + ``suffix`` [B, S]) -> [B, S+N].

    ``prefix_kv`` is a :class:`PrefixCache` entry's KV tree (bucket
    length read from its leaves); ``prefix_len``/``suffix_len`` may be
    traced (bucket-padding semantics as in ``generate()``).  Output
    mirrors generate() with the suffix as the prompt: positions
    ``[0, suffix_len)`` echo the suffix, ``[suffix_len, suffix_len+N)``
    are generated — the caller owns re-attaching the prefix ids.
    """
    if not model.decode:
        raise ValueError(
            "generate_with_prefix() needs a model built with decode=True")
    b, s = suffix.shape
    if suffix_len is None:
        suffix_len = s
    pfx_bucket = prefix_bucket_len(prefix_kv)
    total = pfx_bucket + s + max_new_tokens

    cache = init_cache(model, b, total)
    cache = splice_prefix(cache, prefix_kv, prefix_len, b)
    end = prefix_len + suffix_len
    cache, last = prefill_continue(
        model, params, cache, suffix, prefix_len, end)
    gen = decode_loop(model, params, cache, last, end, max_new_tokens,
                      temperature, rng, suffix.dtype)

    out = jnp.concatenate(
        [suffix, jnp.zeros((b, max_new_tokens), suffix.dtype)], axis=1)
    return jax.lax.dynamic_update_slice(out, gen, (0, suffix_len))


class PrefixCache:
    """Host-side LRU of prefilled prefix KV blocks, keyed by the exact
    token tuple.  Thread-safe for serving handlers; misses build
    outside the lock (two racing misses on the same new prefix cost one
    redundant prefill, never a wrong entry)."""

    def __init__(self, model, params, max_prefix_len: int,
                 max_entries: int = 8):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.max_prefix_len = max_prefix_len
        self.max_entries = max_entries
        self._store = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # One compile per prefix bucket (shape-keyed jit), SHARED
        # across caches on an equal model (module-level jit with the
        # flax module static — a per-instance jit of this lambda would
        # recompile per cache by function identity; see
        # models/batching.py's shared-kernel note).
        self._build = lambda pfx, plen: _build_prefix(
            model, params, pfx, plen)

    def get_or_build(self, ids: Tuple[int, ...]):
        """-> (prefix_kv tree, prefix_len) for the exact prefix ``ids``.

        ``ids`` longer than ``max_prefix_len`` are rejected (the caller
        decides how to degrade — serve_lm falls back to the plain
        path)."""
        ids = tuple(int(t) for t in ids)
        if not ids or len(ids) > self.max_prefix_len:
            raise ValueError(
                f"prefix length {len(ids)} outside (0, "
                f"{self.max_prefix_len}]")
        with self._lock:
            entry = self._store.get(ids)
            if entry is not None:
                self._store.move_to_end(ids)
                self.hits += 1
                return entry
            self.misses += 1
        bucket = bucket_len(len(ids), self.max_prefix_len)
        padded = jnp.asarray(
            [list(ids) + [0] * (bucket - len(ids))], jnp.int32)
        kv = self._build(padded, len(ids))
        entry = (kv, len(ids))
        with self._lock:
            self._store[ids] = entry
            self._store.move_to_end(ids)
            while len(self._store) > self.max_entries:
                self._store.popitem(last=False)
                self.evictions += 1
        return entry

    def __len__(self):
        with self._lock:
            return len(self._store)

    def stats(self):
        with self._lock:
            return {"entries": len(self._store), "hits": self.hits,
                    "misses": self.misses, "evictions": self.evictions}
