"""Decoder-only transformer LM — the long-context demo workload.

The reference demos only convolutional families (TF ResNet sweep,
demo/gpu-training/generate_job.sh:19-24; TPU ResNet/Inception jobs,
demo/tpu-training/*.yaml); its long-sequence story is bandwidth
infrastructure, not model code (SURVEY.md §5).  This model is the
TPU-native counterpart that makes the sequence-parallel fabric
(parallel/seq.py) load-bearing: a pre-norm decoder LM whose attention
can run dense (single device), ring (ppermute over ICI), or Ulysses
(all_to_all), selected per call.

TPU-first choices: bf16 compute / f32 params, RMSNorm (one fused
rsqrt, no mean subtraction), SwiGLU MLP (two matmuls feed one
elementwise gate — MXU-dense), rotary position embeddings computed
with static shapes, grouped-query attention (``num_kv_heads``) so the
decode KV cache — the HBM-bandwidth term that bounds serving
tokens/sec — shrinks by the group factor, and no data-dependent
control flow anywhere, so the whole step jits and shards under GSPMD.
"""

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from container_engine_accelerators_tpu.ops.flash_attention import (
    flash_attention,
    supports_flash,
)
from container_engine_accelerators_tpu.parallel.seq import (
    dense_attention,
    ring_attention,
    ulysses_attention,
)


def _on_tpu() -> bool:
    return jax.devices()[0].platform in ("tpu", "axon")


def rotary_embedding(x: jax.Array, positions: jax.Array) -> jax.Array:
    """Apply RoPE to ``x`` [B, T, H, D] at absolute ``positions``.

    ``positions`` is [T] (shared across the batch — training, and
    whole-batch generation) or [B, T] (per-sample — continuous-batching
    decode, where every slot sits at its own depth).  Passed explicitly
    so sequence-parallel shards rotate with their *global* offsets.
    """
    d = x.shape[-1]
    half = d // 2
    freq = 1.0 / (10000.0 ** (jnp.arange(half, dtype=jnp.float32) / half))
    theta = positions[..., None].astype(jnp.float32) * freq  # [..., T, half]
    if positions.ndim == 1:
        theta = theta[None]
    cos = jnp.cos(theta)[:, :, None, :].astype(x.dtype)  # [B|1, T, 1, half]
    sin = jnp.sin(theta)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)


class RMSNorm(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        y = x.astype(jnp.float32)
        y = y * jax.lax.rsqrt(jnp.mean(y * y, -1, keepdims=True) + 1e-6)
        return (y * scale).astype(self.dtype)


def _dense_factory(quant: bool, dtype):
    """Projection-module factory: nn.DenseGeneral, or the int8
    QDenseGeneral when serving quantized weights (models/quant.py).
    One seam so the quant wiring can't diverge between sublayers."""
    if quant:
        from container_engine_accelerators_tpu.models.quant import (
            QDenseGeneral,
        )

        return functools.partial(QDenseGeneral, dtype=dtype)
    return functools.partial(nn.DenseGeneral, use_bias=False, dtype=dtype)


class Attention(nn.Module):
    num_heads: int
    head_dim: int
    dtype: Any = jnp.bfloat16
    seq_parallel: Optional[str] = None  # None | "ring" | "ulysses"
    seq_axis: str = "data"
    use_flash: Optional[bool] = None  # None = auto: TPU + tile-aligned
    decode: bool = False  # autoregressive KV-cache mode
    # Grouped-query attention (GQA): project K/V to this many heads
    # (None = MHA).  The KV cache and the K/V projections shrink by
    # num_heads/num_kv_heads — on TPU the decode step is HBM-bound on
    # the cache read, so this is a direct tokens/sec and
    # max-context-length lever for serving.
    num_kv_heads: Optional[int] = None
    # int8 kernels + f32 scales (models/quant.py): 4x less param HBM
    # traffic per decoded token.  Params come from quantize_params().
    quant: bool = False
    # Pallas flash-decode kernel (ops/flash_decode.py) for the
    # single-token cache attention: streams the cache in chunks and
    # SKIPS chunks beyond the visible length instead of masking the
    # whole fixed buffer.  Long-context serving lever; explicit opt-in,
    # single chip (no GSPMD rule — the tp path keeps XLA einsums).
    use_flash_decode: bool = False

    @nn.compact
    def __call__(self, x, positions):
        dense = _dense_factory(self.quant, self.dtype)
        kv_heads = self.num_kv_heads or self.num_heads
        if self.num_heads % kv_heads:
            raise ValueError(
                f"num_heads={self.num_heads} not divisible by "
                f"num_kv_heads={kv_heads}"
            )
        q = dense((self.num_heads, self.head_dim), name="q")(x)
        k = dense((kv_heads, self.head_dim), name="k")(x)
        v = dense((kv_heads, self.head_dim), name="v")(x)
        q = rotary_embedding(q, positions)
        k = rotary_embedding(k, positions)

        if self.decode:
            if self.seq_parallel:
                raise ValueError(
                    "decode mode is single-sequence; it does not compose "
                    "with sequence parallelism"
                )
            return dense(x.shape[-1], axis=(-2, -1), name="out")(
                self._decode_attend(q, k, v, positions)
            )

        if kv_heads != self.num_heads:
            # Training/prefill paths share MHA kernels (flash, ring,
            # Ulysses all assume equal Q/KV heads): broadcast K/V up.
            # XLA fuses the repeat into the consuming matmul, so no
            # materialized copy; the projection/optimizer savings stand.
            # Decode does NOT take this path — its cache stays at
            # kv_heads and the einsums group instead (_decode_attend).
            rep = self.num_heads // kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)

        if self.seq_parallel in ("ring", "ring-zigzag"):
            # ring-zigzag: shards are in zigzag storage order (the
            # balanced causal layout, parallel/seq.py); rotary above
            # already used the matching positions the caller passed.
            layout = (
                "zigzag" if self.seq_parallel == "ring-zigzag"
                else "contiguous"
            )
            o = ring_attention(q, k, v, axis_name=self.seq_axis,
                               causal=True, layout=layout)
        elif self.seq_parallel == "ulysses":
            o = ulysses_attention(
                q, k, v, axis_name=self.seq_axis, causal=True
            )
        else:
            flash = self.use_flash
            if flash is None:
                # Auto only on a SINGLE chip: pallas_call has no GSPMD
                # partitioning rule, so under a sharded jit it would
                # gather full q/k/v per chip.  Multi-chip dense mode
                # keeps XLA attention (which partitions); callers that
                # wrap the model in shard_map may force use_flash=True.
                flash = (
                    _on_tpu()
                    and jax.device_count() == 1
                    and supports_flash(q.shape[1], self.head_dim)
                )
            if flash:
                o = flash_attention(q, k, v, True)
            else:
                o = dense_attention(q, k, v, causal=True)
        return dense(
            x.shape[-1], axis=(-2, -1), name="out"
        )(o)

    def _decode_attend(self, q, k, v, positions):
        """KV-cache attention: append this call's K/V at the cache cursor
        and attend the queries over everything cached so far.  The cache
        length is fixed by the shape used at ``init`` (flax's standard
        cache-variable pattern), so the decode step jits once and is
        reused for every token.

        Under GQA the cache holds only ``num_kv_heads`` heads and the
        score/value einsums group the query heads over them — the
        repeat is never materialized, so the HBM read per decoded
        token shrinks by the group factor.

        The write cursor is PER SAMPLE (``cache_index`` [B]) and
        ``positions`` may be [B, T]: continuous-batching serving steps
        a fixed fleet of slots each sitting at its own depth.  Batched
        single-sequence generation passes shared [T] positions and a
        uniform cursor — the same code path.
        """
        b, t, h, d = q.shape
        kvh = k.shape[2]
        cached_k = self.variable(
            "cache", "cached_key",
            lambda: jnp.zeros((b, t, kvh, d), k.dtype),
        )
        cached_v = self.variable(
            "cache", "cached_value",
            lambda: jnp.zeros((b, t, kvh, d), v.dtype),
        )
        cache_index = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((b,), jnp.int32)
        )
        if self.is_initializing():
            # init just shapes the cache to the full target length
            return jnp.zeros_like(q)

        idx = cache_index.value  # [b]
        max_len = cached_k.value.shape[1]

        def write(buf, new, i):
            return jax.lax.dynamic_update_slice(buf, new, (i, 0, 0))

        cached_k.value = jax.vmap(write)(cached_k.value, k, idx)
        cached_v.value = jax.vmap(write)(cached_v.value, v, idx)
        cache_index.value = idx + t

        # Group query heads over the (possibly fewer) cached KV heads:
        # q head g*i+j attends KV head i.  With kvh == h the reshape is
        # the identity grouping and this is plain MHA.
        if self.use_flash_decode and t == 1:
            from container_engine_accelerators_tpu.ops.flash_decode import (
                flash_decode,
            )

            pos_b = (
                positions[:, 0] if positions.ndim == 2
                else jnp.broadcast_to(positions[0], (b,))
            )
            o = flash_decode(
                q[:, 0], cached_k.value, cached_v.value, pos_b + 1,
                scale=self.head_dim ** -0.5,
                interpret=jax.devices()[0].platform == "cpu",
            )
            return o[:, None].astype(q.dtype)

        group = h // kvh
        qg = q.reshape(b, t, kvh, group, d)
        s = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qg * (self.head_dim**-0.5),
            cached_k.value, preferred_element_type=jnp.float32,
        )
        # Key slot j is visible to a query at global position p when
        # j <= p; `positions` is [t] (shared) or [b, t] (per slot).
        key_pos = jnp.arange(max_len)
        pos_bt = positions if positions.ndim == 2 else positions[None]
        mask = key_pos[None, None, :] <= pos_bt[:, :, None]  # [b|1, t, L]
        s = jnp.where(mask[:, None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum(
            "bhgqk,bkhd->bqhgd", p, cached_v.value,
            preferred_element_type=jnp.float32,
        )
        return o.reshape(b, t, h, d).astype(q.dtype)


class Block(nn.Module):
    num_heads: int
    head_dim: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    seq_parallel: Optional[str] = None
    seq_axis: str = "data"
    use_flash: Optional[bool] = None
    decode: bool = False
    num_experts: int = 0  # >0: MoE FFN (Switch top-1) instead of dense
    num_kv_heads: Optional[int] = None  # GQA (None = MHA)
    quant: bool = False  # int8 kernels (models/quant.py)
    moe_capacity_factor: float = 1.25  # train-mode MoE capacity
    use_flash_decode: bool = False  # Pallas cache-attention kernel

    @nn.compact
    def __call__(self, x, positions):
        y = RMSNorm(dtype=self.dtype, name="ln_attn")(x)
        x = x + Attention(
            self.num_heads,
            self.head_dim,
            self.dtype,
            self.seq_parallel,
            self.seq_axis,
            self.use_flash,
            self.decode,
            num_kv_heads=self.num_kv_heads,
            quant=self.quant,
            use_flash_decode=self.use_flash_decode,
            name="attn",
        )(y, positions)
        y = RMSNorm(dtype=self.dtype, name="ln_mlp")(x)
        if self.num_experts > 0:
            from container_engine_accelerators_tpu.ops.moe import MoEFFN

            out, aux = MoEFFN(
                num_experts=self.num_experts,
                mlp_dim=self.mlp_dim,
                dtype=self.dtype,
                capacity_factor=self.moe_capacity_factor,
                # Decode must route drop-free: train-style capacity
                # depends on the token count, so single-token steps and
                # the prefill would drop different tokens than a full
                # forward and the KV-cache contract would break.
                no_drop=self.decode,
                name="moe",
            )(y)
            return x + out, aux
        # nn.DenseGeneral with int features == nn.Dense (same kernel
        # shape and param name), so the factory serves the MLP too.
        dense = _dense_factory(self.quant, self.dtype)
        gate = dense(self.mlp_dim, name="gate")(y)
        up = dense(self.mlp_dim, name="up")(y)
        x = x + dense(x.shape[-1], name="down")(nn.silu(gate) * up)
        return x, jnp.zeros((), jnp.float32)


class _ScanBlock(nn.Module):
    """Block wrapped into nn.scan's (carry, out) contract; the per-layer
    MoE aux loss rides the scan's output slot."""

    num_heads: int
    head_dim: int
    mlp_dim: int
    dtype: Any
    seq_parallel: Optional[str]
    seq_axis: str
    use_flash: Optional[bool]
    decode: bool
    num_experts: int = 0
    num_kv_heads: Optional[int] = None
    quant: bool = False
    moe_capacity_factor: float = 1.25
    use_flash_decode: bool = False

    @nn.compact
    def __call__(self, x, positions):
        x, aux = Block(
            self.num_heads,
            self.head_dim,
            self.mlp_dim,
            self.dtype,
            self.seq_parallel,
            self.seq_axis,
            self.use_flash,
            self.decode,
            self.num_experts,
            num_kv_heads=self.num_kv_heads,
            quant=self.quant,
            moe_capacity_factor=self.moe_capacity_factor,
            use_flash_decode=self.use_flash_decode,
            name="block",
        )(x, positions)
        return x, aux


class TransformerLM(nn.Module):
    """Causal LM.  ``__call__(tokens [B, T], positions [T]) -> logits``.

    ``positions`` defaults to ``arange(T)``; sequence-parallel callers
    pass each shard's global positions.
    """

    vocab_size: int = 32_000
    num_layers: int = 12
    num_heads: int = 8
    head_dim: int = 64
    mlp_dim: int = 2048
    dtype: Any = jnp.bfloat16
    seq_parallel: Optional[str] = None
    seq_axis: str = "data"
    use_flash: Optional[bool] = None
    decode: bool = False
    num_experts: int = 0  # >0: MoE-LM (Switch FFN in every block)
    num_kv_heads: Optional[int] = None  # GQA (None = MHA)
    quant: bool = False  # int8 serving kernels (models/quant.py)
    moe_capacity_factor: float = 1.25  # train-mode MoE capacity
    use_flash_decode: bool = False  # Pallas cache-attention kernel
    remat: bool = True  # rematerialize blocks in backward (saves HBM)

    @nn.compact
    def __call__(self, tokens, positions=None, train: bool = True,
                 project: bool = True):
        del train  # no dropout: demo parity with the reference trainers
        if positions is None:
            positions = jnp.arange(tokens.shape[1])
        emb = nn.Embed(
            self.vocab_size,
            self.num_heads * self.head_dim,
            dtype=self.dtype,
            name="embed",
        )
        x = emb(tokens)
        block_args = (
            self.num_heads,
            self.head_dim,
            self.mlp_dim,
            self.dtype,
            self.seq_parallel,
            self.seq_axis,
            self.use_flash,
            self.decode,
            self.num_experts,
            self.num_kv_heads,
            self.quant,
            self.moe_capacity_factor,
            self.use_flash_decode,
        )
        # Scan over a single stacked Block: compile time is O(1) in depth
        # instead of O(num_layers) — with a Python loop the 12-layer
        # flash-attention step took >15 min to compile on the TPU backend;
        # XLA sees one layer either way.  Decode mode scans its KV cache
        # along the same leading layer axis, so train-mode params load
        # directly into a decode-mode model (one param-tree layout).
        # Remat each scanned layer: without it the backward saves every
        # layer's SwiGLU/attention activations (O(layers * B * T * mlp)
        # HBM — a 12L/4096-seq train step OOMs a 16 GB chip); with it the
        # scan carry is the only per-layer residual and the block
        # recomputes inside the backward sweep.  prevent_cse=False is the
        # documented setting under scan (the loop structure already
        # prevents the CSE remat guards against).
        block_cls = (
            nn.remat(_ScanBlock, prevent_cse=False)
            if self.remat
            else _ScanBlock
        )
        stack = nn.scan(
            block_cls,
            variable_axes={"params": 0, "cache": 0},
            split_rngs={"params": True},
            length=self.num_layers,
            in_axes=nn.broadcast,
            metadata_params={nn.meta.PARTITION_NAME: "layers"},
        )(*block_args, name="blocks")
        x, layer_aux = stack(x, positions)
        if self.num_experts > 0:
            # Total Switch load-balance loss; training reads it via
            # mutable=["losses"] (lm_train adds it to the CE loss).
            self.sow("losses", "moe_aux", jnp.sum(layer_aux))
        x = RMSNorm(dtype=self.dtype, name="ln_f")(x)
        if not project:
            # Pre-projection hidden states: callers that consume only a
            # few positions (batched prefill gathers ONE row) skip the
            # B*T*vocab LM-head matmul and project the gathered rows
            # themselves against params["embed"]["embedding"] with the
            # same dtype rules as below (models/generate.py does).
            return x
        # Final projection with TRUE f32 logits for a numerically stable
        # softmax loss: Embed.attend would promote the query back to the
        # module dtype (bf16), so tie the weights manually.  Operands stay
        # in the compute dtype with f32 ACCUMULATION
        # (preferred_element_type) — the MXU runs at bf16 rate and the
        # logits tensor still comes out f32.
        return jnp.dot(
            x,
            emb.embedding.T.astype(x.dtype),
            preferred_element_type=jnp.float32,
        )


def transformer_lm(**kwargs) -> TransformerLM:
    return TransformerLM(**kwargs)
