"""Greedy speculative decoding: draft k cheap tokens, verify them in
ONE target forward.

Plain decode is HBM-bound: every generated token re-reads all target
params and cache (the roofline bench.py's decode workload measures).
Speculative decoding converts k of those sequential reads into one
MXU-dense (k+1)-token verify chunk — the chunk re-reads params ONCE for
k+1 positions, so accepted drafts cost ~1/k of the bandwidth.  This is
the serving-side counterpart of prefill's batching (generate.py phase
1), applied to the decode phase.

The invariant that makes it testable: with greedy acceptance the output
is EXACTLY the target model's own greedy continuation — the draft can
only change the speed, never a token.  Concretely, each round:

1. draft autoregressively proposes ``d_1..d_k`` (k+1 single-token
   steps — the extra step keeps the draft's own cache complete when
   all k are accepted);
2. the target runs ONE forward over ``[t_last, d_1..d_k]`` at
   positions ``p0..p0+k`` (the same chunked-continuation the batched
   prefill uses, so it hits the MXU);
3. the longest prefix of drafts matching the target's argmax at each
   position is accepted, plus the target's own token at the first
   divergence (or the bonus token when everything matched): ``m+1``
   tokens per round for ``m`` accepted drafts;
4. both caches' write cursors rewind to the new head position — stale
   slots beyond the cursor are dead, exactly like bucket-padding slots
   (generate.py's ``_rewind_cache_index`` semantics): the visibility
   mask hides them and in-order writes overwrite them.

Everything is static-shape: the round is a ``lax.while_loop`` whose
body runs a fixed k+1-step draft scan and one fixed (k+1)-token verify,
so the whole generation jits once per (prompt bucket, max_new, k).

The reference has no model runtime; within this framework the
counterpart contracts are generate.py (greedy == iterated train argmax)
and batching.py (fleet == per-request) — this module extends that
exactness chain to the draft/verify composition.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from container_engine_accelerators_tpu.models.generate import (
    _rewind_cache_index,
    init_cache,
    prefill,
    prefill_continue,
    prefix_bucket_len,
    splice_prefix,
)


def _spec_setup(model, params, draft_model, draft_params, prompt,
                max_new_tokens, k, prompt_len, prefix):
    """Shared prefill/splice setup for both speculative variants —
    the cache/margin/ctx contract lives HERE so the greedy and sampled
    rounds cannot drift: both caches cued past prompt (+ spliced
    prefix), ``margin = plen + max_new + k + 1`` because the final
    round can overshoot by up to k and finished samples keep
    clamp-writing into the tail while stragglers catch up.

    Returns ``(t_cache, d_cache, t_last_logits, ctx_len, prompt_len,
    out0, g0, stats0)`` where ``out0`` is the output buffer WITHOUT
    the first token written (the variants decode tok0 differently:
    argmax vs a sample) and ``ctx_len`` is the global depth of the
    last real prompt token + 1 — cache positions are ctx-global while
    the output buffer stays suffix-local (prompt_len-indexed).
    """
    b, plen = prompt.shape
    if prompt_len is None:
        prompt_len = plen
    prompt_len = jnp.asarray(prompt_len, jnp.int32)

    if prefix is None:
        prefix_len = jnp.zeros((), jnp.int32)
        t_pfx_bucket = d_pfx_bucket = 0
    else:
        t_kv, d_kv, prefix_len = prefix
        prefix_len = jnp.asarray(prefix_len, jnp.int32)
        t_pfx_bucket = prefix_bucket_len(t_kv)
        d_pfx_bucket = prefix_bucket_len(d_kv)
    ctx_len = prefix_len + prompt_len
    margin = plen + max_new_tokens + k + 1

    if prefix is None:
        t_cache, t_last_logits = prefill(
            model, params, prompt, prompt_len, margin)
        d_cache, _ = prefill(
            draft_model, draft_params, prompt, prompt_len, margin)
    else:
        t_cache = init_cache(model, b, t_pfx_bucket + margin)
        t_cache = splice_prefix(t_cache, t_kv, prefix_len, b)
        t_cache, t_last_logits = prefill_continue(
            model, params, t_cache, prompt, prefix_len, ctx_len)
        d_cache = init_cache(draft_model, b, d_pfx_bucket + margin)
        d_cache = splice_prefix(d_cache, d_kv, prefix_len, b)
        d_cache, _ = prefill_continue(
            draft_model, draft_params, d_cache, prompt, prefix_len,
            ctx_len)

    out0 = jnp.concatenate(
        [prompt, jnp.zeros((b, max_new_tokens + k + 1), prompt.dtype)],
        axis=1,
    )
    g0 = jnp.ones((b,), jnp.int32)  # tok0 emitted by the caller
    stats0 = {
        "rounds": jnp.zeros((), jnp.int32),
        "drafted": jnp.zeros((b,), jnp.int32),
        "accepted": jnp.zeros((b,), jnp.int32),
    }
    return (t_cache, d_cache, t_last_logits, ctx_len, prompt_len, out0,
            g0, stats0)


def generate_speculative(
    model,
    params,
    draft_model,
    draft_params,
    prompt: jax.Array,
    max_new_tokens: int,
    k: int = 4,
    prompt_len=None,
    prefix=None,
):
    """Greedy-decode ``max_new_tokens`` past ``prompt`` [B, P] with
    k-token speculation -> (tokens [B, P+N], stats).

    Both models must be built with ``decode=True`` and share the
    vocabulary.  ``prompt_len`` has generate()'s bucket-padding
    semantics (may be traced).  ``stats`` is a dict of arrays:
    ``rounds`` (scalar), ``drafted``/``accepted`` ([B], counted only
    while the sample was still generating) — acceptance rate =
    accepted/drafted is the lever that decides the realized speedup.

    Output layout matches generate(): positions [prompt_len,
    prompt_len + max_new_tokens) hold the generated tokens, and they
    equal the target model's own greedy continuation token-for-token.

    ``prefix`` is the prefix-cache composition:
    ``(target_kv, draft_kv, prefix_len)`` — each model's OWN prefilled
    block for the shared system prompt (a PrefixCache per model;
    serve_lm holds both).  ``prompt`` then carries only the suffix and
    the output layout stays suffix-local, exactly like
    :func:`~.prefix_cache.generate_with_prefix`.
    """
    if not (model.decode and draft_model.decode):
        raise ValueError(
            "generate_speculative() needs decode=True models")
    if k < 1:
        raise ValueError("k must be >= 1")
    b, plen = prompt.shape
    (t_cache, d_cache, t_last_logits, ctx_len, prompt_len, out, g0,
     stats0) = _spec_setup(model, params, draft_model, draft_params,
                           prompt, max_new_tokens, k, prompt_len, prefix)

    tok0 = jnp.argmax(t_last_logits, axis=-1).astype(prompt.dtype)
    out = jax.lax.dynamic_update_slice(out, tok0[:, None], (0, prompt_len))

    def cond(carry):
        _, _, _, g, _, _ = carry
        return jnp.min(g) < max_new_tokens

    def body(carry):
        t_cache, d_cache, out, g, t_last, stats = carry
        active = g < max_new_tokens
        p0 = ctx_len + g - 1  # [B] global position of t_last

        # Draft phase: k+1 single-token steps (feed t_last, then each
        # proposal; the last feed only completes the draft cache).
        def dstep(c, _):
            d_cache, tok, pos = c
            logits, mut = draft_model.apply(
                {"params": draft_params, "cache": d_cache},
                tok[:, None],
                positions=pos[:, None],
                mutable=["cache"],
            )
            nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(tok.dtype)
            return (mut["cache"], nxt, pos + 1), nxt

        (d_cache, _, _), drafts = jax.lax.scan(
            dstep, (d_cache, t_last, p0), None, length=k + 1
        )
        drafts = drafts.transpose(1, 0)[:, :k]  # [B, k]: d_1..d_k

        # Verify phase: ONE chunked target forward.
        chunk = jnp.concatenate([t_last[:, None], drafts], axis=1)
        pos_chunk = p0[:, None] + jnp.arange(k + 1, dtype=jnp.int32)[None]
        logits, mut = model.apply(
            {"params": params, "cache": t_cache},
            chunk,
            positions=pos_chunk,
            mutable=["cache"],
        )
        t_cache = mut["cache"]
        tgt_choice = jnp.argmax(logits, axis=-1).astype(t_last.dtype)

        # m = longest matching prefix; emit d_1..d_m + target's token.
        matches = (drafts == tgt_choice[:, :k]).astype(jnp.int32)
        m = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)  # [B]
        next_tok = jnp.take_along_axis(
            tgt_choice, m[:, None], axis=1)[:, 0]
        row = jnp.concatenate(
            [drafts, jnp.zeros((b, 1), drafts.dtype)], axis=1)
        row = row.at[jnp.arange(b), m].set(next_tok)

        out = jax.vmap(
            lambda o, r, off: jax.lax.dynamic_update_slice(o, r, (off,))
        )(out, row, prompt_len + g)

        g = g + m + 1
        t_cache = _rewind_cache_index(t_cache, ctx_len + g - 1)
        d_cache = _rewind_cache_index(d_cache, ctx_len + g - 1)
        stats = {
            "rounds": stats["rounds"] + 1,
            "drafted": stats["drafted"] + jnp.where(active, k, 0),
            "accepted": stats["accepted"] + jnp.where(active, m, 0),
        }
        return t_cache, d_cache, out, g, next_tok, stats

    _, _, out, _, _, stats = jax.lax.while_loop(
        cond, body, (t_cache, d_cache, out, g0, tok0, stats0)
    )
    return out[:, : plen + max_new_tokens], stats


def generate_speculative_sampled(
    model,
    params,
    draft_model,
    draft_params,
    prompt: jax.Array,
    max_new_tokens: int,
    k: int = 4,
    temperature: float = 1.0,
    rng: Optional[jax.Array] = None,
    prompt_len=None,
    prefix=None,
):
    """Distribution-exact SAMPLED speculative decoding (VERDICT r4
    item 3): the classic rejection scheme — draft samples ``x_i ~ q``,
    the one chunked target forward yields ``p`` at every position,
    ``x_i`` is accepted with probability ``min(1, p_i(x_i)/q_i(x_i))``,
    and the first rejection resamples from the residual
    ``normalize(max(p - q, 0))``; a fully-accepted round samples the
    bonus position from ``p`` directly.  The output token distribution
    is EXACTLY the target's temperature sampling, for ANY draft — the
    draft only moves the speed (tests/test_speculative.py pins the
    marginals against plain sampling with a deliberately mismatched
    draft).

    Same cache/cursor/layout contract as :func:`generate_speculative`
    (bucket padding via ``prompt_len``, optional
    ``prefix=(target_kv, draft_kv, prefix_len)`` splice, stats dict);
    ``temperature`` may be a traced scalar but must be > 0 — the
    greedy limit is :func:`generate_speculative`, which serve_lm
    routes to separately.  The first token is sampled from the prefill
    logits, as in ``generate()``'s sampled path.

    Implementation notes: acceptance tests ``u * q(x) < p(x)`` (the
    division-free form of ``u < p/q``); the bonus case reuses the
    residual formula with ``q`` padded to zero at index k, where
    ``max(p - 0, 0) = p``; an identically-zero residual (p == q
    exactly) falls back to sampling ``p``.
    """
    if not (model.decode and draft_model.decode):
        raise ValueError(
            "generate_speculative_sampled() needs decode=True models")
    if k < 1:
        raise ValueError("k must be >= 1")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    b, plen = prompt.shape
    temperature = jnp.asarray(temperature, jnp.float32)
    (t_cache, d_cache, t_last_logits, ctx_len, prompt_len, out, g0,
     stats0) = _spec_setup(model, params, draft_model, draft_params,
                           prompt, max_new_tokens, k, prompt_len, prefix)

    rng, k0 = jax.random.split(rng)
    tok0 = jax.random.categorical(
        k0, t_last_logits / temperature).astype(prompt.dtype)
    out = jax.lax.dynamic_update_slice(out, tok0[:, None], (0, prompt_len))

    def cond(carry):
        _, _, _, g, _, _, _ = carry
        return jnp.min(g) < max_new_tokens

    def body(carry):
        t_cache, d_cache, out, g, t_last, stats, rkey = carry
        active = g < max_new_tokens
        p0 = ctx_len + g - 1
        rkey, kd, ka, kr = jax.random.split(rkey, 4)

        def dstep(c, i):
            cache, tok, pos = c
            logits, mut = draft_model.apply(
                {"params": draft_params, "cache": cache},
                tok[:, None],
                positions=pos[:, None],
                mutable=["cache"],
            )
            logits = logits[:, 0, :] / temperature
            nxt = jax.random.categorical(
                jax.random.fold_in(kd, i), logits).astype(tok.dtype)
            return (mut["cache"], nxt, pos + 1), (
                nxt, jax.nn.softmax(logits, axis=-1))

        (d_cache, _, _), (draft_toks, draft_qs) = jax.lax.scan(
            dstep, (d_cache, t_last, p0), jnp.arange(k + 1)
        )
        drafts = draft_toks.transpose(1, 0)[:, :k]       # [B, k]
        qs = draft_qs.transpose(1, 0, 2)[:, :k, :]       # [B, k, V]

        chunk = jnp.concatenate([t_last[:, None], drafts], axis=1)
        pos_chunk = p0[:, None] + jnp.arange(k + 1, dtype=jnp.int32)[None]
        logits, mut = model.apply(
            {"params": params, "cache": t_cache},
            chunk,
            positions=pos_chunk,
            mutable=["cache"],
        )
        t_cache = mut["cache"]
        ps = jax.nn.softmax(logits / temperature, axis=-1)  # [B, k+1, V]

        p_at = jnp.take_along_axis(
            ps[:, :k, :], drafts[..., None], axis=-1)[..., 0]  # [B, k]
        q_at = jnp.take_along_axis(
            qs, drafts[..., None], axis=-1)[..., 0]            # [B, k]
        u = jax.random.uniform(ka, (b, k))
        accepted = (u * q_at < p_at).astype(jnp.int32)
        m = jnp.sum(jnp.cumprod(accepted, axis=1), axis=1)     # [B]

        # Residual at the first rejected position; q padded to zero at
        # index k makes the all-accepted bonus case the same formula
        # (max(p - 0, 0) = p).
        qs_pad = jnp.concatenate(
            [qs, jnp.zeros_like(ps[:, :1, :])], axis=1)        # [B, k+1, V]
        p_m = jnp.take_along_axis(
            ps, m[:, None, None], axis=1)[:, 0, :]             # [B, V]
        q_m = jnp.take_along_axis(
            qs_pad, m[:, None, None], axis=1)[:, 0, :]
        res = jnp.maximum(p_m - q_m, 0.0)
        res_sum = jnp.sum(res, axis=-1, keepdims=True)
        safe = jnp.where(res_sum > 0, res, p_m)
        next_tok = jax.random.categorical(
            kr, jnp.log(safe + 1e-30)).astype(t_last.dtype)

        row = jnp.concatenate(
            [drafts, jnp.zeros((b, 1), drafts.dtype)], axis=1)
        row = row.at[jnp.arange(b), m].set(next_tok)
        out = jax.vmap(
            lambda o, r, off: jax.lax.dynamic_update_slice(o, r, (off,))
        )(out, row, prompt_len + g)

        g = g + m + 1
        t_cache = _rewind_cache_index(t_cache, ctx_len + g - 1)
        d_cache = _rewind_cache_index(d_cache, ctx_len + g - 1)
        stats = {
            "rounds": stats["rounds"] + 1,
            "drafted": stats["drafted"] + jnp.where(active, k, 0),
            "accepted": stats["accepted"] + jnp.where(active, m, 0),
        }
        return t_cache, d_cache, out, g, next_tok, stats, rkey

    _, _, out, _, _, stats, _ = jax.lax.while_loop(
        cond, body, (t_cache, d_cache, out, g0, tok0, stats0, rng)
    )
    return out[:, : plen + max_new_tokens], stats
