"""Greedy speculative decoding: draft k cheap tokens, verify them in
ONE target forward.

Plain decode is HBM-bound: every generated token re-reads all target
params and cache (the roofline bench.py's decode workload measures).
Speculative decoding converts k of those sequential reads into one
MXU-dense (k+1)-token verify chunk — the chunk re-reads params ONCE for
k+1 positions, so accepted drafts cost ~1/k of the bandwidth.  This is
the serving-side counterpart of prefill's batching (generate.py phase
1), applied to the decode phase.

The invariant that makes it testable: with greedy acceptance the output
is EXACTLY the target model's own greedy continuation — the draft can
only change the speed, never a token.  Concretely, each round:

1. draft autoregressively proposes ``d_1..d_k`` (k+1 single-token
   steps — the extra step keeps the draft's own cache complete when
   all k are accepted);
2. the target runs ONE forward over ``[t_last, d_1..d_k]`` at
   positions ``p0..p0+k`` (the same chunked-continuation the batched
   prefill uses, so it hits the MXU);
3. the longest prefix of drafts matching the target's argmax at each
   position is accepted, plus the target's own token at the first
   divergence (or the bonus token when everything matched): ``m+1``
   tokens per round for ``m`` accepted drafts;
4. both caches' write cursors rewind to the new head position — stale
   slots beyond the cursor are dead, exactly like bucket-padding slots
   (generate.py's ``_rewind_cache_index`` semantics): the visibility
   mask hides them and in-order writes overwrite them.

Everything is static-shape: the round is a ``lax.while_loop`` whose
body runs a fixed k+1-step draft scan and one fixed (k+1)-token verify,
so the whole generation jits once per (prompt bucket, max_new, k).

The reference has no model runtime; within this framework the
counterpart contracts are generate.py (greedy == iterated train argmax)
and batching.py (fleet == per-request) — this module extends that
exactness chain to the draft/verify composition.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from container_engine_accelerators_tpu.models.generate import (
    _rewind_cache_index,
    init_cache,
    prefill,
    prefill_continue,
    prefix_bucket_len,
    splice_prefix,
)


def generate_speculative(
    model,
    params,
    draft_model,
    draft_params,
    prompt: jax.Array,
    max_new_tokens: int,
    k: int = 4,
    prompt_len=None,
    prefix=None,
):
    """Greedy-decode ``max_new_tokens`` past ``prompt`` [B, P] with
    k-token speculation -> (tokens [B, P+N], stats).

    Both models must be built with ``decode=True`` and share the
    vocabulary.  ``prompt_len`` has generate()'s bucket-padding
    semantics (may be traced).  ``stats`` is a dict of arrays:
    ``rounds`` (scalar), ``drafted``/``accepted`` ([B], counted only
    while the sample was still generating) — acceptance rate =
    accepted/drafted is the lever that decides the realized speedup.

    Output layout matches generate(): positions [prompt_len,
    prompt_len + max_new_tokens) hold the generated tokens, and they
    equal the target model's own greedy continuation token-for-token.

    ``prefix`` is the prefix-cache composition:
    ``(target_kv, draft_kv, prefix_len)`` — each model's OWN prefilled
    block for the shared system prompt (a PrefixCache per model;
    serve_lm holds both).  ``prompt`` then carries only the suffix and
    the output layout stays suffix-local, exactly like
    :func:`~.prefix_cache.generate_with_prefix`.
    """
    if not (model.decode and draft_model.decode):
        raise ValueError(
            "generate_speculative() needs decode=True models")
    if k < 1:
        raise ValueError("k must be >= 1")
    b, plen = prompt.shape
    if prompt_len is None:
        prompt_len = plen
    prompt_len = jnp.asarray(prompt_len, jnp.int32)

    if prefix is None:
        prefix_len = jnp.zeros((), jnp.int32)
        t_pfx_bucket = d_pfx_bucket = 0
    else:
        t_kv, d_kv, prefix_len = prefix
        prefix_len = jnp.asarray(prefix_len, jnp.int32)
        t_pfx_bucket = prefix_bucket_len(t_kv)
        d_pfx_bucket = prefix_bucket_len(d_kv)
    # ctx_len = global depth of the last real prompt token + 1: cache
    # positions are ctx-global, while the output buffer stays
    # suffix-local (prompt_len-indexed).
    ctx_len = prefix_len + prompt_len

    # Margin: the final round can overshoot by up to k extra tokens,
    # and finished samples keep clamp-writing into the tail margin
    # while stragglers catch up.
    margin = plen + max_new_tokens + k + 1

    if prefix is None:
        t_cache, t_last_logits = prefill(
            model, params, prompt, prompt_len, margin)
        d_cache, _ = prefill(
            draft_model, draft_params, prompt, prompt_len, margin)
    else:
        t_cache = init_cache(model, b, t_pfx_bucket + margin)
        t_cache = splice_prefix(t_cache, t_kv, prefix_len, b)
        t_cache, t_last_logits = prefill_continue(
            model, params, t_cache, prompt, prefix_len, ctx_len)
        d_cache = init_cache(draft_model, b, d_pfx_bucket + margin)
        d_cache = splice_prefix(d_cache, d_kv, prefix_len, b)
        d_cache, _ = prefill_continue(
            draft_model, draft_params, d_cache, prompt, prefix_len,
            ctx_len)

    tok0 = jnp.argmax(t_last_logits, axis=-1).astype(prompt.dtype)
    out = jnp.concatenate(
        [prompt, jnp.zeros((b, max_new_tokens + k + 1), prompt.dtype)],
        axis=1,
    )
    out = jax.lax.dynamic_update_slice(out, tok0[:, None], (0, prompt_len))

    g0 = jnp.ones((b,), jnp.int32)  # tok0 already emitted
    stats0 = {
        "rounds": jnp.zeros((), jnp.int32),
        "drafted": jnp.zeros((b,), jnp.int32),
        "accepted": jnp.zeros((b,), jnp.int32),
    }

    def cond(carry):
        _, _, _, g, _, _ = carry
        return jnp.min(g) < max_new_tokens

    def body(carry):
        t_cache, d_cache, out, g, t_last, stats = carry
        active = g < max_new_tokens
        p0 = ctx_len + g - 1  # [B] global position of t_last

        # Draft phase: k+1 single-token steps (feed t_last, then each
        # proposal; the last feed only completes the draft cache).
        def dstep(c, _):
            d_cache, tok, pos = c
            logits, mut = draft_model.apply(
                {"params": draft_params, "cache": d_cache},
                tok[:, None],
                positions=pos[:, None],
                mutable=["cache"],
            )
            nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(tok.dtype)
            return (mut["cache"], nxt, pos + 1), nxt

        (d_cache, _, _), drafts = jax.lax.scan(
            dstep, (d_cache, t_last, p0), None, length=k + 1
        )
        drafts = drafts.transpose(1, 0)[:, :k]  # [B, k]: d_1..d_k

        # Verify phase: ONE chunked target forward.
        chunk = jnp.concatenate([t_last[:, None], drafts], axis=1)
        pos_chunk = p0[:, None] + jnp.arange(k + 1, dtype=jnp.int32)[None]
        logits, mut = model.apply(
            {"params": params, "cache": t_cache},
            chunk,
            positions=pos_chunk,
            mutable=["cache"],
        )
        t_cache = mut["cache"]
        tgt_choice = jnp.argmax(logits, axis=-1).astype(t_last.dtype)

        # m = longest matching prefix; emit d_1..d_m + target's token.
        matches = (drafts == tgt_choice[:, :k]).astype(jnp.int32)
        m = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)  # [B]
        next_tok = jnp.take_along_axis(
            tgt_choice, m[:, None], axis=1)[:, 0]
        row = jnp.concatenate(
            [drafts, jnp.zeros((b, 1), drafts.dtype)], axis=1)
        row = row.at[jnp.arange(b), m].set(next_tok)

        out = jax.vmap(
            lambda o, r, off: jax.lax.dynamic_update_slice(o, r, (off,))
        )(out, row, prompt_len + g)

        g = g + m + 1
        t_cache = _rewind_cache_index(t_cache, ctx_len + g - 1)
        d_cache = _rewind_cache_index(d_cache, ctx_len + g - 1)
        stats = {
            "rounds": stats["rounds"] + 1,
            "drafted": stats["drafted"] + jnp.where(active, k, 0),
            "accepted": stats["accepted"] + jnp.where(active, m, 0),
        }
        return t_cache, d_cache, out, g, next_tok, stats

    _, _, out, _, _, stats = jax.lax.while_loop(
        cond, body, (t_cache, d_cache, out, g0, tok0, stats0)
    )
    return out[:, : plen + max_new_tokens], stats
