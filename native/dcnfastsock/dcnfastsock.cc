// libdcnfastsock.so — DCN TCP transport tuning layer.
//
// TPU-native analog of the reference's NCCL fast-socket plugin
// (ref: fast-socket-installer/image/Dockerfile:6-7, consumed as a
// prebuilt libnccl-net.so).  NCCL loads its transport plugin through a
// plugin ABI; JAX/XLA's DCN path uses plain sockets, so the idiomatic
// delivery here is an LD_PRELOAD interposer that applies the same class
// of tuning the fast-socket plugin applied inside NCCL:
//
//   * large SO_SNDBUF/SO_RCVBUF (DCN has a high bandwidth-delay product)
//   * TCP_NODELAY (latency-sensitive control traffic)
//   * optional SO_ZEROCOPY and SO_BUSY_POLL
//
// Tunables via env, all optional:
//   DCN_FASTSOCK_SNDBUF / DCN_FASTSOCK_RCVBUF  (bytes, default 64 MiB)
//   DCN_FASTSOCK_BUSY_POLL                     (µs, default off)
//   DCN_FASTSOCK_ZEROCOPY=1                    (default off)
//   DCN_FASTSOCK_VERBOSE=1                     (log each tuned socket)
//
// Only AF_INET/AF_INET6 SOCK_STREAM sockets are touched; unix sockets
// (kubelet gRPC, dcnxferd control) pass through untouched.

#include <dlfcn.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#ifndef SO_ZEROCOPY
#define SO_ZEROCOPY 60
#endif
#ifndef SO_BUSY_POLL
#define SO_BUSY_POLL 46
#endif

namespace {

using socket_fn = int (*)(int, int, int);

long env_long(const char* name, long fallback) {
  const char* v = getenv(name);
  if (!v || !*v) return fallback;
  char* end = nullptr;
  long out = strtol(v, &end, 10);
  return (end && *end == '\0') ? out : fallback;
}

bool verbose() { return env_long("DCN_FASTSOCK_VERBOSE", 0) != 0; }

void tune(int fd, int domain, int type) {
  if (domain != AF_INET && domain != AF_INET6) return;
  if ((type & 0xff) != SOCK_STREAM) return;

  long sndbuf = env_long("DCN_FASTSOCK_SNDBUF", 64L << 20);
  long rcvbuf = env_long("DCN_FASTSOCK_RCVBUF", 64L << 20);
  long busy_poll = env_long("DCN_FASTSOCK_BUSY_POLL", 0);
  long zerocopy = env_long("DCN_FASTSOCK_ZEROCOPY", 0);

  int one = 1;
  if (sndbuf > 0) {
    int v = static_cast<int>(sndbuf);
    setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &v, sizeof(v));
  }
  if (rcvbuf > 0) {
    int v = static_cast<int>(rcvbuf);
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &v, sizeof(v));
  }
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  if (busy_poll > 0) {
    int v = static_cast<int>(busy_poll);
    setsockopt(fd, SOL_SOCKET, SO_BUSY_POLL, &v, sizeof(v));
  }
  if (zerocopy) {
    setsockopt(fd, SOL_SOCKET, SO_ZEROCOPY, &one, sizeof(one));
  }
  if (verbose()) {
    fprintf(stderr,
            "[dcnfastsock] tuned fd=%d sndbuf=%ld rcvbuf=%ld busy_poll=%ld "
            "zerocopy=%ld\n",
            fd, sndbuf, rcvbuf, busy_poll, zerocopy);
  }
}

}  // namespace

extern "C" int socket(int domain, int type, int protocol) {
  static socket_fn real = reinterpret_cast<socket_fn>(
      dlsym(RTLD_NEXT, "socket"));
  if (!real) {
    errno = ENOSYS;
    return -1;
  }
  int fd = real(domain, type, protocol);
  if (fd >= 0) tune(fd, domain, type);
  return fd;
}

// accept()ed sockets inherit buffer sizes from the listener on Linux,
// but TCP_NODELAY does not propagate from all paths — interpose both.
extern "C" int accept4(int sockfd, struct sockaddr* addr, socklen_t* addrlen,
                       int flags) {
  using accept4_fn =
      int (*)(int, struct sockaddr*, socklen_t*, int);
  static accept4_fn real = reinterpret_cast<accept4_fn>(
      dlsym(RTLD_NEXT, "accept4"));
  if (!real) {
    errno = ENOSYS;
    return -1;
  }
  int fd = real(sockfd, addr, addrlen, flags);
  if (fd >= 0) {
    struct sockaddr_storage ss;
    socklen_t slen = sizeof(ss);
    if (getsockname(fd, reinterpret_cast<struct sockaddr*>(&ss), &slen) == 0) {
      tune(fd, ss.ss_family, SOCK_STREAM);
    }
  }
  return fd;
}
