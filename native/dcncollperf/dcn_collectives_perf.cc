// dcn_collectives_perf — native DCN collective-bandwidth benchmark.
//
// TPU-native analog of nccl-tests' all_gather_perf/all_reduce_perf (SURVEY.md
// §2.2; ref: gpudirect-tcpxo/nccl-test.yaml:62 runs `all_gather_perf` via MPI;
// gpudirect-tcpx/nccl-config.yaml:60-63 sweeps 1M→512M, ×2/step, 100 iters,
// warmup 5, -c 0).  In-slice collectives ride ICI through XLA and are
// benchmarked by the JAX sweep (container_engine_accelerators_tpu/collectives/
// bench.py); this binary benchmarks the *DCN* path — the cross-slice fabric
// the reference drives with NCCL+TCPX — with a ring algorithm over TCP
// sockets, so `LD_PRELOAD=libdcnfastsock.so` tuning applies to it exactly the
// way the fast-socket plugin applies to nccl-tests.
//
// CLI (nccl-tests semantics):
//   dcn_collectives_perf --op all_reduce|all_gather
//     --rank R --hosts h0:p0,h1:p1,...   (rank r binds hosts[r], ring order)
//     [-b 1M] [-e 512M] [-f 2] [-n 100] [-w 5] [-c 0|1]
//
// Ring wiring: rank r accepts one connection from rank r-1 and connects to
// rank r+1 (mod N) with retry, so start order doesn't matter.  All ranks
// print the nccl-tests-style table (size, count, time, algbw, busbw, #wrong);
// rank 0 also prints one machine-readable JSON summary line at the end (the
// shape the xla-collectives rigs emit for the driver).
//
// Bus-bandwidth factors match nccl-tests' definitions:
//   all_reduce: busbw = algbw * 2*(N-1)/N      (size = per-rank buffer)
//   all_gather: busbw = algbw * (N-1)/N        (size = total output buffer)
//
// Build: make native  (g++ -std=c++17, no external deps).

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <signal.h>
#include <stdarg.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <string>
#include <vector>

namespace {

double NowSec() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

[[noreturn]] void Die(const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  fprintf(stderr, "dcn_collectives_perf: ");
  vfprintf(stderr, fmt, ap);
  fprintf(stderr, "\n");
  va_end(ap);
  exit(1);
}

// ---- size parsing (nccl-tests accepts 1M / 512M / 1G) ----------------------

int64_t ParseBytes(const char* s) {
  char* end = nullptr;
  double v = strtod(s, &end);
  if (end == s) Die("bad size %s", s);
  switch (*end) {
    case 'G': case 'g': v *= 1 << 30; break;
    case 'M': case 'm': v *= 1 << 20; break;
    case 'K': case 'k': v *= 1 << 10; break;
    case '\0': break;
    default: Die("bad size suffix in %s", s);
  }
  return static_cast<int64_t>(v);
}

// ---- ring wiring -----------------------------------------------------------

struct HostPort {
  std::string host;
  int port;
};

std::vector<HostPort> ParseHosts(const std::string& arg) {
  std::vector<HostPort> out;
  size_t pos = 0;
  while (pos < arg.size()) {
    size_t comma = arg.find(',', pos);
    if (comma == std::string::npos) comma = arg.size();
    std::string item = arg.substr(pos, comma - pos);
    size_t colon = item.rfind(':');
    if (colon == std::string::npos) Die("bad host:port %s", item.c_str());
    out.push_back({item.substr(0, colon), atoi(item.c_str() + colon + 1)});
    pos = comma + 1;
  }
  return out;
}

void SetSockOpts(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

int ListenOn(const HostPort& hp) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) Die("socket: %s", strerror(errno));
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(hp.port));
  addr.sin_addr.s_addr = INADDR_ANY;  // bind all: pod IP vs localhost
  if (bind(fd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)) < 0)
    Die("bind %d: %s", hp.port, strerror(errno));
  if (listen(fd, 1) < 0) Die("listen: %s", strerror(errno));
  return fd;
}

int ConnectTo(const HostPort& hp, double timeout_sec) {
  double deadline = NowSec() + timeout_sec;
  for (;;) {
    struct addrinfo hints, *res = nullptr;
    memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    char portstr[16];
    snprintf(portstr, sizeof(portstr), "%d", hp.port);
    if (getaddrinfo(hp.host.c_str(), portstr, &hints, &res) == 0 && res) {
      int fd = socket(AF_INET, SOCK_STREAM, 0);
      if (fd >= 0 &&
          connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
        freeaddrinfo(res);
        SetSockOpts(fd);
        return fd;
      }
      if (fd >= 0) close(fd);
    }
    if (res) freeaddrinfo(res);
    if (NowSec() > deadline)
      Die("connect %s:%d timed out", hp.host.c_str(), hp.port);
    usleep(100 * 1000);
  }
}

// ---- ring handshake --------------------------------------------------------
// The listener binds INADDR_ANY, so the first inbound connection could be a
// port scanner or a misconfigured peer; silently wiring it in as prev-rank
// would corrupt the benchmark/data check.  Each rank therefore sends a
// magic+rank header right after connect, and the accept side keeps accepting
// until it sees the expected prev rank.

constexpr uint32_t kHelloMagic = 0x44434e43;  // "DCNC"

struct Hello {
  uint32_t magic;
  int32_t rank;
};

bool ReadFullTimeout(int fd, char* buf, size_t len, double timeout_sec) {
  if (timeout_sec <= 0) return false;
  struct timeval tv;
  tv.tv_sec = static_cast<time_t>(timeout_sec);
  tv.tv_usec =
      static_cast<suseconds_t>((timeout_sec - tv.tv_sec) * 1e6);
  if (tv.tv_sec == 0 && tv.tv_usec == 0) tv.tv_usec = 1;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  size_t got = 0;
  while (got < len) {
    ssize_t k = recv(fd, buf + got, len - got, 0);
    if (k <= 0) return false;  // EOF, timeout, or error: reject the peer
    got += static_cast<size_t>(k);
  }
  return true;
}

void SendHello(int fd, int rank) {
  Hello h{kHelloMagic, rank};
  const char* p = reinterpret_cast<const char*>(&h);
  size_t sent = 0;
  while (sent < sizeof(h)) {
    ssize_t k = send(fd, p + sent, sizeof(h) - sent, MSG_NOSIGNAL);
    if (k < 0) Die("handshake send: %s", strerror(errno));
    sent += static_cast<size_t>(k);
  }
}

// Accept until the peer proves it is `want_rank` via the Hello header.
// The listener is polled with a timeout so the deadline also fires when
// no peer ever connects (a blocking accept would hang forever).
int AcceptRank(int lfd, int want_rank, double deadline) {
  for (;;) {
    double remain = deadline - NowSec();
    if (remain <= 0) Die("timed out waiting for prev-rank hello");
    struct pollfd pfd = {lfd, POLLIN, 0};
    int ready = poll(&pfd, 1, static_cast<int>(remain * 1000) + 1);
    if (ready < 0) {
      if (errno == EINTR) continue;
      Die("poll(listen): %s", strerror(errno));
    }
    if (ready == 0) continue;  // deadline re-checked at loop top
    int fd = accept(lfd, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      Die("accept: %s", strerror(errno));
    }
    Hello h{};
    // Clamp the per-connection handshake budget to the remaining overall
    // deadline so slow-dripping strays can't starve the real peer.
    double hs = std::min(5.0, deadline - NowSec());
    if (ReadFullTimeout(fd, reinterpret_cast<char*>(&h), sizeof(h), hs) &&
        h.magic == kHelloMagic && h.rank == want_rank) {
      SetSockOpts(fd);
      return fd;
    }
    fprintf(stderr,
            "dcn_collectives_perf: rejecting stray connection "
            "(magic=0x%x rank=%d, want rank %d)\n",
            h.magic, h.rank, want_rank);
    close(fd);
  }
}

// ---- full-duplex progress engine -------------------------------------------
// Every ring step sends one chunk to next while receiving one from prev.  A
// blocking send of a chunk larger than the socket buffer would deadlock the
// ring (all ranks blocked in send(), nobody draining), so both directions are
// progressed from one poll() loop over nonblocking sockets.

void SetNonBlocking(int fd, bool on) {
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, on ? (flags | O_NONBLOCK) : (flags & ~O_NONBLOCK));
}

void SendRecv(int send_fd, const char* send_buf, size_t send_len,
              int recv_fd, char* recv_buf, size_t recv_len) {
  size_t sent = 0, rcvd = 0;
  int stalls = 0;
  while (sent < send_len || rcvd < recv_len) {
    struct pollfd pfds[2];
    int n = 0;
    int send_idx = -1, recv_idx = -1;
    if (sent < send_len) {
      pfds[n] = {send_fd, POLLOUT, 0};
      send_idx = n++;
    }
    if (rcvd < recv_len) {
      pfds[n] = {recv_fd, POLLIN, 0};
      recv_idx = n++;
    }
    int ready = poll(pfds, n, 10000);
    if (ready < 0) {
      if (errno == EINTR) continue;
      Die("poll: %s", strerror(errno));
    }
    if (ready == 0) {
      // A stalled peer (partition without RST, paused pod) must fail the
      // benchmark, not wedge the Job forever.
      if (++stalls >= 6) Die("peer stalled for 60s mid-collective");
      continue;
    }
    stalls = 0;
    if (send_idx >= 0 && (pfds[send_idx].revents & (POLLOUT | POLLERR))) {
      ssize_t k = send(send_fd, send_buf + sent, send_len - sent,
                       MSG_NOSIGNAL);
      if (k < 0 && errno != EAGAIN && errno != EWOULDBLOCK)
        Die("send: %s", strerror(errno));
      if (k > 0) sent += static_cast<size_t>(k);
    }
    if (recv_idx >= 0 &&
        (pfds[recv_idx].revents & (POLLIN | POLLERR | POLLHUP))) {
      ssize_t k = recv(recv_fd, recv_buf + rcvd, recv_len - rcvd, 0);
      if (k == 0) Die("peer closed mid-collective");
      if (k < 0 && errno != EAGAIN && errno != EWOULDBLOCK)
        Die("recv: %s", strerror(errno));
      if (k > 0) rcvd += static_cast<size_t>(k);
    }
  }
}

// ---- collectives -----------------------------------------------------------
// Chunk layout: the element buffer is split into nranks equal chunks (counts
// padded so nelem % nranks == 0 is guaranteed by the sweep generator).

struct Ring {
  int rank = 0;
  int nranks = 0;
  int next_fd = -1;  // we send to rank+1
  int prev_fd = -1;  // we receive from rank-1
};

// Ring all-reduce (sum, float32): N-1 reduce-scatter steps then N-1
// all-gather steps.  data holds nelem floats in place.
void RingAllReduce(const Ring& ring, float* data, size_t nelem,
                   std::vector<float>* scratch) {
  int n = ring.nranks;
  size_t chunk = nelem / n;
  scratch->resize(chunk);
  // Reduce-scatter: in step s, send chunk (rank - s) and receive + accumulate
  // chunk (rank - s - 1).
  for (int s = 0; s < n - 1; ++s) {
    int send_c = ((ring.rank - s) % n + n) % n;
    int recv_c = ((ring.rank - s - 1) % n + n) % n;
    SendRecv(ring.next_fd,
             reinterpret_cast<const char*>(data + send_c * chunk),
             chunk * sizeof(float), ring.prev_fd,
             reinterpret_cast<char*>(scratch->data()),
             chunk * sizeof(float));
    float* dst = data + recv_c * chunk;
    const float* src = scratch->data();
    for (size_t i = 0; i < chunk; ++i) dst[i] += src[i];
  }
  // All-gather the reduced chunks: in step s, send chunk (rank + 1 - s) and
  // receive chunk (rank - s).
  for (int s = 0; s < n - 1; ++s) {
    int send_c = ((ring.rank + 1 - s) % n + n) % n;
    int recv_c = ((ring.rank - s) % n + n) % n;
    SendRecv(ring.next_fd,
             reinterpret_cast<const char*>(data + send_c * chunk),
             chunk * sizeof(float), ring.prev_fd,
             reinterpret_cast<char*>(data + recv_c * chunk),
             chunk * sizeof(float));
  }
}

// Ring all-gather: rank r owns chunk r on entry; N-1 forwarding steps.
void RingAllGather(const Ring& ring, float* data, size_t nelem) {
  int n = ring.nranks;
  size_t chunk = nelem / n;
  for (int s = 0; s < n - 1; ++s) {
    int send_c = ((ring.rank - s) % n + n) % n;
    int recv_c = ((ring.rank - s - 1) % n + n) % n;
    SendRecv(ring.next_fd,
             reinterpret_cast<const char*>(data + send_c * chunk),
             chunk * sizeof(float), ring.prev_fd,
             reinterpret_cast<char*>(data + recv_c * chunk),
             chunk * sizeof(float));
  }
}

// Any-payload barrier so timing starts aligned: one-byte token around the
// ring twice (the second lap guarantees everyone saw the first).
void RingBarrier(const Ring& ring) {
  char t = 0;
  for (int lap = 0; lap < 2; ++lap)
    SendRecv(ring.next_fd, &t, 1, ring.prev_fd, &t, 1);
}

float Pattern(int rank, size_t i) {
  // Small integers: float32 summation over ranks stays exact.
  return static_cast<float>((rank + 1) * ((i % 97) + 1) % 1013);
}

}  // namespace

int main(int argc, char** argv) {
  std::string op = "all_reduce";
  int64_t minbytes = 1 << 20, maxbytes = 512 << 20;
  int factor = 2, iters = 100, warmup = 5, check = 0;
  int rank = -1;
  std::string hosts_arg;
  double connect_timeout = 60.0;

  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> const char* {
      if (i + 1 >= argc) Die("missing value for %s", a.c_str());
      return argv[++i];
    };
    if (a == "--op") op = next();
    else if (a == "-b" || a == "--minbytes") minbytes = ParseBytes(next());
    else if (a == "-e" || a == "--maxbytes") maxbytes = ParseBytes(next());
    else if (a == "-f" || a == "--stepfactor") factor = atoi(next());
    else if (a == "-n" || a == "--iters") iters = atoi(next());
    else if (a == "-w" || a == "--warmup_iters") warmup = atoi(next());
    else if (a == "-c" || a == "--check") check = atoi(next());
    else if (a == "--rank") rank = atoi(next());
    else if (a == "--hosts") hosts_arg = next();
    else if (a == "--connect_timeout") connect_timeout = atof(next());
    else Die("unknown flag %s", a.c_str());
  }
  if (op != "all_reduce" && op != "all_gather")
    Die("--op must be all_reduce or all_gather");
  if (rank < 0 || hosts_arg.empty()) Die("--rank and --hosts are required");
  std::vector<HostPort> hosts = ParseHosts(hosts_arg);
  int nranks = static_cast<int>(hosts.size());
  if (nranks < 2) Die("need >= 2 ranks");
  if (rank >= nranks) Die("--rank %d out of range", rank);
  if (minbytes <= 0 || maxbytes <= 0 || minbytes > maxbytes)
    Die("need 0 < minbytes <= maxbytes (got -b %ld -e %ld)",
        static_cast<long>(minbytes), static_cast<long>(maxbytes));
  if (iters < 1 || warmup < 0) Die("need -n >= 1 and -w >= 0");
  if (factor < 2) factor = 2;

  signal(SIGPIPE, SIG_IGN);

  // Wire the ring: listen first, then connect to next with retry, then
  // accept from prev — no start-order requirement.
  Ring ring;
  ring.rank = rank;
  ring.nranks = nranks;
  int lfd = ListenOn(hosts[rank]);
  ring.next_fd = ConnectTo(hosts[(rank + 1) % nranks], connect_timeout);
  SendHello(ring.next_fd, rank);
  ring.prev_fd = AcceptRank(lfd, (rank + nranks - 1) % nranks,
                            NowSec() + connect_timeout);
  close(lfd);
  SetNonBlocking(ring.next_fd, true);
  SetNonBlocking(ring.prev_fd, true);

  if (rank == 0) {
    printf("# dcn_collectives_perf op=%s nranks=%d minbytes=%ld "
           "maxbytes=%ld factor=%d iters=%d warmup=%d check=%d\n",
           op.c_str(), nranks, static_cast<long>(minbytes),
           static_cast<long>(maxbytes), factor, iters, warmup, check);
    printf("# %12s %12s %8s %12s %10s %10s %8s\n", "size(B)", "count",
           "type", "time(us)", "algbw(GB/s)", "busbw(GB/s)", "#wrong");
  }

  double max_busbw = 0, sum_busbw = 0;
  int rows = 0;
  std::vector<float> scratch;
  for (int64_t size = minbytes; size <= maxbytes; size *= factor) {
    // nelem divisible by nranks so chunks are equal (nccl-tests rounds the
    // same way); "size" follows nccl-tests conventions per op.
    size_t nelem =
        (static_cast<size_t>(size) / sizeof(float) / nranks) * nranks;
    if (nelem == 0) continue;
    std::vector<float> data(nelem);
    size_t chunk = nelem / nranks;

    auto reset = [&]() {
      if (op == "all_reduce") {
        for (size_t i = 0; i < nelem; ++i) data[i] = Pattern(rank, i);
      } else {
        // all-gather input: only our chunk is defined.
        for (size_t i = 0; i < chunk; ++i)
          data[rank * chunk + i] = Pattern(rank, i);
      }
    };
    auto run_once = [&]() {
      if (op == "all_reduce")
        RingAllReduce(ring, data.data(), nelem, &scratch);
      else
        RingAllGather(ring, data.data(), nelem);
    };

    long wrong = -1;
    if (check) {
      reset();
      run_once();
      wrong = 0;
      if (op == "all_reduce") {
        for (size_t i = 0; i < nelem; ++i) {
          float want = 0;
          for (int r = 0; r < nranks; ++r) want += Pattern(r, i);
          if (data[i] != want) ++wrong;
        }
      } else {
        for (int r = 0; r < nranks; ++r)
          for (size_t i = 0; i < chunk; ++i)
            if (data[r * chunk + i] != Pattern(r, i)) ++wrong;
      }
    }

    reset();
    for (int it = 0; it < warmup; ++it) run_once();
    RingBarrier(ring);
    double t0 = NowSec();
    for (int it = 0; it < iters; ++it) run_once();
    RingBarrier(ring);
    double dt = (NowSec() - t0) / iters;

    double bytes = static_cast<double>(nelem) * sizeof(float);
    double algbw = bytes / dt / 1e9;
    double busbw = op == "all_reduce"
                       ? algbw * 2.0 * (nranks - 1) / nranks
                       : algbw * (nranks - 1) / nranks;
    max_busbw = std::max(max_busbw, busbw);
    sum_busbw += busbw;
    ++rows;
    if (rank == 0) {
      char wrongs[24];
      if (wrong < 0) snprintf(wrongs, sizeof(wrongs), "N/A");
      else snprintf(wrongs, sizeof(wrongs), "%ld", wrong);
      printf("  %12zu %12zu %8s %12.1f %10.3f %10.3f %8s\n",
             static_cast<size_t>(bytes), nelem, "float", dt * 1e6, algbw,
             busbw, wrongs);
      fflush(stdout);
    }
    if (wrong > 0) Die("data check failed: %ld wrong elements", wrong);
  }

  if (rank == 0 && rows > 0) {
    printf("{\"metric\": \"dcn_%s_busbw_gbps\", \"value\": %.3f, "
           "\"unit\": \"GB/s\", \"nranks\": %d, \"avg_busbw_gbps\": %.3f}\n",
           op.c_str(), max_busbw, nranks, sum_busbw / rows);
  }
  close(ring.next_fd);
  close(ring.prev_fd);
  return 0;
}
