/* tpushim — native TPU node library (the NVML-analog).
 *
 * The reference consumes NVML as its native device library: enumeration,
 * memory/utilization sampling, and the Xid event stream (SURVEY.md §2.2;
 * ref: pkg/gpu/nvidia/metrics/util.go:17-73 links NVML via cgo).  TPU nodes
 * expose the same information as a filesystem contract (documented in
 * container_engine_accelerators_tpu/tpulib/__init__.py); this library is
 * the C++ implementation of that contract with an inotify-driven event
 * loop, consumed from Python via ctypes (no pybind11 in the image).
 *
 * All functions return 0 on success, negative errno-style codes on error.
 */
#ifndef TPUSHIM_H_
#define TPUSHIM_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define TPUSHIM_NAME_LEN 32
#define TPUSHIM_ADDR_LEN 32
#define TPUSHIM_MSG_LEN 256
#define TPUSHIM_HEALTH_LEN 64

typedef struct tpu_ctx tpu_ctx;

typedef struct {
  char name[TPUSHIM_NAME_LEN]; /* "accelN" */
  int32_t index;
  int32_t chip_id;
  char pci_addr[TPUSHIM_ADDR_LEN];
  int32_t coords[3];   /* ICI mesh coordinates */
  int32_t topology[3]; /* host-local mesh bounds */
} tpu_chip_info_t;

typedef struct {
  int32_t code;
  /* device[0] == '\0' means "no device attribution" (whole node). */
  char device[TPUSHIM_NAME_LEN];
  char message[TPUSHIM_MSG_LEN];
} tpu_event_t;

/* Open a context rooted at `root` ("/" on a real node; a fixture dir in
 * tests).  Returns NULL on allocation failure only — a root with no chips
 * is valid (chip_count() == 0). */
tpu_ctx* tpu_open(const char* root);
void tpu_close(tpu_ctx* ctx);

int tpu_chip_count(tpu_ctx* ctx);
int tpu_chip_info(tpu_ctx* ctx, int index, tpu_chip_info_t* out);
/* Fill up to max_n entries from ONE directory scan; returns the number
 * filled (the snapshot is consistent, unlike per-index queries racing
 * hotplug). */
int tpu_chip_info_all(tpu_ctx* ctx, tpu_chip_info_t* out, int max_n);
int tpu_hbm_info(tpu_ctx* ctx, const char* name, int64_t* total_bytes,
                 int64_t* used_bytes);
/* Returns duty cycle 0-100, or negative on error. */
int tpu_duty_cycle(tpu_ctx* ctx, const char* name);
int tpu_health(tpu_ctx* ctx, const char* name, char* buf, int buf_len);

/* Block up to timeout_ms for the next error event from
 * <root>/var/run/tpu/events (inotify; consumed files are unlinked).
 * Returns 1 with *out filled, 0 on timeout, negative on error. */
int tpu_wait_for_event(tpu_ctx* ctx, int timeout_ms, tpu_event_t* out);

const char* tpushim_version(void);

#ifdef __cplusplus
}
#endif

#endif /* TPUSHIM_H_ */
