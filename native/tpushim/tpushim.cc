// tpushim implementation — see tpushim.h for the contract.

#include "tpushim.h"

#include <dirent.h>
#include <errno.h>
#include <limits.h>
#include <poll.h>
#include <stdio.h>
#include <string.h>
#include <sys/inotify.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <cstdlib>
#include <fstream>
#include <sstream>
#include <string>
#include <vector>

namespace {

bool is_accel_name(const std::string& n, int* index) {
  if (n.rfind("accel", 0) != 0 || n.size() <= 5) return false;
  for (size_t i = 5; i < n.size(); i++) {
    if (n[i] < '0' || n[i] > '9') return false;
  }
  *index = std::atoi(n.c_str() + 5);
  return true;
}

bool read_file(const std::string& path, std::string* out) {
  std::ifstream f(path);
  if (!f) return false;
  std::stringstream ss;
  ss << f.rdbuf();
  *out = ss.str();
  while (!out->empty() && (out->back() == '\n' || out->back() == '\r' ||
                           out->back() == ' '))
    out->pop_back();
  return true;
}

void parse_triple(const std::string& raw, char sep, int32_t out[3]) {
  out[0] = out[1] = out[2] = 1;
  std::stringstream ss(raw);
  std::string part;
  for (int i = 0; i < 3 && std::getline(ss, part, sep); i++) {
    out[i] = std::atoi(part.c_str());
  }
}

// Minimal parser for the flat event JSON our node components write:
//   {"code": <int>, "device": "accelN"|null, "message": "<str>"}
// Strict on shape, tolerant of key order and whitespace.  Unknown keys are
// skipped.  Returns false on anything structurally unexpected.
struct EventJson {
  long code = -1;
  std::string device;  // empty = null / absent
  std::string message;
};

void skip_ws(const std::string& s, size_t* i) {
  while (*i < s.size() && (s[*i] == ' ' || s[*i] == '\t' || s[*i] == '\n' ||
                           s[*i] == '\r'))
    (*i)++;
}

bool parse_json_string(const std::string& s, size_t* i, std::string* out) {
  if (*i >= s.size() || s[*i] != '"') return false;
  (*i)++;
  out->clear();
  while (*i < s.size()) {
    char c = s[*i];
    if (c == '"') {
      (*i)++;
      return true;
    }
    if (c == '\\') {
      (*i)++;
      if (*i >= s.size()) return false;
      char e = s[*i];
      switch (e) {
        case 'n': out->push_back('\n'); break;
        case 't': out->push_back('\t'); break;
        case 'r': out->push_back('\r'); break;
        case 'b': out->push_back('\b'); break;
        case 'f': out->push_back('\f'); break;
        case '"': out->push_back('"'); break;
        case '\\': out->push_back('\\'); break;
        case '/': out->push_back('/'); break;
        case 'u': {
          if (*i + 4 >= s.size()) return false;
          int cp = 0;
          for (int k = 1; k <= 4; k++) {
            char h = s[*i + k];
            int d;
            if (h >= '0' && h <= '9') d = h - '0';
            else if (h >= 'a' && h <= 'f') d = h - 'a' + 10;
            else if (h >= 'A' && h <= 'F') d = h - 'A' + 10;
            else return false;
            cp = cp * 16 + d;
          }
          *i += 4;
          // UTF-8 encode (surrogate pairs outside our producers' range
          // degrade to '?' rather than corrupting the byte stream).
          if (cp < 0x80) {
            out->push_back(static_cast<char>(cp));
          } else if (cp < 0x800) {
            out->push_back(static_cast<char>(0xC0 | (cp >> 6)));
            out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
          } else if (cp >= 0xD800 && cp <= 0xDFFF) {
            out->push_back('?');
          } else {
            out->push_back(static_cast<char>(0xE0 | (cp >> 12)));
            out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
            out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
          }
          break;
        }
        default: return false;
      }
      (*i)++;
    } else {
      out->push_back(c);
      (*i)++;
    }
  }
  return false;  // unterminated
}

bool parse_event_json(const std::string& s, EventJson* ev) {
  size_t i = 0;
  skip_ws(s, &i);
  if (i >= s.size() || s[i] != '{') return false;
  i++;
  skip_ws(s, &i);
  if (i < s.size() && s[i] == '}') return true;  // empty object
  while (i < s.size()) {
    std::string key;
    if (!parse_json_string(s, &i, &key)) return false;
    skip_ws(s, &i);
    if (i >= s.size() || s[i] != ':') return false;
    i++;
    skip_ws(s, &i);
    if (i >= s.size()) return false;
    if (s[i] == '"') {
      std::string val;
      if (!parse_json_string(s, &i, &val)) return false;
      if (key == "device") ev->device = val;
      else if (key == "message") ev->message = val;
    } else if (s.compare(i, 4, "null") == 0) {
      i += 4;
    } else if (s.compare(i, 4, "true") == 0) {
      i += 4;
    } else if (s.compare(i, 5, "false") == 0) {
      i += 5;
    } else {
      // number
      size_t start = i;
      if (s[i] == '-') i++;
      while (i < s.size() &&
             ((s[i] >= '0' && s[i] <= '9') || s[i] == '.' || s[i] == 'e' ||
              s[i] == 'E' || s[i] == '+' || s[i] == '-'))
        i++;
      if (i == start) return false;
      if (key == "code") ev->code = std::strtol(s.c_str() + start, nullptr, 10);
    }
    skip_ws(s, &i);
    if (i >= s.size()) return false;
    if (s[i] == ',') {
      i++;
      skip_ws(s, &i);
      continue;
    }
    if (s[i] == '}') return true;
    return false;
  }
  return false;
}

}  // namespace

struct tpu_ctx {
  std::string root;
  std::string sys_dir;
  std::string events_dir;
  int inotify_fd = -1;
  int watch_fd = -1;
};

extern "C" {

tpu_ctx* tpu_open(const char* root) {
  tpu_ctx* ctx = new (std::nothrow) tpu_ctx();
  if (!ctx) return nullptr;
  ctx->root = root ? root : "/";
  if (!ctx->root.empty() && ctx->root.back() != '/') ctx->root += '/';
  ctx->sys_dir = ctx->root + "sys/class/accel";
  ctx->events_dir = ctx->root + "var/run/tpu/events";
  ctx->inotify_fd = inotify_init1(IN_NONBLOCK | IN_CLOEXEC);
  if (ctx->inotify_fd >= 0) {
    // Watch may fail if the dir doesn't exist yet; we retry on each wait.
    ctx->watch_fd = inotify_add_watch(ctx->inotify_fd, ctx->events_dir.c_str(),
                                      IN_MOVED_TO | IN_CLOSE_WRITE);
  }
  return ctx;
}

void tpu_close(tpu_ctx* ctx) {
  if (!ctx) return;
  if (ctx->inotify_fd >= 0) close(ctx->inotify_fd);
  delete ctx;
}

static std::vector<std::string> list_chips(tpu_ctx* ctx) {
  std::vector<std::pair<int, std::string>> found;
  DIR* d = opendir(ctx->sys_dir.c_str());
  if (!d) return {};
  struct dirent* e;
  while ((e = readdir(d)) != nullptr) {
    int idx;
    std::string name(e->d_name);
    if (is_accel_name(name, &idx)) found.emplace_back(idx, name);
  }
  closedir(d);
  std::sort(found.begin(), found.end());
  std::vector<std::string> names;
  names.reserve(found.size());
  for (auto& p : found) names.push_back(p.second);
  return names;
}

static bool chip_attr(tpu_ctx* ctx, const std::string& name,
                      const char* attr, std::string* out) {
  return read_file(ctx->sys_dir + "/" + name + "/device/" + attr, out);
}

int tpu_chip_count(tpu_ctx* ctx) {
  if (!ctx) return -EINVAL;
  return static_cast<int>(list_chips(ctx).size());
}

static void fill_chip_info(tpu_ctx* ctx, const std::string& name,
                           tpu_chip_info_t* out);

int tpu_chip_info(tpu_ctx* ctx, int index, tpu_chip_info_t* out) {
  if (!ctx || !out) return -EINVAL;
  std::vector<std::string> chips = list_chips(ctx);
  if (index < 0 || index >= static_cast<int>(chips.size())) return -ERANGE;
  fill_chip_info(ctx, chips[index], out);
  return 0;
}

int tpu_chip_info_all(tpu_ctx* ctx, tpu_chip_info_t* out, int max_n) {
  if (!ctx || !out || max_n < 0) return -EINVAL;
  std::vector<std::string> chips = list_chips(ctx);
  int n = std::min<int>(max_n, static_cast<int>(chips.size()));
  for (int i = 0; i < n; i++) fill_chip_info(ctx, chips[i], &out[i]);
  return n;
}

static void fill_chip_info(tpu_ctx* ctx, const std::string& name,
                           tpu_chip_info_t* out) {
  memset(out, 0, sizeof(*out));
  snprintf(out->name, sizeof(out->name), "%s", name.c_str());
  out->index = std::atoi(name.c_str() + 5);
  std::string v;
  out->chip_id = chip_attr(ctx, name, "chip_id", &v) ? std::atoi(v.c_str()) : 0;
  if (chip_attr(ctx, name, "pci_addr", &v))
    snprintf(out->pci_addr, sizeof(out->pci_addr), "%s", v.c_str());
  parse_triple(chip_attr(ctx, name, "coords", &v) ? v : "0,0,0", ',',
               out->coords);
  parse_triple(chip_attr(ctx, name, "topology", &v) ? v : "1x1x1", 'x',
               out->topology);
}

int tpu_hbm_info(tpu_ctx* ctx, const char* name, int64_t* total_bytes,
                 int64_t* used_bytes) {
  if (!ctx || !name || !total_bytes || !used_bytes) return -EINVAL;
  std::string v;
  *total_bytes =
      chip_attr(ctx, name, "hbm_total_bytes", &v) ? std::atoll(v.c_str()) : 0;
  *used_bytes =
      chip_attr(ctx, name, "hbm_used_bytes", &v) ? std::atoll(v.c_str()) : 0;
  return 0;
}

int tpu_duty_cycle(tpu_ctx* ctx, const char* name) {
  if (!ctx || !name) return -EINVAL;
  std::string v;
  if (!chip_attr(ctx, name, "duty_cycle_pct", &v)) return 0;
  int pct = std::atoi(v.c_str());
  if (pct < 0) pct = 0;
  if (pct > 100) pct = 100;
  return pct;
}

int tpu_health(tpu_ctx* ctx, const char* name, char* buf, int buf_len) {
  if (!ctx || !name || !buf || buf_len <= 0) return -EINVAL;
  std::string v;
  if (!chip_attr(ctx, name, "health", &v)) v = "ok";
  snprintf(buf, buf_len, "%s", v.c_str());
  return 0;
}

// Pop the oldest event file (lexicographic = chronological: producers name
// files by monotonic nanosecond sequence).  Malformed files are unlinked
// and skipped so one bad writer can't wedge the stream.
static int try_pop_event(tpu_ctx* ctx, tpu_event_t* out) {
  DIR* d = opendir(ctx->events_dir.c_str());
  if (!d) return 0;
  std::vector<std::string> files;
  struct dirent* e;
  while ((e = readdir(d)) != nullptr) {
    std::string name(e->d_name);
    if (name.size() > 5 && name.rfind(".json") == name.size() - 5 &&
        name[0] != '.')
      files.push_back(name);
  }
  closedir(d);
  std::sort(files.begin(), files.end());
  for (const std::string& name : files) {
    std::string path = ctx->events_dir + "/" + name;
    std::string body;
    if (!read_file(path, &body)) {
      unlink(path.c_str());
      continue;  // racing consumer took it
    }
    EventJson ev;
    bool parsed = parse_event_json(body, &ev);
    // Unlink best-effort AFTER a successful read: on a read-only events dir
    // the event is still delivered (matching SysfsTpuLib) rather than lost.
    unlink(path.c_str());
    if (!parsed) continue;  // malformed: discarded
    memset(out, 0, sizeof(*out));
    out->code = static_cast<int32_t>(ev.code);
    snprintf(out->device, sizeof(out->device), "%s", ev.device.c_str());
    snprintf(out->message, sizeof(out->message), "%s", ev.message.c_str());
    return 1;
  }
  return 0;
}

int tpu_wait_for_event(tpu_ctx* ctx, int timeout_ms, tpu_event_t* out) {
  if (!ctx || !out) return -EINVAL;
  if (ctx->inotify_fd < 0) return -EBADF;
  if (ctx->watch_fd < 0) {
    // Events dir may have been created after open.
    ctx->watch_fd = inotify_add_watch(ctx->inotify_fd, ctx->events_dir.c_str(),
                                      IN_MOVED_TO | IN_CLOSE_WRITE);
  }
  // Drain anything already queued before blocking.
  int got = try_pop_event(ctx, out);
  if (got) return got;

  struct pollfd pfd = {ctx->inotify_fd, POLLIN, 0};
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  int64_t deadline_ms =
      ts.tv_sec * 1000LL + ts.tv_nsec / 1000000LL + timeout_ms;
  const int slice_ms = 200;  // re-check dir even without inotify (NFS etc.)
  for (;;) {
    clock_gettime(CLOCK_MONOTONIC, &ts);
    int64_t now_ms = ts.tv_sec * 1000LL + ts.tv_nsec / 1000000LL;
    int64_t remaining = deadline_ms - now_ms;
    if (remaining <= 0) return 0;
    int wait = ctx->watch_fd >= 0
                   ? static_cast<int>(std::min<int64_t>(remaining, 10000))
                   : static_cast<int>(std::min<int64_t>(remaining, slice_ms));
    int rc = poll(&pfd, 1, wait);
    if (rc > 0) {
      char buf[4096];
      while (read(ctx->inotify_fd, buf, sizeof(buf)) > 0) {
      }
    }
    // A wakeup can be for a writer's tmp file before its rename lands
    // (IN_CLOSE_WRITE on ".<seq>.tmp"); the deadline loop naturally
    // re-polls until the IN_MOVED_TO arrives.
    got = try_pop_event(ctx, out);
    if (got) return got;
  }
}

const char* tpushim_version(void) { return "tpushim 0.1.0"; }

}  // extern "C"
