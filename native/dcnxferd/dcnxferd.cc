// dcnxferd — per-node DCN transfer daemon (native C++).
//
// TPU-native analog of the reference's tcpgpudmarxd RX-datapath manager
// (SURVEY.md §2.2; ref: gpudirect-tcpx/nccl-test.yaml:29-52 runs it as a
// privileged sidecar owning flow-steering state and GPU-memory RX buffers,
// with a UDS control socket under /run/tcpx).  Here the daemon owns the
// node's cross-slice DCN transfer state: workers register flows, the daemon
// allocates pinned staging buffers from a bounded pool (mmap'd, mlock
// best-effort), accounts transferred bytes, and releases a client's flows
// when its connection drops — the same client-lifetime contract rxdm gives
// the NCCL plugin.
//
// Control protocol: newline-delimited JSON over a UNIX stream socket
// (<uds_path>/xferd.sock).  Requests are flat objects:
//   {"op":"version"}
//   {"op":"register_flow","flow":"g0","peer":"slice1-h0","bytes":4194304}
//   {"op":"record_transfer","flow":"g0","bytes":1048576}
//   {"op":"release_flow","flow":"g0"}
//   {"op":"stats"}
// Responses: {"ok":true,...} or {"ok":false,"error":"..."}.
//
// Build: make native  (g++ -std=c++17, no external deps).

#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <signal.h>
#include <stdarg.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <unistd.h>

#include <map>
#include <string>
#include <vector>

namespace {

int g_verbose = 0;
volatile sig_atomic_t g_stop = 0;

void logf(int level, const char* fmt, ...) {
  if (level > g_verbose) return;
  va_list ap;
  va_start(ap, fmt);
  fprintf(stderr, "dcnxferd: ");
  vfprintf(stderr, fmt, ap);
  fprintf(stderr, "\n");
  va_end(ap);
}

void on_signal(int) { g_stop = 1; }

// ---- minimal flat-JSON request parsing -------------------------------------
// Requests are single-level objects with string or integer values; anything
// else is a protocol error.  (Responses are emitted with snprintf.)

bool ParseFlatJson(const std::string& line,
                   std::map<std::string, std::string>* out) {
  size_t i = 0;
  auto skip_ws = [&] {
    while (i < line.size() && isspace((unsigned char)line[i])) i++;
  };
  auto parse_string = [&](std::string* s) -> bool {
    if (line[i] != '"') return false;
    i++;
    s->clear();
    while (i < line.size() && line[i] != '"') {
      if (line[i] == '\\' && i + 1 < line.size()) i++;  // unescape next
      s->push_back(line[i++]);
    }
    if (i >= line.size()) return false;
    i++;  // closing quote
    return true;
  };
  skip_ws();
  if (i >= line.size() || line[i] != '{') return false;
  i++;
  skip_ws();
  if (i < line.size() && line[i] == '}') return true;  // empty object
  while (i < line.size()) {
    skip_ws();
    std::string key, value;
    if (!parse_string(&key)) return false;
    skip_ws();
    if (i >= line.size() || line[i] != ':') return false;
    i++;
    skip_ws();
    if (i >= line.size()) return false;
    if (line[i] == '"') {
      if (!parse_string(&value)) return false;
    } else {  // bare token: number / true / false / null
      size_t start = i;
      while (i < line.size() && line[i] != ',' && line[i] != '}' &&
             !isspace((unsigned char)line[i]))
        i++;
      value = line.substr(start, i - start);
    }
    (*out)[key] = value;
    skip_ws();
    if (i < line.size() && line[i] == ',') {
      i++;
      continue;
    }
    if (i < line.size() && line[i] == '}') return true;
    return false;
  }
  return false;
}

// Flow and peer names are operator/workload-supplied; constraining them
// keeps every response JSON well-formed without an escaper and bounds the
// fixed-size response buffers.
constexpr size_t kMaxNameLen = 64;
bool IsValidName(const std::string& s) {
  if (s.empty() || s.size() > kMaxNameLen) return false;
  for (char ch : s) {
    if (!isalnum((unsigned char)ch) && ch != '-' && ch != '_' && ch != '.' &&
        ch != ':' && ch != '/')
      return false;
  }
  return true;
}

std::string JsonEscape(const std::string& s) {
  std::string out;
  for (char ch : s) {
    if (ch == '"' || ch == '\\') {
      out.push_back('\\');
      out.push_back(ch);
    } else if ((unsigned char)ch < 0x20) {
      char buf[8];
      snprintf(buf, sizeof(buf), "\\u%04x", ch);
      out += buf;
    } else {
      out.push_back(ch);
    }
  }
  return out;
}

// ---- flow / buffer-pool state ----------------------------------------------

struct Flow {
  std::string name;
  std::string peer;
  int owner_fd = -1;
  size_t buffer_bytes = 0;
  void* buffer = nullptr;
  unsigned long long transferred = 0;
};

class Daemon {
 public:
  Daemon(size_t pool_bytes, size_t max_flows)
      : pool_bytes_(pool_bytes), max_flows_(max_flows) {}

  std::string Handle(int fd, const std::map<std::string, std::string>& req) {
    auto it = req.find("op");
    if (it == req.end()) return Err("missing op");
    const std::string& op = it->second;
    if (op == "version") return Ok("\"version\":\"dcnxferd/1.0\"");
    if (op == "ping") return Ok("");
    if (op == "register_flow") return RegisterFlow(fd, req);
    if (op == "record_transfer") return RecordTransfer(fd, req);
    if (op == "release_flow") return ReleaseFlow(fd, req);
    if (op == "stats") return Stats();
    return Err("unknown op '" + op + "'");
  }

  void ReleaseClient(int fd) {
    for (auto it = flows_.begin(); it != flows_.end();) {
      if (it->second.owner_fd == fd) {
        logf(1, "releasing flow '%s' (client fd %d gone)",
             it->first.c_str(), fd);
        FreeFlow(&it->second);
        it = flows_.erase(it);
      } else {
        ++it;
      }
    }
  }

  ~Daemon() {
    for (auto& kv : flows_) FreeFlow(&kv.second);
  }

 private:
  static std::string Ok(const std::string& extra) {
    return extra.empty() ? "{\"ok\":true}"
                         : "{\"ok\":true," + extra + "}";
  }
  static std::string Err(const std::string& msg) {
    return "{\"ok\":false,\"error\":\"" + msg + "\"}";
  }

  std::string RegisterFlow(int fd,
                           const std::map<std::string, std::string>& req) {
    auto fit = req.find("flow");
    if (fit == req.end() || fit->second.empty())
      return Err("register_flow needs 'flow'");
    const std::string& name = fit->second;
    if (!IsValidName(name))
      return Err("invalid flow name (max 64 chars of [A-Za-z0-9._:/-])");
    if (flows_.count(name))
      return Err("flow '" + JsonEscape(name) + "' already exists");
    if (flows_.size() >= max_flows_) return Err("max flows reached");

    size_t bytes = 4 << 20;  // default 4 MiB staging buffer
    auto bit = req.find("bytes");
    if (bit != req.end()) {
      if (bit->second.empty() || !isdigit((unsigned char)bit->second[0]))
        return Err("invalid 'bytes'");
      char* end = nullptr;
      unsigned long long v = strtoull(bit->second.c_str(), &end, 10);
      if (end == bit->second.c_str() || *end != '\0' || v == 0 ||
          v > (1ull << 40))
        return Err("invalid 'bytes'");
      bytes = (size_t)v;
    }
    // Page-align; enforce the pool bound.
    size_t page = (size_t)sysconf(_SC_PAGESIZE);
    bytes = (bytes + page - 1) / page * page;
    if (pool_used_ + bytes > pool_bytes_)
      return Err("buffer pool exhausted");

    void* buf = mmap(nullptr, bytes, PROT_READ | PROT_WRITE,
                     MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (buf == MAP_FAILED) return Err("mmap failed");
    // Pin best-effort: staging buffers should not page out mid-transfer.
    // Unprivileged runs (tests) may exceed RLIMIT_MEMLOCK; that is fine.
    if (mlock(buf, bytes) != 0)
      logf(2, "mlock(%zu) failed: %s (continuing unpinned)", bytes,
           strerror(errno));

    Flow f;
    f.name = name;
    auto pit = req.find("peer");
    if (pit != req.end()) {
      if (!pit->second.empty() && !IsValidName(pit->second))
        return Err("invalid peer name (max 64 chars of [A-Za-z0-9._:/-])");
      f.peer = pit->second;
    }
    f.owner_fd = fd;
    f.buffer_bytes = bytes;
    f.buffer = buf;
    pool_used_ += bytes;
    flows_[name] = f;
    logf(1, "registered flow '%s' peer='%s' buffer=%zu", name.c_str(),
         f.peer.c_str(), bytes);

    char extra[160];
    snprintf(extra, sizeof(extra),
             "\"flow\":\"%s\",\"buffer_bytes\":%zu,\"pool_used\":%zu",
             name.c_str(), bytes, pool_used_);
    return Ok(extra);
  }

  std::string RecordTransfer(int fd,
                             const std::map<std::string, std::string>& req) {
    auto fit = req.find("flow");
    if (fit == req.end()) return Err("record_transfer needs 'flow'");
    auto it = flows_.find(fit->second);
    if (it == flows_.end())
      return Err("unknown flow '" + JsonEscape(fit->second) + "'");
    if (it->second.owner_fd != fd) return Err("flow owned by another client");
    auto bit = req.find("bytes");
    if (bit == req.end()) return Err("record_transfer needs 'bytes'");
    // Reject signs and garbage; strtoull would silently wrap "-1" to 2^64-1.
    if (bit->second.empty() || !isdigit((unsigned char)bit->second[0]))
      return Err("invalid 'bytes'");
    char* end = nullptr;
    unsigned long long v = strtoull(bit->second.c_str(), &end, 10);
    if (end == bit->second.c_str() || *end != '\0' || v > (1ull << 62))
      return Err("invalid 'bytes'");
    it->second.transferred += v;
    total_transferred_ += v;
    char extra[96];
    snprintf(extra, sizeof(extra), "\"flow_bytes\":%llu",
             it->second.transferred);
    return Ok(extra);
  }

  std::string ReleaseFlow(int fd,
                          const std::map<std::string, std::string>& req) {
    auto fit = req.find("flow");
    if (fit == req.end()) return Err("release_flow needs 'flow'");
    auto it = flows_.find(fit->second);
    if (it == flows_.end())
      return Err("unknown flow '" + JsonEscape(fit->second) + "'");
    if (it->second.owner_fd != fd) return Err("flow owned by another client");
    FreeFlow(&it->second);
    flows_.erase(it);
    return Ok("");
  }

  std::string Stats() {
    std::string detail = "[";
    bool first = true;
    for (const auto& kv : flows_) {
      char item[320];  // names are <=64 chars (IsValidName), so this fits
      snprintf(item, sizeof(item),
               "%s{\"flow\":\"%s\",\"peer\":\"%s\",\"buffer_bytes\":%zu,"
               "\"transferred\":%llu}",
               first ? "" : ",", kv.second.name.c_str(),
               kv.second.peer.c_str(), kv.second.buffer_bytes,
               kv.second.transferred);
      detail += item;
      first = false;
    }
    detail += "]";
    char extra[256];
    snprintf(extra, sizeof(extra),
             "\"pool_bytes\":%zu,\"pool_used\":%zu,\"active_flows\":%zu,"
             "\"total_transferred\":%llu,\"flows\":",
             pool_bytes_, pool_used_, flows_.size(), total_transferred_);
    return Ok(extra + detail);
  }

  void FreeFlow(Flow* f) {
    if (f->buffer) {
      munlock(f->buffer, f->buffer_bytes);
      munmap(f->buffer, f->buffer_bytes);
      f->buffer = nullptr;
    }
    pool_used_ -= f->buffer_bytes;
  }

  size_t pool_bytes_;
  size_t max_flows_;
  size_t pool_used_ = 0;
  unsigned long long total_transferred_ = 0;
  std::map<std::string, Flow> flows_;
};

// ---- event loop ------------------------------------------------------------

struct Client {
  int fd;
  std::string inbuf;
  std::string outbuf;  // pending response bytes (client slow to read)
};

// A client that won't drain 1 MiB of pending responses is broken or
// malicious; drop it rather than buffer without bound.
constexpr size_t kMaxOutbuf = 1 << 20;
constexpr size_t kMaxInbuf = 1 << 16;

// Returns false when the connection is dead.  Writes what it can now and
// leaves the rest in outbuf for POLLOUT — one stuck client must never
// block the event loop (fds are non-blocking).
bool FlushClient(Client* c) {
  while (!c->outbuf.empty()) {
    ssize_t put = write(c->fd, c->outbuf.data(), c->outbuf.size());
    if (put > 0) {
      c->outbuf.erase(0, (size_t)put);
    } else if (put < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      return true;  // try again on POLLOUT
    } else {
      return false;
    }
  }
  return true;
}

int MakeListener(const std::string& sock_path) {
  int fd = socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (fd < 0) {
    perror("socket");
    return -1;
  }
  unlink(sock_path.c_str());
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  if (sock_path.size() >= sizeof(addr.sun_path)) {
    fprintf(stderr, "dcnxferd: socket path too long: %s\n", sock_path.c_str());
    close(fd);
    return -1;
  }
  strncpy(addr.sun_path, sock_path.c_str(), sizeof(addr.sun_path) - 1);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    perror("bind");
    close(fd);
    return -1;
  }
  chmod(sock_path.c_str(), 0666);  // workload pods connect unprivileged
  if (listen(fd, 64) != 0) {
    perror("listen");
    close(fd);
    return -1;
  }
  return fd;
}

int Serve(const std::string& sock_path, Daemon* daemon) {
  int listener = MakeListener(sock_path);
  if (listener < 0) return 1;
  logf(0, "listening on %s", sock_path.c_str());

  std::vector<Client> clients;
  while (!g_stop) {
    std::vector<pollfd> fds;
    fds.push_back({listener, POLLIN, 0});
    for (const auto& c : clients) {
      short events = POLLIN;
      if (!c.outbuf.empty()) events |= POLLOUT;
      fds.push_back({c.fd, events, 0});
    }
    int n = poll(fds.data(), fds.size(), 500);
    if (n < 0) {
      if (errno == EINTR) continue;
      perror("poll");
      break;
    }
    // Only the clients present when poll() ran have valid revents; a
    // freshly-accepted client is picked up on the next loop iteration.
    size_t polled = fds.size() - 1;
    for (size_t ci = 0; ci < polled;) {
      Client& c = clients[ci];
      pollfd& p = fds[1 + ci];
      bool drop = false;
      if (p.revents & POLLOUT) {
        if (!FlushClient(&c)) drop = true;
      }
      if (!drop && (p.revents & (POLLIN | POLLHUP | POLLERR))) {
        char buf[4096];
        ssize_t got = read(c.fd, buf, sizeof(buf));
        if (got == 0 || (got < 0 && errno != EAGAIN && errno != EWOULDBLOCK)) {
          drop = true;
        } else if (got > 0) {
          c.inbuf.append(buf, (size_t)got);
          size_t nl;
          while ((nl = c.inbuf.find('\n')) != std::string::npos) {
            std::string line = c.inbuf.substr(0, nl);
            c.inbuf.erase(0, nl + 1);
            if (line.empty()) continue;
            std::map<std::string, std::string> req;
            std::string resp = ParseFlatJson(line, &req)
                                   ? daemon->Handle(c.fd, req)
                                   : "{\"ok\":false,\"error\":\"bad json\"}";
            c.outbuf += resp + "\n";
          }
          // Input lines are bounded; a client streaming garbage without
          // newlines (or not draining responses) must not grow buffers
          // forever.
          if (c.inbuf.size() > kMaxInbuf || c.outbuf.size() > kMaxOutbuf)
            drop = true;
          if (!drop && !FlushClient(&c)) drop = true;
        }
      }
      if (drop) {
        daemon->ReleaseClient(c.fd);
        close(c.fd);
        logf(1, "client fd %d disconnected", c.fd);
        clients.erase(clients.begin() + ci);
        fds.erase(fds.begin() + 1 + ci);
        polled--;
      } else {
        ++ci;
      }
    }
    if (fds[0].revents & POLLIN) {
      int cfd = accept4(listener, nullptr, nullptr,
                        SOCK_CLOEXEC | SOCK_NONBLOCK);
      if (cfd >= 0) {
        clients.push_back({cfd, "", ""});
        logf(1, "client fd %d connected", cfd);
      }
    }
  }
  for (auto& c : clients) {
    daemon->ReleaseClient(c.fd);
    close(c.fd);
  }
  close(listener);
  unlink(sock_path.c_str());
  logf(0, "shut down");
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  std::string uds_path = "/run/tpu-dcn";
  size_t pool_bytes = 256ull << 20;
  size_t max_flows = 256;

  for (int i = 1; i < argc; i++) {
    std::string arg = argv[i];
    auto next = [&]() -> const char* {
      return (i + 1 < argc) ? argv[++i] : nullptr;
    };
    if (arg == "--uds_path" || arg == "--uds-path") {
      const char* v = next();
      if (v) uds_path = v;
    } else if (arg == "--pool_bytes" || arg == "--pool-bytes") {
      const char* v = next();
      if (v) pool_bytes = strtoull(v, nullptr, 10);
    } else if (arg == "--max_flows" || arg == "--max-flows") {
      const char* v = next();
      if (v) max_flows = strtoull(v, nullptr, 10);
    } else if (arg == "--verbose" || arg == "-v") {
      const char* v = next();
      if (v) g_verbose = atoi(v);
    } else if (arg == "--help" || arg == "-h") {
      printf("usage: dcnxferd [--uds_path DIR] [--pool_bytes N] "
             "[--max_flows N] [--verbose LEVEL]\n");
      return 0;
    } else {
      fprintf(stderr, "dcnxferd: unknown flag %s\n", arg.c_str());
      return 2;
    }
  }

  mkdir(uds_path.c_str(), 0755);
  signal(SIGINT, on_signal);
  signal(SIGTERM, on_signal);
  signal(SIGPIPE, SIG_IGN);

  Daemon daemon(pool_bytes, max_flows);
  return Serve(uds_path + "/xferd.sock", &daemon);
}
